"""The shipped Grafana dashboard / Prometheus config must stay in sync with
the metric names the framework actually registers (reference ships
docker/metrics/dashboards/apps.json + prometheus.yml; a dashboard whose
queries match nothing is worse than none)."""

import json
from pathlib import Path

import yaml

METRICS_DIR = Path(__file__).parent.parent / "docker" / "metrics"

# Dashboard-vs-source metric-name consistency (every __name__ matcher in
# serving.json must resolve to a metric something registers) is enforced
# STATICALLY by the `registry-drift` analysis pass (LSA405) — see
# langstream_tpu/analysis/registry_drift.py and docs/ANALYSIS.md — which
# runs in CI's `analysis` job and in test_analysis.py's whole-repo-clean
# test. The runtime scans that used to live here (source grep + a live
# MetricsReporter exposition) are retired; this file keeps the
# JSON/YAML-validity and panel-presence checks the static pass does not
# cover.


def test_prometheus_config_parses_and_scrapes_runtime():
    doc = yaml.safe_load((METRICS_DIR / "prometheus.yml").read_text())
    jobs = {j["job_name"]: j for j in doc["scrape_configs"]}
    assert "langstream-runtime" in jobs
    targets = jobs["langstream-runtime"]["static_configs"][0]["targets"]
    # the runtime http server's default port (runtime/http_server.py)
    assert any(t.endswith(":8080") for t in targets)


def test_observability_panels_present():
    """The round-11 observability panels must survive dashboard edits: the
    TTFT histogram HEATMAP (reads the engine histogram's _bucket series
    with a heatmap-format target) and the load-score panel (the replica
    balancer's routing signal, ROADMAP item 3)."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    by_title = {p.get("title", ""): p for p in doc["panels"]}
    heat = next(
        (p for t, p in by_title.items() if "heatmap" in t.lower()), None
    )
    assert heat is not None, "TTFT histogram heatmap panel missing"
    assert heat["type"] == "heatmap"
    heat_exprs = " ".join(t["expr"] for t in heat["targets"])
    assert "engine_ttft_s_bucket" in heat_exprs
    assert "by (le)" in heat_exprs, "heatmap must aggregate by bucket label"
    load = next(
        (p for t, p in by_title.items() if "load score" in t.lower()), None
    )
    assert load is not None, "engine load-score panel missing"
    assert any(
        "engine_load_score" in t["expr"] for t in load["targets"]
    )


def test_fleet_panels_present():
    """The round-12 fleet panels must survive dashboard edits: routing
    decisions (affinity vs balanced — the cache-aware dispatch signal,
    serving/fleet.py) and the replica-count panel paired with the
    autoscale-hint story (docs/SERVING.md §13)."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    routing = next(
        (e for t, e in exprs_by_title.items() if "fleet routing" in t.lower()),
        None,
    )
    assert routing is not None, "fleet routing-decisions panel missing"
    assert "fleet_routed_affinity_total" in routing
    assert "fleet_routed_balanced_total" in routing
    replicas = next(
        (e for t, e in exprs_by_title.items() if "fleet replicas" in t.lower()),
        None,
    )
    assert replicas is not None, "fleet replica-count panel missing"
    assert "fleet_replica_count" in replicas


def test_fleet_wire_panels_present():
    """The ISSUE-12 fleet-wire panels must survive dashboard edits: the
    wire-health panel (mid-stream warm failovers + circuit-breaker opens +
    beacon probe failures — serving/fleet.py, docs/SERVING.md §17) and the
    remote-hop latency panel reading the fleet_hop_s histogram buckets."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    wire = next(
        (e for t, e in exprs_by_title.items() if "fleet wire" in t.lower()),
        None,
    )
    assert wire is not None, "fleet wire-health panel missing"
    assert "fleet_stream_failovers_total" in wire
    assert "fleet_circuit_open_total" in wire
    assert "fleet_beacon_failures_total" in wire
    hop = next(
        (e for t, e in exprs_by_title.items() if "fleet hop" in t.lower()),
        None,
    )
    assert hop is not None, "fleet hop-latency panel missing"
    assert "fleet_hop_s_bucket" in hop
    assert "histogram_quantile" in hop


def test_migration_panels_present():
    """The ISSUE-13 disaggregated-serving panels must survive dashboard
    edits: the migration-traffic panel (completed migrations, pages/bytes
    moved, decode-in-place fallbacks — serving/migrate.py + fleet.py,
    docs/SERVING.md §18) and the migration-latency panel reading the
    fleet_migrate_s histogram buckets."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    traffic = next(
        (
            e for t, e in exprs_by_title.items()
            if "migration traffic" in t.lower()
        ),
        None,
    )
    assert traffic is not None, "KV migration-traffic panel missing"
    assert "fleet_migrations_total" in traffic
    assert "fleet_pages_migrated_total" in traffic
    assert "fleet_migrate_bytes_total" in traffic
    assert "fleet_migrate_fallbacks_total" in traffic
    latency = next(
        (
            e for t, e in exprs_by_title.items()
            if "migration latency" in t.lower()
        ),
        None,
    )
    assert latency is not None, "KV migration-latency panel missing"
    assert "fleet_migrate_s_bucket" in latency
    assert "histogram_quantile" in latency


def test_agentic_panels_present():
    """The ISSUE-10 agentic-tier panels must survive dashboard edits:
    adapter residency/swaps (the multi-LoRA pool-thrash signal,
    serving/adapters.py) and the constrained-decoding volume + mask
    overhead pair (serving/constrain.py; docs/SERVING.md §15)."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    adapters = next(
        (e for t, e in exprs_by_title.items() if "adapter" in t.lower()),
        None,
    )
    assert adapters is not None, "adapter multiplexing panel missing"
    assert "engine_adapters_resident" in adapters
    assert "engine_adapter_swaps_total" in adapters
    constrained = next(
        (e for t, e in exprs_by_title.items() if "constrained" in t.lower()),
        None,
    )
    assert constrained is not None, "constrained-decoding panel missing"
    assert "engine_constrained_requests_total" in constrained
    assert "engine_constrain_overhead_ms" in constrained


def test_tiered_kv_panels_present():
    """The ISSUE-11 tiered-KV panels must survive dashboard edits: the
    host-tier occupancy panel (arena pages + spill/restore byte traffic,
    serving/pagepool.HostPageTier) and the restore-vs-recompute split —
    THE health gauge of the hibernation wake path (docs/SERVING.md §16)."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    occupancy = next(
        (e for t, e in exprs_by_title.items() if "host kv tier" in t.lower()),
        None,
    )
    assert occupancy is not None, "host-tier occupancy panel missing"
    assert "engine_host_pages_in_use" in occupancy
    assert "engine_host_pages_total" in occupancy
    assert "engine_spill_bytes_total" in occupancy
    assert "engine_restore_bytes_total" in occupancy
    wake = next(
        (
            e for t, e in exprs_by_title.items()
            if "restore vs recompute" in t.lower()
        ),
        None,
    )
    assert wake is not None, "restore-vs-recompute panel missing"
    assert "engine_restored_hits_total" in wake
    assert "engine_recompute_fallbacks_total" in wake


def test_tenancy_panels_present():
    """The ISSUE-14 multi-tenant overload-control panels must survive
    dashboard edits: the tenant-overload panel (cross-tenant shed volume +
    the worst per-tenant queue-wait EMA — the noisy-neighbor victim
    signal, serving/tenancy.py) and the brownout-ladder panel
    (docs/SERVING.md §19)."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    overload = next(
        (
            e for t, e in exprs_by_title.items()
            if "tenant overload" in t.lower()
        ),
        None,
    )
    assert overload is not None, "tenant-overload panel missing"
    assert "tenant_shed_total" in overload
    assert "tenant_queue_wait" in overload
    brownout = next(
        (e for t, e in exprs_by_title.items() if "brownout" in t.lower()),
        None,
    )
    assert brownout is not None, "brownout-ladder panel missing"
    assert "brownout_level" in brownout
    assert "brownout_transitions_total" in brownout


def test_spmd_resilience_panels_present():
    """The ISSUE-15 SPMD slice-resilience panels must survive dashboard
    edits: the recovery-epochs panel (coordinated OP_RECOVER recoveries,
    divergence resyncs and the epoch gauge — parallel/spmd_serving.py)
    and the watchdog-detections panel (docs/SERVING.md §20)."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    recovery = next(
        (
            e for t, e in exprs_by_title.items()
            if "spmd slice recovery" in t.lower()
        ),
        None,
    )
    assert recovery is not None, "SPMD recovery-epochs panel missing"
    assert "engine_spmd_recoveries_total" in recovery
    assert "engine_spmd_resyncs_total" in recovery
    assert "engine_spmd_recovery_epoch" in recovery
    watchdog = next(
        (
            e for t, e in exprs_by_title.items()
            if "spmd watchdog" in t.lower()
        ),
        None,
    )
    assert watchdog is not None, "SPMD watchdog-detections panel missing"
    assert "engine_spmd_watchdog_trips_total" in watchdog


def test_fleet_wire_v2_panels_present():
    """The ISSUE-16 binary-wire + P2P panels must survive dashboard edits:
    the per-protocol wire-bytes panel (v1 NDJSON vs v2 binary — the rollout
    health signal for the lstpu-kvmig-v2/frames-v2 codecs, serving/wire.py,
    docs/SERVING.md §21) and the peer-to-peer page-fetch panel (warm admits
    vs local-cold fallbacks plus bytes pulled in from peers)."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    wire = next(
        (
            e for t, e in exprs_by_title.items()
            if "wire bytes by protocol" in t.lower()
        ),
        None,
    )
    assert wire is not None, "fleet wire-bytes-by-protocol panel missing"
    assert "fleet_wire_bytes_total" in wire
    assert 'proto="v1"' in wire
    assert 'proto="v2"' in wire
    p2p = next(
        (e for t, e in exprs_by_title.items() if "p2p page fetch" in t.lower()),
        None,
    )
    assert p2p is not None, "fleet P2P page-fetch panel missing"
    assert "fleet_p2p_fetch_total" in p2p
    assert "fleet_p2p_fetch_fallback_total" in p2p
    assert "fleet_p2p_bytes_in_total" in p2p


def test_cold_start_panels_present():
    """The ISSUE-17 cold-start panel must survive dashboard edits: the
    streamed weight-load panel (models/streamload.py, docs/SERVING.md §22)
    carries the per-build load wall gauge, the checkpoint bytes-read gauge
    and the cross-build engine_weight_load_s histogram quantile — the
    rollout/autoscale health trio for engine build time."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    cold = next(
        (e for t, e in exprs_by_title.items() if "cold start" in t.lower()),
        None,
    )
    assert cold is not None, "cold-start weight-load panel missing"
    assert "weight_load_s" in cold
    assert "weight_load_bytes_total" in cold
    assert "engine_weight_load_s" in cold


def test_grammar_pool_panel_present():
    """The ISSUE-20 packed-grammar-pool panel must survive dashboard
    edits: HBM held by the packed bitmask/exception planes plus the
    resident-row count (serving/constrain.py, docs/SERVING.md §15) — the
    pool-thrash signal that pairs with the constrained-decoding panel."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    pool = next(
        (e for t, e in exprs_by_title.items() if "grammar pool" in t.lower()),
        None,
    )
    assert pool is not None, "grammar-pool panel missing"
    assert "engine_grammar_pool_bytes" in pool
    assert "engine_grammar_rows_resident" in pool


def test_grafana_provisioning_parses():
    ds = yaml.safe_load(
        (METRICS_DIR / "provisioning" / "datasources" / "prometheus.yaml").read_text()
    )
    assert ds["datasources"][0]["type"] == "prometheus"
    dash = yaml.safe_load(
        (METRICS_DIR / "provisioning" / "dashboards" / "dashboards.yaml").read_text()
    )
    assert dash["providers"][0]["type"] == "file"


def test_durable_tier_panels_present():
    """The ISSUE-18 durable-tier panels must survive dashboard edits: the
    checkpoint/restore latency quantile pair (the hibernate-vs-resurrect
    wall the §23 drill tracks) and the occupancy/failures panel (entries,
    bytes on disk, resurrections, dead entries — the scale-to-zero health
    trio plus the prefetch fetch counter)."""
    doc = json.loads((METRICS_DIR / "dashboards" / "serving.json").read_text())
    exprs_by_title = {
        p.get("title", ""): " ".join(t["expr"] for t in p.get("targets", []))
        for p in doc["panels"]
    }
    lat = next(
        (
            e for t, e in exprs_by_title.items()
            if "durable" in t.lower() and "latency" in t.lower()
        ),
        None,
    )
    assert lat is not None, "durable checkpoint/restore latency panel missing"
    assert "engine_durable_checkpoint_s" in lat
    assert "engine_durable_restore_s" in lat
    occ = next(
        (
            e for t, e in exprs_by_title.items()
            if "durable" in t.lower() and "occupancy" in t.lower()
        ),
        None,
    )
    assert occ is not None, "durable occupancy/failures panel missing"
    assert "durable_entries" in occ
    assert "durable_bytes_on_disk" in occ
    assert "durable_restores_total" in occ
    assert "durable_restore_failures_total" in occ
    assert "durable_dead_entries_total" in occ
    assert "fleet_prefetch_fetch_total" in occ
