"""Golden-transcript replay harness (docs/COMPAT_RUNBOOK.md's vendoring
format): every ``tests/golden/*.hex`` conversation file is loaded and each
frame is replayed through the matching protocol codec.

- ``>`` lines (client→server) must decode cleanly AND re-encode to the
  EXACT same bytes (detects any wire-format drift in the codec since the
  transcript was captured).
- ``<`` lines (server→client) must decode cleanly.
- ``#`` lines are comments.

The shipped sample transcripts are fake-broker captures (see
tests/golden/generate_sample.py — honest about their provenance); drop in
real-broker tcpdump captures with the same names/format to upgrade them to
true external validation without touching this harness."""

from pathlib import Path

import pytest

GOLDEN = Path(__file__).parent / "golden"


def _load(path: Path):
    frames = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        direction, _, hexdata = line.partition(" ")
        assert direction in (">", "<"), f"{path.name}: bad direction {direction!r}"
        frames.append((direction, bytes.fromhex(hexdata)))
    return frames


def _replay_pulsar(direction: str, data: bytes) -> None:
    from langstream_tpu.messaging import pulsar_protocol as wire

    # frame: [totalSize][body]; body: [cmdSize][cmd][optional payload part]
    total = int.from_bytes(data[:4], "big")
    assert total == len(data) - 4, "frame length header mismatch"
    name, fields, metadata, payload = wire.split_frame(data[4:])
    assert not name.startswith("unknown_"), (
        f"unsupported command type {name} — extend pulsar_protocol._COMMANDS"
    )
    if direction == "<":
        # server frames only need to DECODE cleanly: a real broker may
        # order protobuf fields differently than our encoder does
        return
    # client frames re-encode EXACTLY (wire-drift pin: these are the bytes
    # our own codec produced at capture time)
    cmd_size = int.from_bytes(data[4:8], "big")
    cmd_bytes = data[8 : 8 + cmd_size]
    assert wire.encode_command(name, fields) == cmd_bytes, (
        f"{name}: re-encoded command differs from transcript"
    )
    if metadata is not None:
        # payload frames: metadata must round-trip to its exact slice
        # (magic[2] + crc[4] + mdSize[4] + md follow the command section)
        md_off = 8 + cmd_size + 2 + 4
        md_size = int.from_bytes(data[md_off : md_off + 4], "big")
        md_bytes = data[md_off + 4 : md_off + 4 + md_size]
        re_md = wire.encode_message(wire.MESSAGE_METADATA, metadata)
        assert re_md == md_bytes, f"{name}: metadata re-encode drifted"


def _replay_kafka(direction: str, data: bytes) -> None:
    from langstream_tpu.messaging import kafka_protocol as wire

    # frame: [int32 size][body]
    size = int.from_bytes(data[:4], "big")
    assert size == len(data) - 4, "frame length header mismatch"
    r = wire.Reader(data[4:])
    if direction == "<":
        # responses carry only [correlation_id][api-specific body]; the
        # api-specific parsers live inline in the client, so the replay
        # asserts framing + correlation header only
        cid = r.int32()
        assert cid > 0, f"bad correlation id {cid}"
        return
    api_key, api_version, cid, client_id = wire.decode_request_header(r)
    assert api_key in wire.API_VERSIONS, (
        f"unknown api key {api_key} — extend kafka_protocol.API_VERSIONS"
    )
    assert api_version == wire.API_VERSIONS[api_key], (
        f"api {api_key}: transcript pins version {api_version}, "
        f"codec now speaks {wire.API_VERSIONS[api_key]}"
    )
    payload = r.data[r.pos :]
    # wire-drift pin: re-encoding the parsed request must reproduce the bytes
    assert wire.encode_request(api_key, cid, client_id or "", payload) == data, (
        f"api {api_key}: re-encoded request differs from transcript"
    )
    if api_key == wire.PRODUCE:
        # decode the record batch payload deeply (the densest codec)
        pr = wire.Reader(payload)
        pr.string()  # transactional_id
        pr.int16()  # acks
        pr.int32()  # timeout
        for _ in range(pr.int32()):
            pr.string()  # topic
            for _ in range(pr.int32()):
                pr.int32()  # partition
                batch = pr.bytes_()
                records = wire.decode_record_batches(batch)
                assert records, "produce batch decodes to no records"
                assert wire.encode_record_batch(
                    records, base_offset=records[0].offset
                ) == batch, "record batch re-encode drifted"


def _replay_cql(direction: str, data: bytes) -> None:
    from langstream_tpu.agents.vector import cql_protocol as wire

    version, stream, opcode, length = wire.parse_header(data[: wire.HEADER_SIZE])
    assert length == len(data) - wire.HEADER_SIZE, "frame length header mismatch"
    body = data[wire.HEADER_SIZE :]
    if direction == ">":
        assert version == wire.VERSION_REQUEST
        # wire-drift pin: the framer must reproduce the exact bytes
        assert wire.frame(opcode, body, stream=stream) == data
        if opcode == wire.OP_PREPARE:
            assert wire.parse_prepare_body(body)
        elif opcode == wire.OP_EXECUTE:
            prepared_id, values, _ = wire.parse_execute_body(body)
            assert prepared_id
        elif opcode == wire.OP_QUERY:
            query, _, _ = wire.parse_query_body(body)
            assert query
        return
    assert version == wire.VERSION_RESPONSE
    if opcode == wire.OP_RESULT:
        result = wire.parse_result_body(body)
        assert result["kind"] in ("rows", "void", "prepared", "schema_change", "set_keyspace")
    elif opcode == wire.OP_ERROR:
        wire.parse_error_body(body)
    else:
        assert opcode in (
            wire.OP_READY,
            wire.OP_AUTHENTICATE,
            wire.OP_AUTH_SUCCESS,
            wire.OP_SUPPORTED,
        ), f"unexpected response opcode 0x{opcode:02x}"


def _replay_pravega(direction: str, data: bytes) -> None:
    from langstream_tpu.messaging import pravega_protocol as wire

    # frame: [type:i32][length:i32][payload]
    type_, length = wire.parse_frame_header(data[:8])
    assert length == len(data) - 8, "frame length header mismatch"
    name, fields = wire.decode(type_, data[8:])
    assert not name.startswith("unknown"), (
        f"unsupported WireCommand type {type_} — extend pravega_protocol"
    )
    if direction == "<":
        return  # server frames only need to decode cleanly
    # wire-drift pin: re-encoding the decoded command reproduces the bytes
    assert wire.encode(name, fields) == data, (
        f"{name}: re-encoded WireCommand differs from transcript"
    )


_REPLAYERS = {
    "pulsar": _replay_pulsar,
    "kafka": _replay_kafka,
    "cql": _replay_cql,
    "pravega": _replay_pravega,
}


def _files():
    return sorted(GOLDEN.glob("*.hex"))


@pytest.mark.parametrize("path", _files(), ids=lambda p: p.name)
def test_golden_transcript_replays(path):
    proto = path.name.split("_")[0]
    replayer = _REPLAYERS.get(proto)
    assert replayer is not None, f"no replayer registered for {proto}"
    frames = _load(path)
    assert frames, f"{path.name} contains no frames"
    for direction, data in frames:
        replayer(direction, data)


def test_golden_directory_has_at_least_the_sample():
    assert _files(), "tests/golden lost its sample transcripts"
