"""Golden-transcript replay harness (docs/COMPAT_RUNBOOK.md's vendoring
format): every ``tests/golden/*.hex`` conversation file is loaded and each
frame is replayed through the matching protocol codec.

- ``>`` lines (client→server) must decode cleanly AND re-encode to the
  EXACT same bytes (detects any wire-format drift in the codec since the
  transcript was captured).
- ``<`` lines (server→client) must decode cleanly.
- ``#`` lines are comments.

The shipped sample transcripts are fake-broker captures (see
tests/golden/generate_sample.py — honest about their provenance); drop in
real-broker tcpdump captures with the same names/format to upgrade them to
true external validation without touching this harness."""

from pathlib import Path

import pytest

GOLDEN = Path(__file__).parent / "golden"


def _load(path: Path):
    frames = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        direction, _, hexdata = line.partition(" ")
        assert direction in (">", "<"), f"{path.name}: bad direction {direction!r}"
        frames.append((direction, bytes.fromhex(hexdata)))
    return frames


def _replay_pulsar(direction: str, data: bytes) -> None:
    from langstream_tpu.messaging import pulsar_protocol as wire

    # frame: [totalSize][body]; body: [cmdSize][cmd][optional payload part]
    total = int.from_bytes(data[:4], "big")
    assert total == len(data) - 4, "frame length header mismatch"
    name, fields, metadata, payload = wire.split_frame(data[4:])
    assert not name.startswith("unknown_"), (
        f"unsupported command type {name} — extend pulsar_protocol._COMMANDS"
    )
    if direction == "<":
        # server frames only need to DECODE cleanly: a real broker may
        # order protobuf fields differently than our encoder does
        return
    # client frames re-encode EXACTLY (wire-drift pin: these are the bytes
    # our own codec produced at capture time)
    cmd_size = int.from_bytes(data[4:8], "big")
    cmd_bytes = data[8 : 8 + cmd_size]
    assert wire.encode_command(name, fields) == cmd_bytes, (
        f"{name}: re-encoded command differs from transcript"
    )
    if metadata is not None:
        # payload frames: metadata must round-trip to its exact slice
        # (magic[2] + crc[4] + mdSize[4] + md follow the command section)
        md_off = 8 + cmd_size + 2 + 4
        md_size = int.from_bytes(data[md_off : md_off + 4], "big")
        md_bytes = data[md_off + 4 : md_off + 4 + md_size]
        re_md = wire.encode_message(wire.MESSAGE_METADATA, metadata)
        assert re_md == md_bytes, f"{name}: metadata re-encode drifted"


_REPLAYERS = {"pulsar": _replay_pulsar}


def _files():
    return sorted(GOLDEN.glob("*.hex"))


@pytest.mark.parametrize("path", _files(), ids=lambda p: p.name)
def test_golden_transcript_replays(path):
    proto = path.name.split("_")[0]
    replayer = _REPLAYERS.get(proto)
    assert replayer is not None, f"no replayer registered for {proto}"
    frames = _load(path)
    assert frames, f"{path.name} contains no frames"
    for direction, data in frames:
        replayer(direction, data)


def test_golden_directory_has_at_least_the_sample():
    assert _files(), "tests/golden lost its sample transcripts"
