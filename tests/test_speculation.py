"""Self-speculative decoding tests (prompt-lookup n-gram drafts + batched
multi-token verification).

The load-bearing claims, each test-enforced rather than asserted in prose:
  - greedy speculative output is TOKEN-EXACT vs non-speculative greedy on
    both cache dtypes and both admission paths (cold + prefix-cache warm) —
    speculation is a bandwidth amortization, never a math change
  - rejection sampling preserves the target distribution exactly (the
    lossless-speculation identity, checked empirically on the emitted
    marginal)
  - the n-gram index proposes historical continuations and nothing else
  - the speculative engine's compile surface is warmed up front:
    compiled_programs stays flat under speculative mixed load
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from langstream_tpu.models.configs import MODEL_PRESETS, GenerationOptions
from langstream_tpu.models.transformer import init_params
from langstream_tpu.serving.engine import GenerationRequest, ServingEngine
from langstream_tpu.serving.sampling import speculative_verify
from langstream_tpu.serving.speculation import NGramIndex

CFG = dataclasses.replace(MODEL_PRESETS["tiny-test"], dtype="float32")
CFG_INT8 = dataclasses.replace(CFG, kv_cache_dtype="int8")
PARAMS = init_params(CFG, jax.random.PRNGKey(0))

# greedy decode on fixed random weights enters a literal cycle on this
# prompt (the workload speculation exists for); the second prompt is
# non-repetitive, so exactness is tested where drafts mostly MISS too
REPETITIVE = ([5, 9, 11, 7] * 10)[:40]
PLAIN = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]
GREEDY = GenerationOptions(max_new_tokens=24, temperature=0.0)


def make_engine(config=CFG, spec=True, **kw):
    # shapes deliberately match tests/test_engine_faults.py's engines
    # (max_seq_len 128, chunk 4, default buckets): within one pytest
    # process the jit cache is shared, so aligned shapes compile ONCE
    # across both files instead of per-file — tier-1 wall time is a budget
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("decode_chunk", 4)
    engine = ServingEngine(
        config, PARAMS, speculation="auto" if spec else "off",
        speculation_tokens=4, **kw,
    )
    engine.start()
    return engine


# ---------------------------------------------------------------------------
# n-gram draft index
# ---------------------------------------------------------------------------


def test_ngram_index_proposes_historical_continuation():
    index = NGramIndex()
    index.extend([1, 2, 3, 4, 1, 2, 3])
    # the tail 3-gram (1,2,3) last occurred ending at index 2; its
    # continuation is tokens[3:] = [4, 1, 2, 3]
    assert index.propose(2) == [4, 1]
    assert index.propose(4) == [4, 1, 2, 3]


def test_ngram_index_longest_gram_wins():
    index = NGramIndex()
    # unigram 7 continues with 9 early on, but the 2-gram (5, 7) continues
    # with 8 — the longer, more specific match must win
    index.extend([7, 9, 5, 7, 8, 2, 5, 7])
    assert index.propose(1) == [8]


def test_ngram_index_no_proposal_without_repeat():
    index = NGramIndex()
    index.extend([1, 2, 3, 4, 5])
    assert index.propose(4) == []
    index.append(6)
    assert index.propose(4) == []


def test_ngram_index_extends_periodically_past_the_tail():
    index = NGramIndex()
    index.extend([1, 2, 3, 1, 2])
    # match (1, 2) → continuation starts at position 2, period 3: the
    # proposal extends cyclically instead of truncating at the tail (a
    # period-p cycle would otherwise never fill more than p draft columns)
    assert index.propose(8) == [3, 1, 2, 3, 1, 2, 3, 1]


# ---------------------------------------------------------------------------
# speculative_verify: greedy acceptance + rejection-sampling distribution
# ---------------------------------------------------------------------------


def _logits_with_argmax_chain(chain, v=16):
    """[1, len(chain), v] logits whose per-position argmax is ``chain``."""
    out = np.random.default_rng(0).normal(size=(1, len(chain), v)).astype(np.float32)
    for j, t in enumerate(chain):
        out[0, j, t] = 10.0
    return jnp.asarray(out)


def _greedy_params(b=1):
    return (
        jnp.zeros(b, jnp.float32),
        jnp.zeros(b, jnp.int32),
        jnp.ones(b, jnp.float32),
    )


def test_verify_greedy_accepts_longest_matching_prefix():
    chain = [3, 7, 2, 9]  # argmax after input 0, 1, 2, 3
    logits = _logits_with_argmax_chain(chain)
    temp, top_k, top_p = _greedy_params()
    key = jax.random.PRNGKey(0)
    # drafts match the chain for 2 positions, then diverge
    out, accept = speculative_verify(
        logits, jnp.asarray([[3, 7, 5]]), key, temp, top_k, top_p
    )
    assert int(accept[0]) == 2
    # emitted = accepted drafts + the correction the draft failed to match
    assert out[0, :3].tolist() == [3, 7, 2]
    # full acceptance ⇒ the bonus token from the last position rides too
    out, accept = speculative_verify(
        logits, jnp.asarray([[3, 7, 2]]), key, temp, top_k, top_p
    )
    assert int(accept[0]) == 3
    assert out[0].tolist() == chain
    # immediate mismatch ⇒ one token, the position-0 argmax
    out, accept = speculative_verify(
        logits, jnp.asarray([[9, 9, 9]]), key, temp, top_k, top_p
    )
    assert int(accept[0]) == 0
    assert int(out[0, 0]) == 3


def test_verify_nan_row_emits_sentinel_with_zero_accept():
    logits = _logits_with_argmax_chain([3, 7, 2])
    logits = logits.at[0, 1, :].set(jnp.nan)
    temp, top_k, top_p = _greedy_params()
    out, accept = speculative_verify(
        logits, jnp.asarray([[3, 7]]), jax.random.PRNGKey(0), temp, top_k, top_p
    )
    assert int(accept[0]) == 0
    assert int(out[0, 0]) == -1


def test_verify_rejection_sampling_preserves_marginal():
    """The lossless-speculation identity: with a point-mass draft q,
    P(emitted first token = t) must equal the target p(t) for EVERY t —
    accept contributes p(d) at the draft, rejection contributes
    (1 - p(d)) * p(t)/(1 - p(d)) elsewhere. Checked empirically over many
    keys against the analytic softmax."""
    v = 8
    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.normal(size=(1, 3, v)).astype(np.float32) * 2.0)
    drafts = jnp.asarray([[5, 1]])
    temp = jnp.asarray([0.7], jnp.float32)
    top_k = jnp.zeros(1, jnp.int32)
    top_p = jnp.ones(1, jnp.float32)

    n = 6000
    keys = jax.random.split(jax.random.PRNGKey(1), n)
    out, _ = jax.vmap(
        lambda k: speculative_verify(logits, drafts, k, temp, top_k, top_p)
    )(keys)
    first = np.asarray(out[:, 0, 0])
    counts = np.bincount(first, minlength=v) / n
    target = np.asarray(jax.nn.softmax(logits[0, 0] / temp[0]))
    # 4-sigma band per bucket at n=6000 is ≲ 0.026 for p ≤ 0.5
    np.testing.assert_allclose(counts, target, atol=0.03)


# ---------------------------------------------------------------------------
# engine: greedy token-exactness on both cache dtypes and admission paths
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("config", [CFG, CFG_INT8], ids=["float", "int8kv"])
def test_greedy_speculative_token_exact_cold(config):
    """Cold (admit-group) path: a speculative engine's greedy output is
    token-for-token identical to a non-speculative engine's — on the
    repetitive prompt where drafts largely hit AND the plain one where they
    largely miss."""
    ref_engine = make_engine(config, spec=False)
    try:
        refs = [
            ref_engine.generate(p, GREEDY, timeout=120).tokens
            for p in (REPETITIVE, PLAIN)
        ]
    finally:
        ref_engine.stop()
    engine = make_engine(config, spec=True)
    try:
        outs = [
            engine.generate(p, GREEDY, timeout=120).tokens
            for p in (REPETITIVE, PLAIN)
        ]
        stats = engine.stats()
    finally:
        engine.stop()
    assert outs == refs
    assert stats["spec-verify-dispatches-total"] > 0  # speculation ran


def test_greedy_speculative_token_exact_warm_prefix():
    """Warm (prefix-cache) admission path: speculation over a prefix-reuse
    admission must still match a cold non-speculative engine exactly, on
    both cache dtypes."""
    for config in (CFG, CFG_INT8):
        prompt = REPETITIVE + [2, 4, 6]
        ref_engine = make_engine(config, spec=False)
        try:
            ref = ref_engine.generate(prompt, GREEDY, timeout=120).tokens
        finally:
            ref_engine.stop()
        engine = make_engine(
            config, spec=True, prefix_cache="auto", prefix_cache_entries=4,
        )
        try:
            first = engine.generate(prompt, GREEDY, timeout=120).tokens
            warm = engine.generate(prompt, GREEDY, timeout=120).tokens
            stats = engine.stats()
        finally:
            engine.stop()
        assert first == ref, "publishing speculative run diverged"
        assert warm == ref, "warm-prefix speculative run diverged"
        assert stats["prefix-cache-hit-rate"] > 0, "warm path never ran"
        assert stats["spec-verify-dispatches-total"] > 0


def test_speculation_accepts_drafts_on_cyclic_output():
    """The workload claim: greedy decode that enters a cycle must be
    accelerated — drafts hit and more than one token rides per verify
    dispatch on average."""
    engine = make_engine(spec=True)
    try:
        engine.generate(REPETITIVE, GenerationOptions(max_new_tokens=32), timeout=120)
        stats = engine.stats()
    finally:
        engine.stop()
    assert stats["spec-accepted-tokens-total"] > 0
    assert stats["spec-accepted-tokens-per-step"] > 1.0
    assert 0.0 < stats["spec-acceptance-rate"] <= 1.0
    assert stats["spec-draft-hit-rate"] > 0.0


def test_speculative_sampled_and_greedy_slots_coexist():
    """Rejection sampling rides the same verify dispatch as greedy
    acceptance: a mixed batch (one sampled slot, one greedy) completes with
    full lengths and the greedy slot stays exact vs a non-spec engine."""
    ref_engine = make_engine(spec=False)
    try:
        ref = ref_engine.generate(PLAIN, GREEDY, timeout=120).tokens
    finally:
        ref_engine.stop()
    engine = make_engine(spec=True)
    try:
        sampled = engine.submit(GenerationRequest(
            prompt_tokens=REPETITIVE,
            options=GenerationOptions(max_new_tokens=20, temperature=0.8, top_k=16),
        ))
        greedy = engine.submit(GenerationRequest(
            prompt_tokens=PLAIN, options=GREEDY,
        ))
        s = sampled.result(timeout=120)
        g = greedy.result(timeout=120)
    finally:
        engine.stop()
    assert len(s.tokens) == 20 and s.finish_reason == "length"
    assert g.tokens == ref


def test_compiled_programs_flat_after_warmup_speculative_mixed_load():
    """precompile=True warms the VERIFY ladder (the speculative engine's
    only decode-phase programs) and every prefill bucket; speculative mixed
    load afterwards — bursts, sampled+greedy slots, draft hits and misses,
    completions freeing slots — must dispatch ZERO novel device programs
    (ISSUE 5 acceptance: each one is a 15-23s mid-traffic stall on chip)."""
    engine = make_engine(spec=True, max_batch=4, precompile=True)
    try:
        engine.generate(
            [1, 2, 3], GenerationOptions(max_new_tokens=4), timeout=120
        )
        warmed = engine.stats()["compiled_programs"]
        assert warmed >= 5  # verify ladder (64,128) + buckets + row-reset
        opts_greedy = GenerationOptions(max_new_tokens=12, temperature=0.0)
        opts_sampled = GenerationOptions(
            max_new_tokens=12, temperature=0.8, top_k=8, seed=3
        )
        requests = [
            engine.submit(GenerationRequest(
                prompt_tokens=(
                    REPETITIVE[: 4 + 9 * (i % 3)]
                    if i % 2
                    else [(7 * i + j) % CFG.vocab_size
                          for j in range(4 + 9 * (i % 3))]
                ),
                options=opts_sampled if i % 3 == 0 else opts_greedy,
            ))
            for i in range(10)
        ]
        for r in requests:
            r.result(timeout=120)
        assert engine.stats()["compiled_programs"] == warmed, (
            "speculative mixed load dispatched a program the warmup missed"
        )
    finally:
        engine.stop()


def test_speculation_off_reports_zeroed_stats():
    engine = make_engine(spec=False)
    try:
        stats = engine.stats()
    finally:
        engine.stop()
    assert stats["speculation"] is False
    assert stats["speculation-tokens"] == 0
    assert stats["spec-acceptance-rate"] == 0.0
    assert stats["spec-accepted-tokens-per-step"] == 0.0


# ---------------------------------------------------------------------------
# constrained + speculative exactness (ISSUE 10): the verify path must stay
# token-exact when grammar masks apply per draft position — greedy output
# equals the non-speculative constrained engine's on both KV dtypes and
# both admission paths, and the sampled path's emitted marginal equals the
# MASKED softmax (the round-9 exactness machinery, extended under masks)
# ---------------------------------------------------------------------------

from langstream_tpu.serving.tokenizer import ByteTokenizer  # noqa: E402

_TOK = ByteTokenizer()
_RF = {
    "type": "json_schema",
    "json_schema": {"schema": {
        "type": "object",
        "properties": {
            "name": {"type": "string", "maxLength": 8},
            "n": {"type": "integer"},
        },
    }},
}


def _constrained_engine(config=CFG, spec=True, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 256)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("grammar_tokenizer", _TOK)
    kw.setdefault("eos_token_id", _TOK.eos_token_id)
    engine = ServingEngine(
        config, PARAMS, speculation="auto" if spec else "off",
        speculation_tokens=4, **kw,
    )
    engine.start()
    return engine


@pytest.mark.slow
@pytest.mark.parametrize("config", [CFG, CFG_INT8], ids=["float", "int8kv"])
def test_constrained_speculative_token_exact_cold(config):
    import json as _json

    opts = GenerationOptions(max_new_tokens=80, response_format=dict(_RF))
    ref = _constrained_engine(config, spec=False)
    try:
        want = ref.generate(_TOK.encode("Hello"), opts, timeout=600)
    finally:
        ref.stop()
    engine = _constrained_engine(config, spec=True)
    try:
        got = engine.generate(_TOK.encode("Hello"), opts, timeout=600)
        stats = engine.stats()
    finally:
        engine.stop()
    assert got.tokens == want.tokens
    assert got.finish_reason == "stop"
    _json.loads(_TOK.decode(got.tokens))  # the structured-output guarantee
    assert stats["spec-verify-dispatches-total"] > 0  # spec actually ran


@pytest.mark.slow
def test_constrained_speculative_token_exact_prefix_warm():
    """Prefix-warm constrained admission under speculation: warm output ==
    cold output == the non-speculative engine's, with a real cache hit."""
    import json as _json

    preamble = _TOK.encode("y" * 80)
    opts = GenerationOptions(max_new_tokens=80, response_format=dict(_RF))
    ref = _constrained_engine(spec=False, prefix_cache="auto")
    try:
        want = ref.generate(list(preamble), opts, timeout=600).tokens
    finally:
        ref.stop()
    engine = _constrained_engine(spec=True, prefix_cache="auto")
    try:
        cold = engine.generate(list(preamble), opts, timeout=600)
        saved0 = engine.stats()["prefill-tokens-saved-total"]
        warm = engine.generate(list(preamble), opts, timeout=600)
        assert engine.stats()["prefill-tokens-saved-total"] > saved0
    finally:
        engine.stop()
    assert cold.tokens == want
    assert warm.tokens == want
    _json.loads(_TOK.decode(warm.tokens))


def test_verify_masked_rejection_sampling_preserves_masked_marginal():
    """Distribution exactness UNDER MASKS: with per-position allowed sets,
    the emitted first token's marginal equals the MASKED softmax — an
    illegal draft (p=0 under the mask) is never accepted, and corrections
    come from the masked residual."""
    v = 8
    rng = np.random.default_rng(9)
    logits = jnp.asarray(rng.normal(size=(1, 3, v)).astype(np.float32) * 2.0)
    allowed = np.zeros((1, 3, v), bool)
    allowed[0, :, [1, 3, 5]] = True
    drafts = jnp.asarray([[2, 5]])  # draft 2 is ILLEGAL at position 0
    temp = jnp.asarray([0.7], jnp.float32)
    top_k = jnp.zeros(1, jnp.int32)
    top_p = jnp.ones(1, jnp.float32)

    n = 6000
    keys = jax.random.split(jax.random.PRNGKey(2), n)
    out, accept = jax.vmap(
        lambda k: speculative_verify(
            logits, drafts, k, temp, top_k, top_p, jnp.asarray(allowed)
        )
    )(keys)
    assert int(np.max(np.asarray(accept))) == 0  # illegal draft never accepted
    first = np.asarray(out[:, 0, 0])
    assert set(np.unique(first)).issubset({1, 3, 5})
    masked = np.where(allowed[0, 0], np.asarray(logits[0, 0]) / 0.7, -np.inf)
    target = np.exp(masked - masked.max())
    target /= target.sum()
    counts = np.bincount(first, minlength=v) / n
    np.testing.assert_allclose(counts, target, atol=0.03)


def test_verify_masked_greedy_accepts_only_legal_matching_drafts():
    temp, top_k, top_p = _greedy_params()
    logits = _logits_with_argmax_chain([3, 7, 2])
    allowed = np.ones((1, 3, 16), bool)
    allowed[0, 1, 7] = False  # the matching draft at position 1 is ILLEGAL
    out, accept = speculative_verify(
        logits, jnp.asarray([[3, 7]]), jax.random.PRNGKey(0), temp, top_k,
        top_p, jnp.asarray(allowed),
    )
    # position 0's draft (3, legal, matches) accepted; position 1's draft
    # matches the RAW argmax but is masked out → rejected, correction is
    # the masked argmax at that position
    assert int(accept[0]) == 1
    assert int(out[0, 0]) == 3
    assert int(out[0, 1]) != 7
