"""gRPC out-of-process agent tests (reference test_grpc_processor/
test_grpc_source/test_grpc_sink against an in-process server + the
subprocess bridge path with crash/restart)."""

import asyncio
import json
from pathlib import Path

import grpc
import pytest

from langstream_tpu.api.record import SimpleRecord
from langstream_tpu.core.parser import ModelBuilder
from langstream_tpu.grpc_runtime import agent_pb2 as pb
from langstream_tpu.grpc_runtime.convert import from_grpc_record, method, to_grpc_record
from langstream_tpu.grpc_runtime.service import AgentServiceServer, load_agent_class

TESTS_DIR = str(Path(__file__).parent)


# ---------------------------------------------------------------------------
# In-process server ↔ raw channel (proto contract tests)
# ---------------------------------------------------------------------------


def test_process_rpc_roundtrip(run):
    async def scenario():
        agent = load_agent_class("grpc_user_agents.Exclaim", TESTS_DIR)
        server = AgentServiceServer(agent, {"suffix": "?!"})
        port = await server.start()
        channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        stub = channel.stream_stream(
            method("process"),
            request_serializer=pb.ProcessorRequest.SerializeToString,
            response_deserializer=pb.ProcessorResponse.FromString,
        )
        call = stub()
        records = [
            to_grpc_record(SimpleRecord.of("hello", key="k1"), 1),
            to_grpc_record(SimpleRecord.of("explode"), 2),
            to_grpc_record(SimpleRecord.of({"structured": True}), 3),
        ]
        await call.write(pb.ProcessorRequest(records=records))
        response = await call.read()
        results = {r.record_id: r for r in response.results}
        assert from_grpc_record(results[1].records[0]).value == "hello?!"
        assert from_grpc_record(results[1].records[0]).key == "k1"
        assert results[2].HasField("error")
        assert "explode" in results[2].error
        # structured value → json round trip, then stringified by Exclaim
        assert "structured" in from_grpc_record(results[3].records[0]).value
        await call.done_writing()
        await channel.close()
        await server.stop()

    run(scenario())


def test_source_rpc_commit_flow(run):
    async def scenario():
        agent = load_agent_class("grpc_user_agents.CountSource", TESTS_DIR)
        server = AgentServiceServer(agent, {"limit": 2})
        port = await server.start()
        channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        stub = channel.stream_stream(
            method("read"),
            request_serializer=pb.SourceRequest.SerializeToString,
            response_deserializer=pb.SourceResponse.FromString,
        )
        call = stub()
        got = []
        while len(got) < 2:
            response = await call.read()
            got.extend(response.records)
        assert [from_grpc_record(m).value for m in got] == ["item-1", "item-2"]
        await call.write(
            pb.SourceRequest(committed_records=[got[0].record_id])
        )
        for _ in range(100):
            if agent.committed:
                break
            await asyncio.sleep(0.02)
        assert agent.committed == ["item-1"]
        await call.done_writing()
        await channel.close()
        await server.stop()

    run(scenario())


def test_agent_info_rpc(run):
    async def scenario():
        agent = load_agent_class("grpc_user_agents.Exclaim", TESTS_DIR)
        agent.agent_id = "my-agent"
        server = AgentServiceServer(agent, {})
        port = await server.start()
        channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
        stub = channel.unary_unary(
            method("agent_info"),
            request_serializer=pb.InfoRequest.SerializeToString,
            response_deserializer=pb.InfoResponse.FromString,
        )
        response = await stub(pb.InfoRequest())
        info = json.loads(response.json_info)
        assert info["agent-id"] == "my-agent"
        assert info["component-type"] == "processor"
        await channel.close()
        await server.stop()

    run(scenario())


# ---------------------------------------------------------------------------
# Subprocess bridge in a full pipeline
# ---------------------------------------------------------------------------

PIPELINE_TEMPLATE = """
module: default
id: p
name: python
topics:
  - name: input-topic
    creation-mode: create-if-not-exists
  - name: output-topic
    creation-mode: create-if-not-exists
errors:
  retries: 5
  on-failure: fail
pipeline:
  - name: user-code
    type: python-processor
    input: input-topic
    output: output-topic
    configuration:
      className: {class_name}
      pythonPath: {python_path}
      {extra}
"""

INSTANCE = """
instance:
  streamingCluster:
    type: memory
  computeCluster:
    type: local
"""


async def run_python_pipeline(class_name, values, extra="", n_out=None, timeout=30):
    from langstream_tpu.runtime.local_runner import LocalApplicationRunner

    pipeline = PIPELINE_TEMPLATE.format(
        class_name=class_name, python_path=TESTS_DIR, extra=extra
    )
    pkg = ModelBuilder.build_application_from_files(
        {"pipeline.yaml": pipeline}, INSTANCE, None
    )
    runner = LocalApplicationRunner("py-test", pkg.application)
    await runner.deploy()
    await runner.start()
    try:
        for v in values:
            await runner.produce("input-topic", v)
        out = await runner.consume(
            "output-topic", n=n_out or len(values), timeout=timeout
        )
        return [r.value for r in out]
    finally:
        await runner.stop()


def test_python_processor_subprocess(run):
    values = run(run_python_pipeline("grpc_user_agents.Exclaim", ["a", "b", "c"]))
    assert values == ["a!", "b!", "c!"]


def test_python_processor_subprocess_crash_restart(run, tmp_path):
    marker = tmp_path / "crashed"
    extra = f"marker-file: {marker}"
    # 'die' crashes the subprocess once (rc=13); the bridge restarts it and
    # at-least-once redelivery retries the record, which then succeeds
    values = run(
        run_python_pipeline(
            "grpc_user_agents.CrashOnce", ["die"], extra=extra, n_out=1, timeout=60
        )
    )
    assert values == ["survived:die"]
    assert marker.exists()
