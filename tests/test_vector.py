"""Vector / datasource agent tests.

Mirrors the reference's JdbcDatabaseIT / QueryVectorDBAgent tests /
ReRankAgent tests (SURVEY §4 tier-2) on the bundled sqlite and local-vector
backends."""

import json
import math

import numpy as np

from langstream_tpu.agents.vector import (
    FlareControllerAgent,
    LocalVectorDataSource,
    ReRankAgent,
    SqliteDataSource,
)
from langstream_tpu.api.record import SimpleRecord, header_value
from langstream_tpu.core.parser import ModelBuilder
from langstream_tpu.runtime.local_runner import LocalApplicationRunner
from langstream_tpu.runtime.topic_adapters import DESTINATION_HEADER


def make_app(pipeline_yaml, configuration_yaml=None):
    files = {"pipeline.yaml": pipeline_yaml}
    if configuration_yaml:
        files["configuration.yaml"] = configuration_yaml
    return ModelBuilder.build_application_from_files(
        files, instance_text="instance:\n  streamingCluster:\n    type: memory\n"
    ).application


# ---------------------------------------------------------------------------
# datasources
# ---------------------------------------------------------------------------


def test_sqlite_datasource(run):
    async def main():
        ds = SqliteDataSource({"url": ":memory:"})
        await ds.execute_statement("CREATE TABLE t (id INTEGER PRIMARY KEY, name TEXT)", [])
        await ds.execute_statement("INSERT INTO t (name) VALUES (?)", ["alice"])
        rows = await ds.fetch_data("SELECT * FROM t WHERE name = ?", ["alice"])
        assert rows == [{"id": 1, "name": "alice"}]
        await ds.close()

    run(main())


def test_local_vector_search(run):
    async def main():
        ds = LocalVectorDataSource({})
        ds.create_index("docs", 4)
        ds.upsert("docs", "a", [1, 0, 0, 0], {"text": "doc a"})
        ds.upsert("docs", "b", [0, 1, 0, 0], {"text": "doc b"})
        ds.upsert("docs", "c", [0.9, 0.1, 0, 0], {"text": "doc c"})
        rows = await ds.fetch_data(
            json.dumps({"index": "docs", "vector": [1, 0, 0, 0], "topK": 2}), []
        )
        assert [r["id"] for r in rows] == ["a", "c"]
        assert rows[0]["similarity"] > 0.99
        assert rows[0]["text"] == "doc a"

    run(main())


def test_local_vector_growth_and_upsert(run):
    async def main():
        ds = LocalVectorDataSource({})
        ds.create_index("d", 8)
        rng = np.random.default_rng(0)
        for i in range(50):  # force capacity doubling past 16
            ds.upsert("d", f"v{i}", rng.normal(size=8).tolist(), {"i": i})
        ds.upsert("d", "v7", [1.0] * 8, {"i": "updated"})  # overwrite
        rows = ds.search("d", [1.0] * 8, top_k=1)
        assert rows[0]["id"] == "v7" and rows[0]["i"] == "updated"
        assert len(ds.search("d", [1.0] * 8, top_k=100)) == 50

    run(main())


def test_local_vector_persistence(run, tmp_path):
    async def main():
        ds = LocalVectorDataSource({"path": str(tmp_path / "vx")})
        ds.create_index("docs", 3)
        ds.upsert("docs", "a", [1, 2, 3], {"text": "hello"})
        await ds.close()
        ds2 = LocalVectorDataSource({"path": str(tmp_path / "vx")})
        rows = ds2.search("docs", [1, 2, 3], top_k=1)
        assert rows[0]["id"] == "a" and rows[0]["text"] == "hello"

    run(main())


# ---------------------------------------------------------------------------
# vector-db-sink + query-vector-db end-to-end
# ---------------------------------------------------------------------------

RAG_CONFIG = """
configuration:
  resources:
    - type: datasource
      name: vdb
      id: vdb
      configuration:
        service: jdbc
        url: "file:ragtest?mode=memory&cache=shared"
"""


def test_jdbc_sink_and_query_pipeline(run):
    pipeline = """
id: p
assets:
  - name: docs-table
    id: docs-table
    asset-type: jdbc-table
    creation-mode: create-if-not-exists
    config:
      table-name: docs
      create-statements:
        - "CREATE TABLE docs (id TEXT PRIMARY KEY, text TEXT)"
      datasource:
        url: "file:ragtest?mode=memory&cache=shared"
topics:
  - name: in-t
  - name: q-in
  - name: q-out
pipeline:
  - type: vector-db-sink
    id: sink
    input: in-t
    configuration:
      datasource: vdb
      table-name: docs
      fields:
        - name: id
          expression: value.id
          primary-key: true
        - name: text
          expression: value.text
  - type: query-vector-db
    id: q
    input: q-in
    output: q-out
    configuration:
      datasource: vdb
      query: "SELECT text FROM docs WHERE id = ?"
      fields:
        - value.lookup
      output-field: value.result
      only-first: true
"""

    async def main():
        app = make_app(pipeline, RAG_CONFIG)
        runner = LocalApplicationRunner("t", app)
        # the jdbc-table asset creates the table via the shared-cache URI;
        # keep one anchor connection open so the shared in-memory DB survives
        # the asset manager's close
        anchor = SqliteDataSource({"url": "file:ragtest?mode=memory&cache=shared"})
        await runner.run()
        ds = runner._service_registry.get_datasource("vdb")
        rows = await ds.fetch_data(
            "SELECT name FROM sqlite_master WHERE type='table' AND name='docs'", []
        )
        assert rows, "jdbc-table asset did not create the table"
        await runner.produce("in-t", json.dumps({"id": "d1", "text": "hello world"}))
        # wait for the sink to land the row
        import asyncio

        for _ in range(100):
            rows = await ds.fetch_data("SELECT * FROM docs", [])
            if rows:
                break
            await asyncio.sleep(0.05)
        assert rows == [{"id": "d1", "text": "hello world"}]
        # upsert: same pk, new text
        await runner.produce("in-t", json.dumps({"id": "d1", "text": "updated"}))
        for _ in range(100):
            rows = await ds.fetch_data("SELECT * FROM docs", [])
            if rows and rows[0]["text"] == "updated":
                break
            await asyncio.sleep(0.05)
        assert rows == [{"id": "d1", "text": "updated"}]

        await runner.produce("q-in", json.dumps({"lookup": "d1"}))
        out = await runner.consume("q-out", 1, timeout=5)
        await runner.stop()
        await anchor.close()
        doc = json.loads(out[0].value)
        assert doc["result"] == {"text": "updated"}

    run(main())


LOCAL_VECTOR_CONFIG = """
configuration:
  resources:
    - type: vector-database
      name: vdb
      id: vdb
      configuration:
        service: local-vector
"""


def test_local_vector_pipeline(run):
    pipeline = """
id: p
topics:
  - name: docs-in
  - name: q-in
  - name: q-out
pipeline:
  - type: vector-db-sink
    id: sink
    input: docs-in
    configuration:
      datasource: vdb
      index-name: docs
      id: value.id
      vector: value.embeddings
      fields:
        - name: text
          expression: value.text
  - type: query-vector-db
    id: q
    input: q-in
    output: q-out
    configuration:
      datasource: vdb
      query: '{"index": "docs", "vector": "?", "topK": 2}'
      fields:
        - value.embeddings
      output-field: value.matches
"""

    async def main():
        import asyncio

        app = make_app(pipeline, LOCAL_VECTOR_CONFIG)
        runner = LocalApplicationRunner("t", app)
        await runner.run()
        ds = runner._service_registry.get_datasource("vdb")
        for i, vec in enumerate([[1, 0, 0], [0, 1, 0], [0.8, 0.2, 0]]):
            await runner.produce(
                "docs-in", json.dumps({"id": f"d{i}", "embeddings": vec, "text": f"doc {i}"})
            )
        for _ in range(100):
            if ds.has_index("docs") and len(ds.search("docs", [1, 0, 0], 10)) == 3:
                break
            await asyncio.sleep(0.05)
        await runner.produce("q-in", json.dumps({"embeddings": [1, 0, 0]}))
        out = await runner.consume("q-out", 1, timeout=5)
        await runner.stop()
        doc = json.loads(out[0].value)
        assert [m["id"] for m in doc["matches"]] == ["d0", "d2"]
        assert doc["matches"][0]["text"] == "doc 0"

    run(main())


# ---------------------------------------------------------------------------
# re-rank + flare
# ---------------------------------------------------------------------------


def test_rerank_mmr(run):
    async def main():
        agent = ReRankAgent()
        await agent.init(
            {
                "field": "value.docs",
                "output-field": "value.ranked",
                "query-embeddings": "value.query_vec",
                "embeddings-field": "record.vec",
                "algorithm": "MMR",
                "lambda": 0.3,
                "max": 2,
            }
        )
        docs = [
            {"id": "close-dup-1", "vec": [1, 0]},
            {"id": "close-dup-2", "vec": [0.999, 0.001]},
            {"id": "diverse", "vec": [0.6, 0.8]},
        ]
        rec = SimpleRecord.of(json.dumps({"docs": docs, "query_vec": [1, 0]}))
        out = await agent.process_record(rec)
        ranked = json.loads(out[0].value)["ranked"]
        # MMR picks the most relevant first, then the diverse one over the dup
        assert ranked[0]["id"] == "close-dup-1"
        assert ranked[1]["id"] == "diverse"

    run(main())


def test_flare_controller(run):
    async def main():
        agent = FlareControllerAgent()
        await agent.init(
            {
                "tokens-field": "value.tokens",
                "logprobs-field": "value.logprobs",
                "min-prob": 0.5,
                "retrieve-query-field": "value.flare-query",
                "loop-topic": "retry-t",
            }
        )
        confident = SimpleRecord.of(
            json.dumps({"tokens": ["a", "b"], "logprobs": [-0.01, -0.02]})
        )
        out = await agent.process_record(confident)
        assert out[0].value == confident.value  # untouched passthrough

        lp_low = math.log(0.1)
        uncertain = SimpleRecord.of(
            json.dumps({"tokens": ["Paris", "is", "wrong"], "logprobs": [-0.01, lp_low, lp_low]})
        )
        out = await agent.process_record(uncertain)
        doc = json.loads(out[0].value)
        assert doc["flare-query"] == "is wrong"
        assert header_value(out[0], DESTINATION_HEADER) == "retry-t"

    run(main())


def test_vector_index_asset(run, tmp_path):
    """Declarative vector-index asset: created at setup, visible to a store
    sharing the same persistence path."""
    from langstream_tpu.api.model import AssetDefinition
    from langstream_tpu.core.registry import REGISTRY

    path = str(tmp_path / "vecs")
    asset = AssetDefinition(
        id="idx",
        asset_type="vector-index",
        creation_mode="create-if-not-exists",
        config={
            "index-name": "docs",
            "dimension": 4,
            "datasource": {"configuration": {"path": path}},
        },
    )

    async def scenario():
        info = REGISTRY.asset("vector-index")
        manager = info.factory()
        await manager.initialize(asset)
        assert not await manager.asset_exists()
        await manager.deploy_asset()
        assert await manager.asset_exists()
        # a fresh store over the same path sees the index
        fresh = LocalVectorDataSource({"path": path})
        assert fresh.has_index("docs")
        await manager.delete_asset()
        assert not await manager.asset_exists()

    run(scenario())
