"""Fused (overlapped) prefill–decode scheduling tests: token-budgeted
prefill slices riding every engine iteration back-to-back with the decode
chunk — exactness vs the serialized path, one-iteration admission latency,
and the no-mid-traffic-compiles guarantee via the compiled_programs stat."""

import dataclasses
from collections import deque

import jax

from langstream_tpu.models.configs import MODEL_PRESETS, GenerationOptions
from langstream_tpu.models.transformer import init_params
from langstream_tpu.serving.engine import GenerationRequest, ServingEngine

CFG = dataclasses.replace(MODEL_PRESETS["tiny-test"], dtype="float32")
PARAMS = init_params(CFG, jax.random.PRNGKey(0))


def make_engine(start=True, **kw):
    engine = ServingEngine(CFG, PARAMS, **kw)
    if start:
        engine.start()
    return engine


def test_mixed_prefill_decode_matches_serialized_reference():
    """Greedy tokens from a fused mixed load (active decode + two long
    prompts chunk-prefilling concurrently + short admissions) are identical
    to each request served ALONE on a serialized (overlap off) engine —
    the fused iterations change scheduling, never math."""
    opts = GenerationOptions(max_new_tokens=16, temperature=0.0)
    short_prompt = [5, 6, 7]
    long_a = [(3 + i) % CFG.vocab_size for i in range(70)]  # 5 segments @16
    long_b = [(11 + 2 * i) % CFG.vocab_size for i in range(55)]  # 4 segments

    ref = {}
    serial = make_engine(
        max_batch=1, max_seq_len=128, decode_chunk=4, prefill_buckets=(16,),
        overlap=False,
    )
    try:
        for name, prompt in (("s", short_prompt), ("a", long_a), ("b", long_b)):
            ref[name] = serial.generate(prompt, opts, timeout=120).tokens
    finally:
        serial.stop()

    fused = make_engine(
        max_batch=4, max_seq_len=128, decode_chunk=4, prefill_buckets=(16,),
        overlap=True, max_prefill_streams=2, prefill_token_budget=32,
    )
    try:
        short_req = fused.submit(
            GenerationRequest(prompt_tokens=short_prompt, options=opts)
        )
        ra = fused.submit(GenerationRequest(prompt_tokens=long_a, options=opts))
        rb = fused.submit(GenerationRequest(prompt_tokens=long_b, options=opts))
        assert short_req.result(timeout=120).tokens == ref["s"]
        assert ra.result(timeout=120).tokens == ref["a"]
        assert rb.result(timeout=120).tokens == ref["b"]
    finally:
        fused.stop()


def test_admission_rides_the_very_next_iteration_under_load():
    """With a decode chunk in flight for a saturated-busy engine, a new
    arrival's prefill must dispatch in the very next fused iteration — not
    after the running generation drains. White-box: drive _iterate by hand
    (no engine thread) so 'one iteration' is exact, not a timing guess."""
    engine = make_engine(
        start=False, max_batch=2, max_seq_len=128, decode_chunk=8,
        overlap=True,
    )
    pending: deque = deque()
    opts = GenerationOptions(max_new_tokens=60, temperature=0.0)
    engine.submit(GenerationRequest(prompt_tokens=[4, 5, 6], options=opts))
    engine._iterate(pending)  # admits A, dispatches its first chunk
    assert sum(1 for s in engine._slots if s.active) == 1

    engine.submit(GenerationRequest(prompt_tokens=[7, 8], options=opts))
    engine._iterate(pending)  # chunk in flight for A — B must still admit
    assert sum(1 for s in engine._slots if s.active) == 2, (
        "new arrival did not get its prefill within one fused iteration"
    )
    engine._stop.set()
    while pending:
        for entry in pending.popleft():
            engine._process_entry(entry)
    engine._fail_all(RuntimeError("test torn down"))


def test_prefill_token_budget_bounds_per_iteration_admission():
    """A backlog wider than the budget admits exactly one budget's worth of
    prefill per iteration (first group always rides), the rest staying
    queued — so decode chunks interleave instead of stalling behind the
    whole wave."""
    engine = make_engine(
        start=False, max_batch=8, max_seq_len=128, decode_chunk=4,
        prefill_buckets=(16,), prefill_batch=2, overlap=True,
        prefill_token_budget=32,
    )
    # long enough that nothing finishes within the iterations driven below
    opts = GenerationOptions(max_new_tokens=60, temperature=0.0)
    for _ in range(6):
        engine.submit(GenerationRequest(prompt_tokens=[9, 9, 9], options=opts))
    pending: deque = deque()
    engine._iterate(pending)
    # budget 32 at bucket width 16 → 2 requests this iteration, 4 queued
    assert sum(1 for s in engine._slots if s.active) == 2
    assert engine._queue.qsize() == 4
    engine._iterate(pending)
    assert sum(1 for s in engine._slots if s.active) == 4
    engine._stop.set()
    while pending:
        for entry in pending.popleft():
            engine._process_entry(entry)
    engine._fail_all(RuntimeError("test torn down"))


def test_compiled_programs_flat_after_warmup_mixed_load():
    """precompile=True warms the decode ladder AND every prefill bucket (the
    fused-iteration shapes); a mixed load afterwards — bursts, sampling,
    queued work, near-tail generations — must dispatch ZERO novel device
    programs (each one would be a 15-23s mid-traffic compile stall on the
    tunneled chip). Overlap retires the shrunk-chunk program entirely, so
    the surface is exactly {ladder} ∪ {prefill buckets}."""
    engine = make_engine(
        max_batch=4, max_seq_len=256, decode_chunk=8, ttft_chunk_floor=4,
        prefill_buckets=(16, 32), precompile=True, overlap=True,
    )
    try:
        # first request completes ⇒ warmup finished (the loop warms before
        # serving); its programs are part of the warmed set by construction
        engine.generate(
            [1, 2, 3], GenerationOptions(max_new_tokens=4, temperature=0.0),
            timeout=120,
        )
        warmed = engine.stats()["compiled_programs"]
        assert warmed >= 5  # ladder (64,128,256) + 2 prefill buckets

        opts_greedy = GenerationOptions(max_new_tokens=12, temperature=0.0)
        opts_sampled = GenerationOptions(
            max_new_tokens=12, temperature=0.8, top_k=8, seed=3
        )
        requests = [
            engine.submit(GenerationRequest(
                prompt_tokens=[(7 * i + j) % CFG.vocab_size
                               for j in range(4 + 9 * (i % 3))],
                options=opts_sampled if i % 3 == 0 else opts_greedy,
            ))
            for i in range(10)
        ]
        for r in requests:
            r.result(timeout=120)
        assert engine.stats()["compiled_programs"] == warmed, (
            "mixed load dispatched a device program the warmup missed"
        )
    finally:
        engine.stop()


def test_overlap_off_preserves_single_stream_behavior():
    """overlap=False keeps the pre-fusion scheduler: unbounded admission,
    one chunked-prefill stream."""
    engine = make_engine(
        start=False, max_batch=4, max_seq_len=128, decode_chunk=4,
        prefill_buckets=(16,), overlap=False,
    )
    assert engine.max_prefill_streams == 1
    opts = GenerationOptions(max_new_tokens=60, temperature=0.0)
    for _ in range(4):
        engine.submit(GenerationRequest(prompt_tokens=[3, 4], options=opts))
    pending: deque = deque()
    engine._iterate(pending)
    # no budget: the whole backlog admits in one iteration
    assert sum(1 for s in engine._slots if s.active) == 4
    engine._stop.set()
    while pending:
        for entry in pending.popleft():
            engine._process_entry(entry)
    engine._fail_all(RuntimeError("test torn down"))


def test_concurrent_long_prefill_streams_share_iterations():
    """Two long prompts prefill CONCURRENTLY (two streams, round-robin
    segments) and both finish with correct token counts while a short
    generation keeps streaming — nobody is serialized behind a whole
    prompt."""
    engine = make_engine(
        max_batch=3, max_seq_len=256, decode_chunk=4, prefill_buckets=(16,),
        overlap=True, max_prefill_streams=2, prefill_token_budget=64,
    )
    try:
        opts = GenerationOptions(max_new_tokens=20, temperature=0.0)
        short = engine.submit(
            GenerationRequest(prompt_tokens=[5, 6, 7], options=opts)
        )
        la = [(3 + i) % CFG.vocab_size for i in range(120)]
        lb = [(5 + 3 * i) % CFG.vocab_size for i in range(100)]
        ra = engine.submit(GenerationRequest(prompt_tokens=la, options=opts))
        rb = engine.submit(GenerationRequest(prompt_tokens=lb, options=opts))
        rs = short.result(timeout=120)
        res_a = ra.result(timeout=120)
        res_b = rb.result(timeout=120)
        assert len(rs.tokens) == 20
        assert res_a.prompt_tokens == 120 and len(res_a.tokens) == 20
        assert res_b.prompt_tokens == 100 and len(res_b.tokens) == 20
    finally:
        engine.stop()


def test_bandwidth_gauge_reports_after_decode():
    """The achieved-HBM-bandwidth gauge is live after decode chunks ran:
    step-time EMA > 0 and the bytes-model yields a finite GB/s (the
    ~25%-of-roofline gap becomes a shipped metric, not a PERF.md note)."""
    engine = make_engine(max_batch=2, max_seq_len=64, decode_chunk=4)
    try:
        engine.generate(
            [1, 2, 3], GenerationOptions(max_new_tokens=8, temperature=0.0),
            timeout=120,
        )
        stats = engine.stats()
        assert stats["decode-step-ms"] > 0
        assert stats["hbm-gbps-decode"] > 0
        assert stats["compiled_programs"] >= 2  # ≥ one prefill + one decode
    finally:
        engine.stop()


def test_overlap_runs_full_chunks_only():
    """Fused scheduling retires the TTFT chunk shrink: queued work no
    longer shrinks the chunk (prefill rides every iteration instead), so
    the decode compile surface is exactly the kv_bound ladder — the shrunk
    size was a whole extra program whose first dispatch landed on the first
    real burst (the r5b mid-traffic stall class)."""
    engine = make_engine(
        start=False, max_batch=4, max_seq_len=256, decode_chunk=64,
        overlap=True,
    )
    engine._slots[0].request = GenerationRequest(
        prompt_tokens=[1], options=GenerationOptions(max_new_tokens=200)
    )
    engine._slots[0].position = 10
    assert engine._chunk_steps() == 64
    engine._queue.put(object())
    assert engine._chunk_steps() == 64  # no shrink under overlap
    engine._queue.get_nowait()
    engine._slots[0].request = None
