"""Tiered KV tests (ROADMAP item 3 / ISSUE 11): host-RAM spill + session
hibernation must be a capacity/bandwidth reorganization, never a math
change. The contracts proven here:

  - RESTORE IS TOKEN-EXACT: a session whose prefix pages were spilled to
    the host arena, demoted off the device pool, and restored on its next
    turn generates byte-identical tokens to an always-device-resident run
    — across float + int8 KV, speculation on/off, and constrained slots.
  - THE TIER DEGRADES, NEVER LIES: a corrupted host page (the ``spill``
    fault site — host-RAM-rot drill) is caught by the arena checksum and
    the victim admission falls back to a cold re-prefill, token-exact,
    with zero engine restarts; survivors restore cleanly.
  - NOTHING LEAKS: spill→evict→restore→free cycles leave BOTH free lists
    (device pool pages, host arena slots) at their initial state.
  - SPILL IS OFF THE HOT LOOP: the per-iteration spill bookkeeping stays
    within the round-11 ≤1% instrumentation bound of a decode step.

CI pins LSTPU_FAULT_SEED (tier1.yml chaos step); the tests pass explicit
seeds anyway so they are deterministic in any environment.
"""

import dataclasses
import logging
import time

import jax
import numpy as np
import pytest

from langstream_tpu.models.configs import MODEL_PRESETS, GenerationOptions
from langstream_tpu.models.transformer import init_params
from langstream_tpu.serving.engine import ServingEngine
from langstream_tpu.serving.faultinject import FaultInjector
from langstream_tpu.serving.pagepool import (
    HostPageTier,
    PagePool,
    PrefixPageIndex,
)
from langstream_tpu.serving.tokenizer import ByteTokenizer

CFG = dataclasses.replace(MODEL_PRESETS["tiny-test"], dtype="float32")
CFG_INT8 = dataclasses.replace(CFG, kv_cache_dtype="int8")
PARAMS = init_params(CFG, jax.random.PRNGKey(0))

GREEDY = GenerationOptions(max_new_tokens=10, temperature=0.0)

# two 45-token sessions over a 16/32/64 bucket ladder at page_size=16:
# each publishes a 32-token (2-page) prefix; kv_pages=5 cannot hold two
# resident sessions, so admitting B demotes A's hibernated prefix — the
# exact churn the tier exists for
PROMPT_A = [(7 + 3 * i) % CFG.vocab_size for i in range(45)]
PROMPT_B = [(5 + 11 * i) % CFG.vocab_size for i in range(45)]


def make_engine(config=CFG, tier=True, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("decode_chunk", 4)
    kw.setdefault("prefill_buckets", (16, 32, 64))
    kw.setdefault("page_size", 16)
    if tier:
        kw.setdefault("kv_pages", 5)
        kw.setdefault("host_kv_fraction", 2.0)
        kw.setdefault("spill_idle_s", 0.0)  # hibernate as soon as idle
        kw.setdefault("prefix_cache", "auto")
        kw.setdefault("prefix_cache_entries", 8)
    else:
        kw.setdefault("prefix_cache", "off")
        kw.setdefault("host_kv_fraction", 0.0)
    engine = ServingEngine(config, PARAMS, kv_layout="paged", **kw)
    engine.start()
    return engine


def wait_spilled(engine, pages: int, timeout: float = 30.0) -> None:
    """Block until the idle-sweep has landed ``pages`` cumulative spill
    pages host-side (the engine iterates ~1ms while idle, so hibernation
    happens promptly once the session finishes)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if engine.stats()["spill-pages-total"] >= pages:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"spill never reached {pages} pages: {engine.stats()['spill-pages-total']}"
    )


def assert_leak_free(engine) -> None:
    """The ISSUE-11 no-leak bar: after the engine quiesces, dropping every
    surviving prefix entry must return BOTH free lists — device pool pages
    and host arena slots — to their initial (all-free) state."""
    pool, index, hier = engine._pagepool, engine._prefix_index, engine._host_tier
    engine._drain_spills()  # fold in any copy that completed at shutdown
    for entry in list(index._live):
        index._drop(pool, entry)
    assert pool.free_pages == pool.num_pages, (
        f"device pool leaked {pool.num_pages - pool.free_pages} pages"
    )
    if hier is not None:
        assert hier.free_slots == hier.num_pages, (
            f"host arena leaked {hier.num_pages - hier.free_slots} slots"
        )


# ---------------------------------------------------------------------------
# Token-exactness: hibernate → demote → restore
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "config, spec",
    [
        # curated combos (the pagepool suite's budget discipline): the two
        # tier-1 legs cover both KV dtypes AND spec on/off; the slow pair
        # completes the product in the chaos CI step (no marker filter)
        (CFG, False),
        pytest.param(CFG, True, marks=pytest.mark.slow),
        pytest.param(CFG_INT8, False, marks=pytest.mark.slow),
        (CFG_INT8, True),
    ],
    ids=["float-plain", "float-spec", "int8kv-plain", "int8kv-spec"],
)
def test_hibernate_restore_token_exact(config, spec):
    """The acceptance bar: session A publishes its prefix, hibernates
    (idle spill), is DEMOTED off the device pool by session B's admission
    (kv_pages=5 can't hold both), and A's next turn must (a) hit the radix
    on the host-tier entry, (b) restore it via the ONE warmed traced-index
    upload program, and (c) generate byte-identically to a tier-off run —
    the restore replaced a re-prefill, not the math."""
    kw = dict(speculation="auto" if spec else "off", speculation_tokens=3)
    cold_e = make_engine(config, tier=False, **kw)
    try:
        cold_a = cold_e.generate(PROMPT_A, GREEDY, timeout=120).tokens
        cold_b = cold_e.generate(PROMPT_B, GREEDY, timeout=120).tokens
    finally:
        cold_e.stop()

    engine = make_engine(config, **kw)
    try:
        a1 = engine.generate(PROMPT_A, GREEDY, timeout=120).tokens
        wait_spilled(engine, 2)  # A's 2-page prefix lands host-side
        b1 = engine.generate(PROMPT_B, GREEDY, timeout=120).tokens
        stats = engine.stats()
        assert stats["host-demotions-total"] >= 1, (
            "B's admission should have demoted A's hibernated prefix"
        )
        tiers = {e.tier for e in engine._prefix_index._live}
        assert "host" in tiers, f"no hibernated entry after demotion: {tiers}"
        a2 = engine.generate(PROMPT_A, GREEDY, timeout=120).tokens
        stats = engine.stats()
        assert a1 == cold_a and b1 == cold_b, "publishing runs diverged"
        assert a2 == cold_a, "post-hibernation turn diverged from cold run"
        assert stats["restored-hits-total"] == 1
        assert stats["restore-pages-total"] == 2
        assert stats["restore-failures-total"] == 0
        assert stats["recompute-fallbacks-total"] == 0
        # restore traffic is accounted in bytes of the POOL's dtype — int8
        # KV halves the per-page bytes, exactly like the device side
        tier = engine._host_tier
        assert stats["restore-bytes-total"] == 2 * tier.bytes_per_page
        assert stats["spill-bytes-total"] >= 2 * tier.bytes_per_page
        # ONE traced-index restore program, regardless of which physical
        # page was the destination (and it was warmed at precompile)
        restores = [s for s in engine._programs if s[0] == "page-restore"]
        assert len(restores) == 1, engine._programs
        # restore latency landed in its own histogram (added TTFT is the
        # tier's cost — it must be observable, docs/SERVING.md §16)
        hist = stats["histograms"]["engine_restore_s"]
        assert hist["count"] >= 1
        assert_leak_free(engine)
    finally:
        engine.stop()


@pytest.mark.slow  # two-engine e2e: runs in the chaos CI step
def test_constrained_slot_hibernate_restore_exact():
    """Constrained slots compose with hibernation: a session decoding
    under a json_schema grammar, hibernated and restored, must match the
    tier-off constrained run token-for-token (the grammar DFA is
    host-side slot state — hibernation only moves KV pages)."""
    tok = ByteTokenizer()
    rf = {"type": "json_schema", "json_schema": {"schema": {
        "type": "object",
        "properties": {"name": {"type": "string", "maxLength": 8}},
    }}}
    opts = GenerationOptions(
        max_new_tokens=24, temperature=0.0, response_format=rf
    )
    prompt = tok.encode("Return the JSON object for the user named Ada now")
    assert len(prompt) >= 33  # must clear the 32-token publish boundary
    kw = dict(grammar_tokenizer=tok, eos_token_id=tok.eos_token_id)
    cold_e = make_engine(CFG, tier=False, **kw)
    try:
        cold = cold_e.generate(list(prompt), opts, timeout=120).tokens
        cold_b = cold_e.generate(PROMPT_B, GREEDY, timeout=120).tokens
    finally:
        cold_e.stop()
    engine = make_engine(CFG, **kw)
    try:
        first = engine.generate(list(prompt), opts, timeout=120).tokens
        wait_spilled(engine, 2)
        b = engine.generate(PROMPT_B, GREEDY, timeout=120).tokens  # demotes
        again = engine.generate(list(prompt), opts, timeout=120).tokens
        stats = engine.stats()
        assert first == cold and again == cold and b == cold_b
        assert stats["restored-hits-total"] >= 1
        assert_leak_free(engine)
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# Chaos: the `spill` fault site (host-RAM rot)
# ---------------------------------------------------------------------------


def test_spill_fault_degrades_to_cold_prefill():
    """``spill@1`` corrupts one host-arena page of the FIRST restore's
    entry: the checksum must catch it, the victim admission must fall back
    to a cold re-prefill (token-exact — poisoned KV is never served), the
    poisoned entry must be dropped (not retried), survivors must restore
    cleanly afterwards, the engine must not restart, and neither free list
    may leak."""
    cold_e = make_engine(CFG, tier=False)
    try:
        cold_a = cold_e.generate(PROMPT_A, GREEDY, timeout=120).tokens
        cold_b = cold_e.generate(PROMPT_B, GREEDY, timeout=120).tokens
    finally:
        cold_e.stop()
    engine = make_engine(
        CFG, fault_injector=FaultInjector("spill@1", seed=0),
        # both sessions' prefixes must coexist host-side: A hibernated +
        # B hibernated (2 pages each) before the faulted restore
        host_kv_fraction=2.0,
    )
    try:
        a1 = engine.generate(PROMPT_A, GREEDY, timeout=120).tokens
        wait_spilled(engine, 2)
        b1 = engine.generate(PROMPT_B, GREEDY, timeout=120).tokens  # demotes A
        wait_spilled(engine, 4)  # B's prefix hibernates too
        # victim turn: restore of A fires the injector, checksum rejects,
        # admission recomputes cold — and must still be token-exact
        a2 = engine.generate(PROMPT_A, GREEDY, timeout=120).tokens
        stats = engine.stats()
        assert a2 == cold_a, "victim fell back but diverged — poisoned KV?"
        assert stats["restore-failures-total"] == 1
        assert stats["recompute-fallbacks-total"] >= 1
        assert stats["restored-hits-total"] == 0
        assert engine._injector.fired["spill"] == 1
        # survivor: B's hibernated session restores cleanly (the fault was
        # one-shot) and stays token-exact
        b2 = engine.generate(PROMPT_B, GREEDY, timeout=120).tokens
        stats = engine.stats()
        assert b2 == cold_b and a1 == cold_a and b1 == cold_b
        assert stats["restored-hits-total"] == 1
        assert stats["restore-failures-total"] == 1
        assert stats["engine-restarts-total"] == 0, "host rot must not restart"
        assert_leak_free(engine)
    finally:
        engine.stop()


def test_hibernation_churn_leak_free():
    """Sustained spill→demote→restore→free churn (both sessions cycling
    through hibernation repeatedly) ends with every device page and every
    arena slot back on its free list."""
    engine = make_engine(CFG)
    try:
        expected = {
            tuple(PROMPT_A): engine.generate(PROMPT_A, GREEDY, timeout=120).tokens,
        }
        wait_spilled(engine, 2)
        expected[tuple(PROMPT_B)] = engine.generate(
            PROMPT_B, GREEDY, timeout=120
        ).tokens
        for turn in range(3):
            for prompt in (PROMPT_A, PROMPT_B):
                got = engine.generate(prompt, GREEDY, timeout=120).tokens
                assert got == expected[tuple(prompt)], f"turn {turn} diverged"
        stats = engine.stats()
        assert stats["restored-hits-total"] >= 2, stats["restored-hits-total"]
        assert stats["spill-failures-total"] == 0
        # arena occupancy gauge tracks the tier's truth
        assert stats["host-pages-in-use"] == sum(
            len(e.host) for e in engine._prefix_index._live
        )
        assert_leak_free(engine)
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# Host arena + index units (no engine)
# ---------------------------------------------------------------------------


def _pool(config=CFG, num_pages=6):
    return PagePool(config, num_pages=num_pages, page_size=16, max_batch=2,
                    max_seq_len=64)


def test_host_tier_write_read_checksum_roundtrip():
    pool = _pool()
    tier = HostPageTier(pool.dev, 3)
    assert tier.free_slots == 3 and tier.slots_in_use == 0
    assert tier.bytes_per_page > 0
    assert tier.bytes_total == 3 * tier.bytes_per_page
    slots = tier.alloc(2)
    assert len(slots) == 2 and tier.free_slots == 1
    assert tier.alloc(2) is None, "over-allocation must fail, not wrap"
    # write one page worth of leaf blocks, read it back bit-exact
    rng = np.random.default_rng(0)
    blocks = [
        rng.standard_normal((a.shape[0],) + a.shape[2:]).astype(a.dtype)
        for a in tier._arrays
    ]
    tier.write(slots[0], blocks)
    got = tier.read(slots[0])
    assert got is not None
    for want, back in zip(blocks, jax.tree.leaves(got)):
        np.testing.assert_array_equal(want, back)
    # a slot nothing was written to has no checksum: unreadable by design
    assert tier.read(slots[1]) is None
    # corruption (one flipped byte anywhere) must fail the checksum
    tier.corrupt(slots[0])
    assert tier.read(slots[0]) is None, "corrupted page served as valid"
    tier.free(slots)
    assert tier.free_slots == 3
    # freeing dropped the checksum: a recycled slot can't serve stale KV
    s2 = tier.alloc(1)
    assert tier.read(s2[0]) is None
    tier.reset()
    assert tier.free_slots == 3


def test_index_demote_restore_semantics():
    """release_device_pages/attach_device_pages are exact inverses, the
    tier property tracks them, and evict_device_lru demotes (entry
    survives, hibernated) when the spill callback secures a host copy —
    and drops outright when it can't."""
    pool = _pool()
    index = PrefixPageIndex((16, 32, 64), max_entries=4)
    tier = HostPageTier(pool.dev, 4)
    index.host_tier = tier
    tok = [3 + i % 40 for i in range(40)]
    owned = pool._alloc(2)
    entry = index.insert(pool, tok, 32, tuple(owned))
    pool.decref(owned)  # the publishing slot frees; the index holds the ref
    assert entry.tier == "device"
    # simulate a completed spill
    entry.host = tuple(tier.alloc(2))
    index._note_tier(entry)
    assert entry.tier == "both"
    assert index.advertised(4) == [(entry.digest, 32, "both")]
    freed = index.release_device_pages(pool, entry)
    assert entry.tier == "host" and len(freed) == 2
    assert pool.free_pages == pool.num_pages
    assert index.advertised(4) == [(entry.digest, 32, "host")]
    # the hibernated entry still radix-hits (pages=() — the engine's cue
    # to restore rather than miss)
    assert index.candidates(tok + [1]) == [(32, entry)]
    pages = pool.alloc_pages(2)
    index.attach_device_pages(pool, entry, pages)
    assert entry.tier == "both" and entry.pages == tuple(pages)
    # demote-before-drop: with a host copy secured the LRU victim survives
    assert index.evict_device_lru(pool, spill_cb=lambda e: bool(e.host))
    assert entry.tier == "host" and index.demotions == 1
    assert index.live_entries == 1
    # nothing holding device pages is left to victimize
    assert index.evict_device_lru(pool, spill_cb=lambda e: False) is False
    index._drop(pool, entry)
    assert index.live_entries == 0
    assert tier.free_slots == 4 and pool.free_pages == pool.num_pages


def test_drop_mid_spill_defers_slot_free_to_drain():
    """An entry dropped while its copy is in flight must NOT free its
    arena slots synchronously (the worker still owns them) — the handle is
    cancelled and the engine's drain frees them. Mirrored by
    engine._drain_spills; here the index-side contract."""
    pool = _pool()
    index = PrefixPageIndex((16, 32), max_entries=2)
    tier = HostPageTier(pool.dev, 2)
    index.host_tier = tier

    class _Handle:
        cancelled = False

    tok = [5 + i % 30 for i in range(34)]
    entry = index.insert(pool, tok, 32, tuple(pool._alloc(2)))
    slots = tier.alloc(2)
    entry.spilling = _Handle()
    handle = entry.spilling
    index._drop(pool, entry)
    assert handle.cancelled and entry.dropped
    assert tier.free_slots == 0, "slots freed while the worker owned them"
    tier.free(slots)  # what _drain_spills does for a cancelled handle
    assert tier.free_slots == 2


def test_failed_spill_of_demoted_entry_drops_zombie():
    """An entry DEMOTED on the strength of an in-flight spill whose copy
    then fails holds neither device nor host pages: the drain must drop
    it (the session re-prefills next turn) — a zombie left in the trie
    would serve a later radix hit a zero-page 'restore' of KV that was
    never written."""
    from langstream_tpu.serving.engine import _Spill

    engine = make_engine(CFG)
    engine.stop()  # engine + spill threads quiesced: drive internals
    pool, index, tier = engine._pagepool, engine._prefix_index, engine._host_tier
    tok = [9 + i % 30 for i in range(34)]
    owned = pool._alloc(2)
    entry = index.insert(pool, tok, 32, tuple(owned))
    pool.decref(owned)
    slots = tier.alloc(2)
    handle = _Spill(entry, slots, [], engine._spill_gen)
    entry.spilling = handle
    index.release_device_pages(pool, entry)  # demoted mid-spill
    handle.error = RuntimeError("device_get failed")
    engine._spill_done.put(handle)
    engine._drain_spills()
    assert entry.dropped and index.live_entries == 0
    assert index.candidates(tok + [1]) == [], "zombie survived the drain"
    assert tier.free_slots == tier.num_pages
    assert pool.free_pages == pool.num_pages
    # belt-and-braces: _restore_entry refuses a zero-page entry outright
    owned = pool._alloc(2)
    entry2 = index.insert(pool, tok, 32, tuple(owned))
    pool.decref(owned)
    index.release_device_pages(pool, entry2)  # host=() zombie by hand
    assert not engine._restore_entry(entry2, 32)
    assert entry2.dropped and engine.stats()["restore-failures-total"] == 1


def test_idle_sweep_rotates_past_hot_head():
    """The spill deque is publish-ordered, not idle-ordered: a hot entry
    at the front (its last_used_t refreshed by every hit) must rotate to
    the back, not block hibernation of the idle entries behind it."""
    engine = make_engine(CFG, spill_idle_s=60.0)
    engine.stop()
    pool, index = engine._pagepool, engine._prefix_index
    tok_a = [1 + i % 20 for i in range(34)]
    tok_b = [2 + i % 25 for i in range(34)]
    entries = []
    for tok in (tok_a, tok_b):
        owned = pool._alloc(2)
        entries.append(index.insert(pool, tok, 32, tuple(owned)))
        pool.decref(owned)
    hot, idle = entries
    hot.last_used_t = time.monotonic()  # front of the deque, recently hit
    idle.last_used_t = time.monotonic() - 120.0
    engine._spill_candidates.clear()
    engine._spill_candidates.extend([hot, idle])
    engine._spill_tick()
    assert idle.spilling is not None, "idle entry starved behind hot head"
    assert hot.spilling is None
    assert hot in engine._spill_candidates, "hot entry must rotate, not drop"


# ---------------------------------------------------------------------------
# Gating, planning, hot-loop bound, observability schema
# ---------------------------------------------------------------------------


def test_spill_needs_prefix_index_and_paged_layout(caplog):
    """host-kv-fraction is an explicit ask: when its prerequisites are
    missing the engine must say so LOUDLY (the round-14 adapters
    precedent), never silently downgrade."""
    with caplog.at_level(logging.WARNING):
        engine = make_engine(
            CFG, tier=False, host_kv_fraction=2.0, prefix_cache="off",
        )
    try:
        assert not engine._spill_on and engine._host_tier is None
        assert engine.stats()["host-tier"] is False
        assert any("prefix index" in r.message for r in caplog.records)
    finally:
        engine.stop()
    with pytest.raises(ValueError):
        ServingEngine(CFG, PARAMS, kv_layout="paged", spill="sometimes")


def test_plan_host_spill_term():
    """The memory plan's host_spill_bytes term: host RAM, reported in the
    summary but EXCLUDED from the HBM total an over-committed config dies
    on; fraction × device-pool pages at the pool's per-page bytes."""
    from langstream_tpu.serving.memory import plan_serving_memory

    base = plan_serving_memory(
        CFG, 4, 128, kv_layout="paged", page_size=16, kv_pages=8,
    )
    tiered = plan_serving_memory(
        CFG, 4, 128, kv_layout="paged", page_size=16, kv_pages=8,
        host_kv_fraction=4.0,
    )
    assert base.host_spill_bytes == 0
    assert tiered.host_spill_bytes == 4 * base.page_pool_bytes
    assert tiered.total_bytes == base.total_bytes, (
        "host arena is RAM — it must not inflate the HBM total"
    )
    assert "host KV tier" in tiered.summary()
    assert "host KV tier" not in base.summary()
    # int8 KV halves the arena like it halves the pool
    tiered_int8 = plan_serving_memory(
        CFG_INT8, 4, 128, kv_layout="paged", page_size=16, kv_pages=8,
        host_kv_fraction=4.0,
    )
    assert tiered_int8.host_spill_bytes < tiered.host_spill_bytes


def test_spill_bookkeeping_within_hot_loop_bound():
    """ISSUE-11 acceptance: the round-11 ≤1% hot-loop overhead bound holds
    with spill ENABLED. The steady-state hot-loop cost of the tier is one
    _spill_tick per iteration (drain poll + deque check — the copies
    themselves run on the worker thread); measured best-of-5 against the
    same engine's measured decode step, amortized per step."""
    engine = make_engine(CFG, kv_pages=16)  # room for a 64-token decode
    try:
        for prompt in (PROMPT_A, PROMPT_B):
            engine.generate(
                prompt, GenerationOptions(max_new_tokens=64, temperature=0.0),
                timeout=300,
            )
        stats = engine.stats()
        step_s = stats["decode-step-ms"] / 1e3
        if step_s <= 0:
            step_s = stats["histograms"]["engine_decode_step_s"]["p50"]
        assert step_s > 0, "no decode step sample — cannot measure the bound"
    finally:
        engine.stop()
    # engine thread is dead: driving _spill_tick from here races nothing.
    # Candidates empty + done-queue empty = the steady state an idle-free
    # hot loop sees every iteration.
    assert engine._spill_on and not engine._spill_candidates
    per_tick = float("inf")
    for _ in range(5):
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            engine._spill_tick()
        per_tick = min(per_tick, (time.perf_counter() - t0) / n)
    per_step = per_tick / engine.decode_chunk
    ratio = per_step / step_s
    assert ratio <= 0.01, (
        f"spill bookkeeping {per_step * 1e6:.2f}us/step is "
        f"{ratio * 100:.2f}% of the {step_s * 1e3:.3f}ms decode step "
        "(bound: 1%)"
    )


def test_spill_stall_dump_reason_and_schema():
    """`spill-stall` is a legal flight-recorder reason; its dumps carry
    the restore timings in `extra`, record host-tier occupancy per
    iteration, and stay token-content-free like every reason."""
    from langstream_tpu.serving.observability import (
        DUMP_REASONS,
        validate_flight_dump,
    )

    assert "spill-stall" in DUMP_REASONS
    engine = make_engine(CFG)
    try:
        engine.generate(PROMPT_A, GREEDY, timeout=120)
        dump = engine._flight_dump(
            "spill-stall",
            extra={"restore-ms": 1234.5, "restore-pages": 2, "reuse-tokens": 32},
        )
        assert dump is not None and validate_flight_dump(dump)
        assert all("host_pages" in it for it in dump["iterations"])
        # redaction negative: token content in the extras must be rejected
        with pytest.raises(ValueError):
            validate_flight_dump({**dump, "extra": {"tokens": [1, 2, 3]}})
    finally:
        engine.stop()


# ---------------------------------------------------------------------------
# Review regressions: stale candidates, deferred-retry gauges, wedged worker
# ---------------------------------------------------------------------------


def test_paged_bind_skips_candidate_dropped_mid_loop():
    """A deeper candidate's restore can evict_for a SHALLOWER candidate out
    of the admission's already-materialized list. The dropped entry must
    read as a cold miss — before the fix its stale .pages aliased pages the
    free list had re-issued to another slot."""
    from langstream_tpu.serving.engine import GenerationRequest

    engine = make_engine(CFG)
    engine.stop()
    pool, index = engine._pagepool, engine._prefix_index
    owned = pool._alloc(2)
    entry = index.insert(pool, PROMPT_A, 32, tuple(owned))
    pool.decref(owned)
    stale_pages = entry.pages
    index._drop(pool, entry)
    # _drop clears the alias surface AND marks the entry
    assert entry.dropped and entry.pages == ()
    # _restore_entry refuses a dropped entry outright, gauges untouched
    assert engine._restore_entry(entry, 32) is False
    assert engine.stats()["restore-failures-total"] == 0
    # the loop-level belt: even a stale entry still carrying pages (the
    # pre-fix shape, only reachable through a list materialized before the
    # drop) must not serve as a hit
    entry.pages = stale_pages
    index.candidates = lambda prompt: [(32, entry)]
    req = GenerationRequest(prompt_tokens=list(PROMPT_A), options=GREEDY)
    reuse = engine._paged_bind(0, req)
    assert reuse == 0, "dropped candidate served as a warm hit"
    entry.pages = ()
    pool.free_slot(0)
    assert pool.free_pages == pool.num_pages


def test_deferred_retry_counts_tier_fallback_once():
    """A page-deferred admission re-runs _paged_bind every engine
    iteration; its failed-restore retries must not inflate
    restore-failures / recompute-fallbacks (THE tier health gauges) —
    each request counts its failures exactly once."""
    from langstream_tpu.serving.engine import GenerationRequest

    engine = make_engine(CFG)
    engine.stop()
    pool, index, tier = engine._pagepool, engine._prefix_index, engine._host_tier
    owned = pool._alloc(2)
    entry = index.insert(pool, PROMPT_A, 32, tuple(owned))
    pool.decref(owned)
    entry.host = tuple(tier.alloc(2))  # hibernated (no checksums needed:
    index.release_device_pages(pool, entry)  # restore fails before read)
    grabbed = pool._alloc(pool.free_pages)  # full pool, nothing evictable
    req = GenerationRequest(prompt_tokens=list(PROMPT_A), options=GREEDY)
    assert engine._paged_bind(0, req) is None  # defers
    assert engine.stats()["restore-failures-total"] == 1
    # a deferral is NOT a cold ending: the retry may restore, and one
    # request must never land on both sides of the health gauge
    assert engine.stats()["recompute-fallbacks-total"] == 0
    assert getattr(req, "_tier_fallback_counted", False)
    for _ in range(25):  # the deferred request's per-iteration retries
        assert engine._paged_bind(0, req) is None
    assert engine.stats()["restore-failures-total"] == 1, (
        "deferred retries inflated the restore-failure gauge"
    )
    assert engine.stats()["recompute-fallbacks-total"] == 0
    # pool frees up; the retry's restore still fails (arena slots carry
    # no checksummed copy) so the admission finally binds COLD — the one
    # and only recompute fallback is counted here, at bind time
    pool.decref(grabbed)
    assert engine._paged_bind(0, req) == 0
    assert engine.stats()["recompute-fallbacks-total"] == 1
    assert engine.stats()["restored-hits-total"] == 0
    pool.free_slot(0)


def test_spill_worker_stop_reports_wedged_thread():
    """stop() must return False — leaving alive() truthful — when the
    worker cannot drain within the timeout (wedged device fetch): crash
    recovery keys off this to abandon the arena instead of resetting it
    under a thread that may still write into it."""
    import queue as queue_mod
    import threading

    from langstream_tpu.serving.engine import _Spill, _SpillWorker

    gate = threading.Event()
    entered = threading.Event()

    class _StuckTier:
        def write(self, slot, leaves):
            entered.set()
            gate.wait()

    worker = _SpillWorker(_StuckTier(), queue_mod.SimpleQueue())
    worker.start()
    handle = _Spill(object(), [0], [np.zeros(2)], 0)
    worker.submit(handle)
    assert entered.wait(10.0)
    assert worker.stop(timeout=0.2) is False, "wedged join reported clean"
    assert worker.alive(), "thread forgotten while still running"
    gate.set()
    assert worker.stop(timeout=10.0) is True
    assert not worker.alive()


def test_entry_cap_never_evicts_hibernated_sessions():
    """The index entry cap bounds the DEVICE-resident working set only:
    hibernated sessions hold exclusive arena slots (the tier the operator
    sized for exactly this), so publish-pressure cap eviction must
    victimize the device LRU and never drop a restorable session."""
    pool = _pool(num_pages=6)
    index = PrefixPageIndex((16, 32), max_entries=2)
    tier = HostPageTier(pool.dev, 4)
    index.host_tier = tier
    hibernated = []
    for i in range(2):
        tok = [i + 1 + j % 20 for j in range(34)]
        owned = pool._alloc(2)
        entry = index.insert(pool, tok, 32, tuple(owned))
        pool.decref(owned)
        entry.host = tuple(tier.alloc(2))
        index.release_device_pages(pool, entry)
        hibernated.append(entry)
    device_entries = []
    for i in range(3):  # one past the cap: eviction must fire
        tok = [50 + i + j % 20 for j in range(34)]
        owned = pool._alloc(2)
        entry = index.insert(pool, tok, 32, tuple(owned))
        assert entry is not None, "publish blocked by hibernated entries"
        pool.decref(owned)
        device_entries.append(entry)
    assert all(not e.dropped for e in hibernated), (
        "cap eviction dropped a hibernated session with a paid-for arena copy"
    )
    assert device_entries[0].dropped, "device LRU should have made room"
    assert sum(1 for e in index._live if e.pages) <= 2
    assert tier.free_slots == 0  # both arena copies intact
    # the incrementally-maintained device-resident list never drifts
    assert sorted(map(id, index._dev_live)) == sorted(
        id(e) for e in index._live if e.pages
    )
    for e in list(index._live):
        index._drop(pool, e)
    assert not index._dev_live and pool.free_pages == pool.num_pages


def test_cap_eviction_demotes_spilled_victim():
    """A publish-cap victim whose host copy is already secured must DEMOTE
    (hibernate, restorable) — not be dropped with its paid-for arena copy,
    which only the never-spilled victim deserves."""
    pool = _pool(num_pages=6)
    index = PrefixPageIndex((16, 32), max_entries=1)
    tier = HostPageTier(pool.dev, 2)
    index.host_tier = tier
    tok = [1 + j % 20 for j in range(34)]
    owned = pool._alloc(2)
    spilled = index.insert(pool, tok, 32, tuple(owned))
    pool.decref(owned)
    spilled.host = tuple(tier.alloc(2))  # spill completed
    owned = pool._alloc(2)
    entry2 = index.insert(pool, [77 + j % 20 for j in range(34)], 32,
                          tuple(owned))
    assert entry2 is not None
    pool.decref(owned)
    assert not spilled.dropped, "cap eviction destroyed a hibernated session"
    assert spilled.tier == "host" and index.demotions == 1
    assert index.candidates(tok + [1]) == [(32, spilled)], "not restorable"
