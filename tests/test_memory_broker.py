"""In-memory broker semantics: partitioning, groups, contiguous-prefix commit
(reference KafkaConsumerWrapper manual offset bookkeeping tests)."""

from langstream_tpu.api.record import SimpleRecord
from langstream_tpu.api.topics import TopicOffsetPosition
from langstream_tpu.messaging.memory import MemoryBroker, MemoryTopicConnectionsRuntime


def test_publish_and_consume(run):
    async def main():
        broker = MemoryBroker.instance()
        rt = MemoryTopicConnectionsRuntime(broker)
        consumer = rt.create_consumer("agent-1", "t")
        await consumer.start()
        producer = rt.create_producer("agent-1", "t")
        await producer.start()
        for i in range(5):
            await producer.write(SimpleRecord.of(i))
        records = await consumer.read()
        assert [r.value for r in records] == [0, 1, 2, 3, 4]
        await consumer.commit(records)
        info = consumer.get_info()
        assert info["committed"]["0"] == 5

    run(main())


def test_contiguous_prefix_commit(run):
    async def main():
        broker = MemoryBroker.instance()
        rt = MemoryTopicConnectionsRuntime(broker)
        consumer = rt.create_consumer("a", "t")
        await consumer.start()
        producer = rt.create_producer("a", "t")
        for i in range(4):
            await producer.write(SimpleRecord.of(i))
        records = await consumer.read()
        # ack out of order: offsets 1,2 first — committed must stay 0
        await consumer.commit([records[1], records[2]])
        assert consumer.get_info()["committed"]["0"] == 0
        # ack offset 0 — committed jumps over the whole prefix to 3
        await consumer.commit([records[0]])
        assert consumer.get_info()["committed"]["0"] == 3
        await consumer.commit([records[3]])
        assert consumer.get_info()["committed"]["0"] == 4

    run(main())


def test_redelivery_after_restart(run):
    async def main():
        broker = MemoryBroker.instance()
        rt = MemoryTopicConnectionsRuntime(broker)
        consumer = rt.create_consumer("a", "t", {"group": "g"})
        await consumer.start()
        producer = rt.create_producer("a", "t")
        for i in range(3):
            await producer.write(SimpleRecord.of(i))
        records = await consumer.read()
        await consumer.commit([records[0]])  # only offset 0 committed
        await consumer.close()

        # new consumer in the same group resumes from committed offset 1
        consumer2 = rt.create_consumer("a", "t", {"group": "g"})
        await consumer2.start()
        redelivered = await consumer2.read()
        assert [r.value for r in redelivered] == [1, 2]

    run(main())


def test_keyed_records_same_partition(run):
    async def main():
        broker = MemoryBroker.instance()
        broker.create_topic("t", partitions=4)
        rt = MemoryTopicConnectionsRuntime(broker)
        producer = rt.create_producer("a", "t")
        for _ in range(8):
            await producer.write(SimpleRecord.of("v", key="same-key"))
        parts = {
            p
            for p, part in enumerate(broker.topics["t"].partitions)
            if part.records
        }
        assert len(parts) == 1

    run(main())


def test_group_partition_split(run):
    async def main():
        broker = MemoryBroker.instance()
        broker.create_topic("t", partitions=2)
        rt = MemoryTopicConnectionsRuntime(broker)
        c1 = rt.create_consumer("a", "t", {"group": "g"})
        c2 = rt.create_consumer("a", "t", {"group": "g"})
        await c1.start()
        await c2.start()
        assigned = sorted(c1._assigned + c2._assigned)
        assert assigned == [0, 1]
        assert len(c1._assigned) == 1 and len(c2._assigned) == 1

    run(main())


def test_reader_positions(run):
    async def main():
        broker = MemoryBroker.instance()
        rt = MemoryTopicConnectionsRuntime(broker)
        producer = rt.create_producer("a", "t")
        for i in range(3):
            await producer.write(SimpleRecord.of(i))

        earliest = rt.create_reader("t", TopicOffsetPosition(position="earliest"))
        await earliest.start()
        res = await earliest.read()
        assert [r.value for r in res.records] == [0, 1, 2]

        latest = rt.create_reader("t", TopicOffsetPosition(position="latest"))
        await latest.start()
        await producer.write(SimpleRecord.of(99))
        res = await latest.read()
        assert [r.value for r in res.records] == [99]

    run(main())
