#!/bin/sh
# Real-chip serving bench (one JSON line; ~3-6 min incl. compiles).
cd "$(dirname "$0")/.."
exec python bench.py
