"""Instrument the engine loop: where does wall time go at steady state?

Monkeypatches dispatch/fetch/process points with timestamps and prints a
phase summary after a llama-3-8b B=96 run.
"""

from __future__ import annotations

import sys
import time

sys.argv = ["x"]

import numpy as np

events: list[tuple[str, float, float, int]] = []  # (kind, t0, dt, steps)


def main() -> None:
    import jax

    from langstream_tpu.serving import engine as eng

    orig_dev_decode = eng.ServingEngine._dev_decode
    orig_dev_prefill = eng.ServingEngine._dev_prefill
    orig_process = eng.ServingEngine._process_entry

    def dev_decode(self, steps, stale, kv_bound=None):
        t0 = time.monotonic()
        out = orig_dev_decode(self, steps, stale, kv_bound)
        events.append((f"dispatch-b{kv_bound}-st{len(stale)}", t0, time.monotonic() - t0, steps))
        return out

    def dev_prefill(self, width, *a):
        t0 = time.monotonic()
        out = orig_dev_prefill(self, width, *a)
        events.append(("prefill", t0, time.monotonic() - t0, width))
        return out

    def process(self, entry):
        t0 = time.monotonic()
        out = orig_process(self, entry)
        events.append((f"proc-{entry[0]}", t0, time.monotonic() - t0, 0))
        return out

    eng.ServingEngine._dev_decode = dev_decode
    eng.ServingEngine._dev_prefill = dev_prefill
    eng.ServingEngine._process_entry = process

    from bench import bench_engine

    t = bench_engine(
        "llama-3-8b", True, max_batch=96, new_tokens=128, n_requests=192,
        max_seq_len=1024, decode_chunk=16, kv_int8=True,
    )
    print(f"tok/s={t:.0f}", flush=True)

    # summarize from the last prefill onward minus warmup (first 20 events)
    ev = events[10:]
    t_start, t_end = ev[0][1], max(e[1] + e[2] for e in ev)
    span = t_end - t_start
    by_kind: dict[str, float] = {}
    counts: dict[str, int] = {}
    for kind, _, dt, _ in ev:
        by_kind[kind] = by_kind.get(kind, 0.0) + dt
        counts[kind] = counts.get(kind, 0) + 1
    print(f"span={span:.2f}s", flush=True)
    for k in sorted(by_kind):
        print(f"  {k}: total={by_kind[k]:.2f}s n={counts[k]} avg={by_kind[k]/counts[k]*1e3:.1f}ms")
    acc = span - sum(by_kind.values())
    print(f"  (loop other/idle: {acc:.2f}s)")
    print("  slowest events:")
    for kind, t0, dt, steps in sorted(ev, key=lambda e: -e[2])[:8]:
        print(f"    {kind} at t+{t0-t_start:.2f}s: {dt*1e3:.0f}ms (steps={steps})")
    # dispatch gap histogram: time between consecutive dispatch STARTS
    disp = [e for e in ev if e[0].startswith("dispatch")]
    gaps = [b[1] - (a[1]) for a, b in zip(disp, disp[1:])]
    if gaps:
        print(
            f"  dispatch-start gaps: mean={np.mean(gaps)*1e3:.1f}ms "
            f"p50={np.percentile(gaps,50)*1e3:.1f} p90={np.percentile(gaps,90)*1e3:.1f} "
            f"max={max(gaps)*1e3:.1f} n={len(gaps)}"
        )
        steps = [d[3] for d in disp]
        print(f"  chunk steps: {dict((s, steps.count(s)) for s in set(steps))}")


if __name__ == "__main__":
    main()
