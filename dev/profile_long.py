"""Profile the chunked-prefill (long-context) path segment by segment.

Replays exactly what engine._long_step dispatches for a 32k llama-3.1-8b
prompt (int8 weights + int8 KV): 16 segments of 2048 through
_prefill_segment_and_sample with the pow2 kv_bound ladder. Prints
per-segment wall time (warm, forced fetch) and the attention kernel's
share, so the 32k TTFT (19.0s in BENCH_r04 vs a ~4-6s roofline) can be
attributed.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="llama-3.1-8b")
    p.add_argument("--prompt-len", type=int, default=32000)
    p.add_argument("--segment", type=int, default=2048)
    p.add_argument("--max-seq", type=int, default=32768)
    p.add_argument("--attn-only", action="store_true")
    args = p.parse_args()

    from langstream_tpu.models.configs import MODEL_PRESETS
    from langstream_tpu.models.quant import init_random_quantized_params
    from langstream_tpu.models.transformer import make_kv_cache
    from langstream_tpu.serving.engine import _prefill_segment_and_sample

    config = MODEL_PRESETS[args.preset]
    config = dataclasses.replace(config, kv_cache_dtype="int8")
    params = init_random_quantized_params(config, jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    width = args.segment
    prompt_len = args.prompt_len
    t_long = width
    while t_long < prompt_len:
        t_long *= 2
    t_long = min(t_long, args.max_seq)

    if args.attn_only:
        attn_only(config, width, t_long)
        return

    rng = np.random.default_rng(0)
    n_seg = -(-prompt_len // width)

    def run_pass(label: str) -> None:
        cache = make_kv_cache(config, 1, t_long)
        key = jax.random.PRNGKey(0)
        total = 0.0
        for seg in range(n_seg):
            s0 = seg * width
            seg_len = min(width, prompt_len - s0)
            kv_bound = width
            while kv_bound < min(s0 + width, t_long):
                kv_bound *= 2
            kv_bound = min(kv_bound, t_long)
            tokens = rng.integers(1, config.vocab_size, size=(1, width)).astype(np.int32)
            t0 = time.monotonic()
            first, cache, key = _prefill_segment_and_sample(
                params, jnp.asarray(tokens), jnp.asarray([s0], jnp.int32),
                jnp.asarray([seg_len], jnp.int32), cache, key,
                jnp.asarray([0.0], jnp.float32), jnp.asarray([0], jnp.int32),
                jnp.asarray([1.0], jnp.float32), config, kv_bound,
            )
            _ = np.asarray(jax.device_get(first))  # force completion
            dt = time.monotonic() - t0
            total += dt
            print(
                f"  [{label}] seg {seg:2d} s0={s0:6d} kv_bound={kv_bound:6d}: "
                f"{dt*1e3:7.1f}ms",
                flush=True,
            )
        print(f"[{label}] total={total:.2f}s over {n_seg} segments", flush=True)

    run_pass("cold")  # includes compiles
    run_pass("warm")


def attn_only(config, width: int, t_long: int) -> None:
    """Time flash_segment_attention alone at a late-segment shape."""
    from langstream_tpu.ops.attention import flash_segment_attention

    b, h, hkv, d = 1, config.n_heads, config.n_kv_heads, config.resolved_head_dim
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, width, h, d), jnp.bfloat16)
    k = jax.random.normal(key, (b, hkv, t_long, d), jnp.bfloat16)
    v = jax.random.normal(key, (b, hkv, t_long, d), jnp.bfloat16)
    offset = jnp.asarray([t_long - width], jnp.int32)

    import os

    bq = int(os.environ.get("BQ", "512"))
    bk = int(os.environ.get("BK", "512"))
    fn = jax.jit(
        lambda q, k, v, o: flash_segment_attention(
            q, k, v, o, config, block_q=bq, block_k=bk
        )
    )
    out = fn(q, k, v, offset)
    _ = np.asarray(jax.device_get(out[0, 0, :4]))
    n = 5
    t0 = time.monotonic()
    for _ in range(n):
        out = fn(q, k, v, offset)
    _ = np.asarray(jax.device_get(out[0, 0, :4]))
    dt = (time.monotonic() - t0) / n
    flops = 2 * 2 * width * (t_long - width // 2) * h * d  # QK + PV, causal avg
    print(
        f"attn-only width={width} t={t_long}: {dt*1e3:.1f}ms "
        f"≈{flops/dt/1e12:.1f} TFLOPS effective",
        flush=True,
    )


if __name__ == "__main__":
    sys.exit(main())
