"""Diagnose the r5 gateway TTFT stall: run the bench gateway phase with an
engine-side event timeline (admissions, dispatches, fetches, first-token
deliveries) and print where the 16s goes.

Usage: python dev/exp_gateway_ttft.py [n_sessions] [prefill_batch]
"""

import asyncio
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

EVENTS: list[tuple[float, str]] = []
T0 = time.monotonic()


def mark(what: str) -> None:
    EVENTS.append((time.monotonic() - T0, what))


def instrument() -> None:
    from langstream_tpu.serving import engine as e

    orig_admit = e.ServingEngine._admit
    orig_dev_decode = e.ServingEngine._dev_decode
    orig_process = e.ServingEngine._process_chunk
    orig_warm = e.ServingEngine._warmup_decode_ladder

    def admit(self, budget=None):
        t = time.monotonic()
        out = orig_admit(self, budget)
        if out:
            mark(
                f"admit n={len(out)} budget={budget} "
                f"took={time.monotonic() - t:.3f}s"
            )
        return out

    def dev_decode(self, steps, stale, kv_bound=None):
        t = time.monotonic()
        out = orig_dev_decode(self, steps, stale, kv_bound)
        dt = time.monotonic() - t
        if dt > 0.05:
            mark(f"dev_decode steps={steps} bound={kv_bound} dispatch_took={dt:.3f}s")
        return out

    def process(self, chunk, snapshot, steps):
        t = time.monotonic()
        out = orig_process(self, chunk, snapshot, steps)
        dt = time.monotonic() - t
        if dt > 0.05:
            mark(f"process_chunk steps={steps} rows={len(snapshot)} took={dt:.3f}s")
        return out

    def warm(self):
        t = time.monotonic()
        orig_warm(self)
        mark(f"warmup_decode_ladder took={time.monotonic() - t:.3f}s")

    e.ServingEngine._admit = admit
    e.ServingEngine._dev_decode = dev_decode
    e.ServingEngine._process_chunk = process
    e.ServingEngine._warmup_decode_ladder = warm


def main() -> None:
    n_sessions = int(sys.argv[1]) if len(sys.argv) > 1 else 96
    prefill_batch = int(sys.argv[2]) if len(sys.argv) > 2 else 192
    instrument()

    import bench

    # also mark every websocket first token
    orig_chat = bench._chat_once

    async def chat(http, server, session_id, timeout=300.0):
        out = await orig_chat(http, server, session_id, timeout)
        mark(f"session {session_id} ttft={out[0]:.3f}s")
        return out

    bench._chat_once = chat

    mark("start")
    extras = asyncio.run(
        bench.bench_gateway(
            "gemma-2b", True, 192, 128, n_sessions, 1024, 16, prefill_batch
        )
    )
    mark("done")
    print("\n=== timeline (events >50ms or structural) ===")
    for t, what in EVENTS:
        print(f"{t:9.3f}  {what}")
    print("\n=== extras ===")
    print(extras)


if __name__ == "__main__":
    main()
