"""Decode-path profiling: isolate device compute from engine overhead.

Times (a) one fused decode chunk on-device with block_until_ready, at
several batch sizes and chunk lengths, (b) prefill, (c) device_put /
fetch costs — to find where the engine's 800 tok/s (vs ~8k roofline)
actually goes. Run on the real chip: `python dev/profile_decode.py`.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from langstream_tpu.models.configs import MODEL_PRESETS
    from langstream_tpu.models.transformer import init_params, make_kv_cache
    from langstream_tpu.serving.engine import _decode_chunk

    config = MODEL_PRESETS["gemma-2b"]
    print("backend:", jax.default_backend())
    params = init_params(config, jax.random.PRNGKey(0))
    jax.block_until_ready(params)

    max_seq = 1024
    for batch in (32, 64):
        cache = make_kv_cache(config, batch, max_seq)
        tokens = jnp.ones(batch, jnp.int32)
        positions = jnp.full(batch, 40, jnp.int32)
        key = jax.random.PRNGKey(0)
        temp = jnp.zeros(batch, jnp.float32)
        top_k = jnp.zeros(batch, jnp.int32)
        top_p = jnp.ones(batch, jnp.float32)
        for steps in (8, 32):
            # compile
            chunk, tokens, positions, cache, key = _decode_chunk(
                params, tokens, positions, cache, key, temp, top_k, top_p, steps, config
            )
            jax.block_until_ready(chunk)
            n_iter = 6
            t0 = time.monotonic()
            for _ in range(n_iter):
                chunk, tokens, positions, cache, key = _decode_chunk(
                    params, tokens, positions, cache, key, temp, top_k, top_p, steps, config
                )
            jax.block_until_ready(chunk)
            dt = (time.monotonic() - t0) / n_iter
            per_step_ms = dt / steps * 1e3
            toks = batch * steps / dt
            print(
                f"B={batch} steps={steps}: chunk={dt*1e3:.1f}ms "
                f"per-step={per_step_ms:.2f}ms device-tok/s={toks:.0f}"
            )

        # dispatch-only latency: time to enqueue without waiting
        t0 = time.monotonic()
        chunk, tokens, positions, cache, key = _decode_chunk(
            params, tokens, positions, cache, key, temp, top_k, top_p, 32, config
        )
        t1 = time.monotonic()
        jax.block_until_ready(chunk)
        t2 = time.monotonic()
        print(f"B={batch}: dispatch={((t1-t0))*1e3:.1f}ms wait={(t2-t1)*1e3:.1f}ms")

        # fetch latency for the chunk tokens
        t0 = time.monotonic()
        np.asarray(jax.device_get(chunk))
        print(f"B={batch}: device_get(chunk)={(time.monotonic()-t0)*1e3:.1f}ms")


if __name__ == "__main__":
    main()
