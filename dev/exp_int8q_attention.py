"""Experiment: int8×int8 decode attention for the BF16 cache path.

The last untaken device lever on PERF.md's list: with an int8 KV cache the
attention dots already run int8×int8→int32 on the MXU (quantized q, scales
hoisted onto the scores). With a BF16 cache the dots run in bf16 — this
experiment measures whether quantizing q per-vector (cheap) and k per-token
ON THE FLY (the cache READ stays bf16 — no bandwidth saving, this is purely
an MXU-rate play) beats the shipped bf16 einsum at decode shapes, and what
it costs in logit error.

Run on the serving chip before shipping any knob; the CPU numbers only
establish the overhead floor (CPU has no int8 matmul advantage, so the
quantize work is pure loss there — recorded in PERF.md round 9 either way).

    JAX_PLATFORMS=cpu python dev/exp_int8q_attention.py
"""

from __future__ import annotations

import functools
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp
import numpy as np

from langstream_tpu.models.configs import MODEL_PRESETS
from langstream_tpu.models.transformer import _quantize_kv, attention


@functools.partial(jax.jit, static_argnames=("config",))
def bf16_decode_attention(q, k, v, mask, config):
    """The shipped path: bf16 q @ bf16 cache, fp32 softmax."""
    return attention(q, k, v, mask, config)


@functools.partial(jax.jit, static_argnames=("config",))
def int8q_decode_attention(q, k, v, mask, config):
    """Variant: quantize q per-vector and k per-token in-register, dot in
    int8×int8→int32, scales applied on the [.., T]-shaped scores (the same
    hoisting the int8-cache path uses); probs·V re-quantized per-row the
    same way. HBM traffic unchanged (the cache is read bf16 first)."""
    h, hkv = config.n_heads, config.n_kv_heads
    group = h // hkv
    b, s, _, d = q.shape
    qg = q.reshape(b, s, hkv, group, d)
    qq, qs = _quantize_kv(qg)
    kq, ks = _quantize_kv(k)  # per-token, on the fly
    scores = jnp.einsum(
        "bshgd,bhtd->bhgst", qq, kq, preferred_element_type=jnp.int32
    ).astype(jnp.float32)
    scores = scores * qs.transpose(0, 2, 3, 1)[:, :, :, :, None]
    scores = scores * ks[:, :, None, None, :]
    scores = scores / jnp.sqrt(jnp.float32(d))
    scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    vq, vs = _quantize_kv(v)
    pv = probs * vs[:, :, None, None, :]
    pq, ps = _quantize_kv(pv)
    out = jnp.einsum(
        "bhgst,bhtd->bshgd", pq, vq, preferred_element_type=jnp.int32
    ).astype(jnp.float32)
    out = (out * ps.transpose(0, 3, 1, 2)[..., None]).astype(q.dtype)
    return out.reshape(b, s, h * d)


def bench(fn, *args, iters=30):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def main() -> None:
    config = MODEL_PRESETS["llama-3-8b-shallow"]  # GQA kv=8, the case that matters
    on_tpu = jax.default_backend() == "tpu"
    b, t = (96, 1024) if on_tpu else (16, 512)
    d = config.resolved_head_dim
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, 1, config.n_heads, d)), dtype)
    k = jnp.asarray(rng.normal(size=(b, config.n_kv_heads, t, d)), dtype)
    v = jnp.asarray(rng.normal(size=(b, config.n_kv_heads, t, d)), dtype)
    lengths = rng.integers(32, t, size=b)
    mask = jnp.asarray(np.arange(t)[None, None, :] < lengths[:, None, None])

    t_bf16, out_bf16 = bench(bf16_decode_attention, q, k, v, mask, config)
    t_int8, out_int8 = bench(int8q_decode_attention, q, k, v, mask, config)
    err = float(
        jnp.max(jnp.abs(out_bf16.astype(jnp.float32) - out_int8.astype(jnp.float32)))
    )
    scale = float(jnp.max(jnp.abs(out_bf16.astype(jnp.float32))))
    print(
        f"backend={jax.default_backend()} B={b} T={t} kv={config.n_kv_heads} "
        f"D={d} dtype={dtype.__name__}"
    )
    print(f"bf16 path:      {t_bf16 * 1e3:8.3f} ms")
    print(f"int8q path:     {t_int8 * 1e3:8.3f} ms  ({t_bf16 / t_int8:.2f}x)")
    print(f"max |Δout| {err:.4g} (max |out| {scale:.4g})")
    verdict = "WINS — consider an opt-in knob" if t_int8 < t_bf16 else "LOSES — no knob"
    print(f"verdict on this backend: int8q {verdict}")


if __name__ == "__main__":
    main()
