#!/bin/sh
# Whole platform in one process against the north-star example
# (reference dev/* local-run loops).
set -e
cd "$(dirname "$0")/.."
exec python -m langstream_tpu.cli run local examples/applications/tpu-completions \
    -i examples/instances/local-memory.yaml "$@"
