"""Experiment: does the in-place layer scan kill the decode-scan cache
double-buffer (VERDICT r4 weak #4)?

Compares the current chunk form (decode_step: layer scan consumes cache as
xs, stacks fresh ys) against decode_step_inplace (carry + DUS) inside the
same steps-scan, reporting peak HBM and step time per batch size.

Usage: python dev/exp_decode_buffer.py [--preset llama-3-8b] [--batches 48,64,80]
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def mem_stats():
    d = jax.devices()[0]
    try:
        s = d.memory_stats()
        return s.get("peak_bytes_in_use", 0), s.get("bytes_in_use", 0)
    except Exception:
        return 0, 0


def make_chunk_fn(body_step, config, steps, kv_bound=None):
    from langstream_tpu.serving.sampling import sample

    @functools.partial(jax.jit, donate_argnames=("cache",))
    def chunk(params, tokens, positions, cache, key, temp, top_k, top_p):
        def body(carry, _):
            tokens, positions, cache, key = carry
            logits, cache = body_step(params, tokens, positions, cache, config, kv_bound=kv_bound)
            key, sub = jax.random.split(key)
            nxt = sample(logits, sub, temp, top_k, top_p)
            return (nxt, positions + 1, cache, key), nxt

        (tokens, positions, cache, key), out = lax.scan(
            body, (tokens, positions, cache, key), None, length=steps
        )
        return out, tokens, positions, cache, key

    return chunk


def run(preset: str, batch: int, steps: int, variant: str, seq_len: int) -> None:
    from langstream_tpu.models.configs import MODEL_PRESETS
    from langstream_tpu.models.quant import init_random_quantized_params
    from langstream_tpu.models.transformer import (
        decode_step,
        decode_step_inplace,
        make_kv_cache,
    )

    config = MODEL_PRESETS[preset]
    config = dataclasses.replace(
        config, kv_cache_dtype="int8", attention_impl=args.attn_impl
    )
    params = init_random_quantized_params(config, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    base_peak, base_now = mem_stats()

    cache = make_kv_cache(config, batch, seq_len)
    tokens = jnp.ones(batch, jnp.int32)
    positions = jnp.full(batch, args.positions, jnp.int32)
    key = jax.random.PRNGKey(0)
    temp = jnp.zeros(batch, jnp.float32)
    top_k = jnp.zeros(batch, jnp.int32)
    top_p = jnp.ones(batch, jnp.float32)

    step = decode_step_inplace if variant == "inplace" else (
        lambda p, t, po, c, cf, kv_bound=None: decode_step(p, t, po, c, cf)
    )
    fn = make_chunk_fn(step, config, steps, kv_bound=args.kv_bound)

    t0 = time.monotonic()
    out, tokens, positions, cache, key = fn(
        params, tokens, positions, cache, key, temp, top_k, top_p
    )
    first = float(np.asarray(jax.device_get(out[-1, 0])))
    compile_s = time.monotonic() - t0

    # timed: 3 chained chunks, forced fetch at the end (tunnel: block_until_ready lies)
    n_chunks = 3
    t0 = time.monotonic()
    for _ in range(n_chunks):
        out, tokens, positions, cache, key = fn(
            params, tokens, positions, cache, key, temp, top_k, top_p
        )
    _ = float(np.asarray(jax.device_get(out[-1, 0])))
    dt = time.monotonic() - t0
    peak, now = mem_stats()
    toks = batch * steps * n_chunks
    print(
        f"RESULT variant={variant} preset={preset} B={batch} steps={steps} "
        f"compile={compile_s:.1f}s time={dt*1e3:.0f}ms tok/s={toks/dt:.0f} "
        f"ms/step={dt*1e3/(steps*n_chunks):.2f} "
        f"peak_gib={peak/2**30:.2f} now_gib={now/2**30:.2f} "
        f"base_now_gib={base_now/2**30:.2f} (first_tok={first})",
        flush=True,
    )


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="llama-3-8b")
    p.add_argument("--batches", default="48")
    p.add_argument("--steps", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--variant", default="inplace", choices=["inplace", "scan", "both"])
    p.add_argument("--kv-bound", type=int, default=None)
    p.add_argument("--attn-impl", default="auto")
    p.add_argument("--positions", type=int, default=32)
    args = p.parse_args()
    variants = ["scan", "inplace"] if args.variant == "both" else [args.variant]
    for b in [int(x) for x in args.batches.split(",")]:
        for v in variants:
            try:
                run(args.preset, b, args.steps, v, args.seq_len)
            except Exception as e:  # noqa: BLE001
                print(f"RESULT variant={v} B={b} FAILED: {type(e).__name__}: {str(e)[:200]}", flush=True)
                sys.exit(0)  # OOM poisons the runtime; bail and rerun per-B
