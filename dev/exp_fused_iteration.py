"""Measure the two candidate shapes of a fused prefill–decode iteration:

  A. back-to-back async dispatches — the admission/segment program followed
     immediately by the decode-chunk program (what the engine ships): two
     dispatch overheads per iteration, ZERO new compiled programs (both
     halves are already in the warmed set).
  B. single fused program — one jit tracing the SAME two halves (the
     prefill segment forward and the decode-chunk scan) as one XLA
     program: one dispatch, but a NEW program per (steps, kv_bound,
     segment width) combination — i.e. the warm set multiplies
     {ladder} × {buckets}, and every novel combo is a 15-23s compile
     through the tunneled chip. (A deeper fusion — prefill and decode
     ROWS sharing one attention call — would build on
     ops.attention.fused_segment_decode_attention, exactness-tested but
     not used here.)

On an in-order device stream both shapes execute the same work in the same
order; the measurable difference is per-iteration dispatch overhead (~1.7ms
per dispatch through the tunnel, ~µs locally) vs the compile-surface
multiplication. Run on the target chip to confirm the PERF.md round-6
decision; on CPU it reports the dispatch-overhead delta only.

Usage: python dev/exp_fused_iteration.py [iters]
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from langstream_tpu.models.configs import MODEL_PRESETS
    from langstream_tpu.models.transformer import (
        init_params,
        make_kv_cache,
        prefill_segment,
    )
    from langstream_tpu.serving.engine import _decode_chunk

    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    config = MODEL_PRESETS["gemma-2b" if on_tpu else "tiny-test"]
    b, t, w, steps = (96, 512, 64, 16) if on_tpu else (4, 128, 32, 4)
    params = init_params(config, jax.random.PRNGKey(0))
    cache = make_kv_cache(config, b, t)
    local = make_kv_cache(config, 1, w)
    tokens = jnp.ones(b, jnp.int32)
    positions = jnp.full(b, 40, jnp.int32)
    temp = jnp.zeros(b, jnp.float32)
    top_k = jnp.zeros(b, jnp.int32)
    top_p = jnp.ones(b, jnp.float32)
    seg = jnp.ones((1, w), jnp.int32)
    key = jax.random.PRNGKey(1)
    kv_bound = 64

    def back_to_back(cache, local, key):
        # dispatch 1: one prefill segment (stands in for admit_group too)
        _, local = prefill_segment(
            params, seg, jnp.zeros(1, jnp.int32), jnp.full(1, w, jnp.int32),
            local, config,
        )
        # dispatch 2: the decode chunk — queued behind dispatch 1 on the
        # in-order stream without any host sync between them
        chunk, *_, cache, key = _decode_chunk(
            params, tokens, positions, cache, key, temp, top_k, top_p,
            steps, config, kv_bound,
        )
        return cache, local, key, chunk

    fused_one = jax.jit(
        lambda cache, local, key: back_to_back(cache, local, key),
        donate_argnums=(0, 1),
    )

    for name, fn in (("back-to-back", back_to_back), ("single-program", fused_one)):
        c = make_kv_cache(config, b, t)
        l = make_kv_cache(config, 1, w)
        k = jax.random.PRNGKey(1)
        c, l, k, chunk = fn(c, l, k)  # compile
        jax.block_until_ready(chunk)
        t0 = time.monotonic()
        for _ in range(iters):
            c, l, k, chunk = fn(c, l, k)
        jax.block_until_ready(chunk)
        dt = (time.monotonic() - t0) / iters
        print(f"{name}: {dt * 1e3:.2f} ms/iteration")


if __name__ == "__main__":
    main()
