"""Protocol-level fake Pulsar broker for tests (the `kafka_fake.py` pattern).

Speaks the binary-protocol subset the client in ``pulsar.py`` does —
CONNECT/CONNECTED, PRODUCER, SEND (payload frames, crc32c verified),
SUBSCRIBE (shared + exclusive, durable + non-durable), FLOW permits, MESSAGE
delivery, individual + cumulative ACK, SEEK, CLOSE_*, PARTITIONED_METADATA,
GET_LAST_MESSAGE_ID, PING/PONG — over a real asyncio socket, plus the admin
REST surface (``/admin/v2/persistent/...``) on an aiohttp server.

Broker semantics modelled:
- one ledger (id 0) per topic; entry_id is the append index
- a SHARED subscription round-robins undelivered entries among its
  consumers, honoring per-consumer FLOW permits (this is what splits work
  across agent replicas — the fake must get it right for the contract
  tests)
- a durable subscription's ack state survives consumer disconnects;
  in-flight (delivered, unacked) entries return to the pool when their
  consumer goes away, so redelivery-on-crash is exercised for real
- SEEK positions the cursor AFTER the given entry (matching the runtime's
  resume convention: the stored offset is the last-read message)

This stands in for the reference's testcontainers Pulsar in an image with
no JVM and no network egress.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from langstream_tpu.messaging import pulsar_protocol as wire

log = logging.getLogger(__name__)


@dataclass
class _ConsumerRef:
    conn: "_Conn"
    consumer_id: int
    permits: int = 0


@dataclass
class _Subscription:
    name: str
    sub_type: int = 1  # shared
    durable: bool = True
    acked: set = field(default_factory=set)
    in_flight: dict = field(default_factory=dict)  # entry_id → _ConsumerRef
    consumers: list = field(default_factory=list)
    rr: int = 0


@dataclass
class _Topic:
    entries: list = field(default_factory=list)  # (metadata bytes, payload)
    subscriptions: dict = field(default_factory=dict)
    producer_seq: int = 0


class _Conn:
    def __init__(self, broker: "FakePulsarBroker", writer: asyncio.StreamWriter) -> None:
        self.broker = broker
        self.writer = writer
        self.lock = asyncio.Lock()
        self.producers: dict[int, str] = {}  # producer_id → topic
        self.consumers: dict[int, tuple[str, str]] = {}  # consumer_id → (topic, sub)

    async def send(self, command: bytes, metadata: bytes = b"", payload: bytes = b"") -> None:
        data = (
            wire.payload_frame(command, metadata, payload)
            if metadata
            else wire.frame(command)
        )
        async with self.lock:
            self.writer.write(data)
            await self.writer.drain()


class FakePulsarBroker:
    """Single-node fake: binary protocol + admin REST, tenant/ns agnostic."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.admin_port = 0
        self.topics: dict[str, _Topic] = {}
        self.partitioned: dict[str, int] = {}  # base topic → partition count
        # multi-broker ownership: data topics listed here are answered with a
        # lookup REDIRECT to the given service_url instead of "connect here"
        self.lookup_redirects: dict[str, str] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._admin_runner: Any = None
        self._conns: set[_Conn] = set()
        self._producer_names = 0

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "FakePulsarBroker":
        self._server = await asyncio.start_server(self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        await self._start_admin()
        return self

    async def _start_admin(self) -> None:
        from aiohttp import web

        app = web.Application()
        app.add_routes(
            [
                web.get("/admin/v2/persistent/{tenant}/{ns}", self._admin_list),
                web.put(
                    "/admin/v2/persistent/{tenant}/{ns}/{topic}/partitions",
                    self._admin_create_partitioned,
                ),
                web.delete(
                    "/admin/v2/persistent/{tenant}/{ns}/{topic}/partitions",
                    self._admin_delete_partitioned,
                ),
                web.get(
                    "/admin/v2/persistent/{tenant}/{ns}/{topic}/partitions",
                    self._admin_get_partitions,
                ),
                web.put("/admin/v2/persistent/{tenant}/{ns}/{topic}", self._admin_create),
                web.delete(
                    "/admin/v2/persistent/{tenant}/{ns}/{topic}", self._admin_delete
                ),
            ]
        )
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, self.host, 0)
        await site.start()
        self.admin_port = site._server.sockets[0].getsockname()[1]
        self._admin_runner = runner

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for conn in list(self._conns):
                conn.writer.close()
            await self._server.wait_closed()
            self._server = None
        if self._admin_runner is not None:
            await self._admin_runner.cleanup()
            self._admin_runner = None

    @property
    def service_url(self) -> str:
        return f"pulsar://{self.host}:{self.port}"

    @property
    def admin_url(self) -> str:
        return f"http://{self.host}:{self.admin_port}"

    # -- admin REST ----------------------------------------------------------

    def _full(self, request) -> str:
        return (
            f"persistent://{request.match_info['tenant']}/"
            f"{request.match_info['ns']}/{request.match_info['topic']}"
        )

    async def _admin_list(self, request):
        from aiohttp import web

        prefix = f"persistent://{request.match_info['tenant']}/{request.match_info['ns']}/"
        names = sorted(
            set(
                [t for t in self.topics if t.startswith(prefix)]
                + [t for t in self.partitioned if t.startswith(prefix)]
            )
        )
        return web.json_response(names)

    async def _admin_create(self, request):
        from aiohttp import web

        full = self._full(request)
        if full in self.topics:
            return web.Response(status=409)
        self.topics[full] = _Topic()
        return web.Response(status=204)

    async def _admin_create_partitioned(self, request):
        from aiohttp import web

        full = self._full(request)
        if full in self.partitioned:
            return web.Response(status=409)
        n = int((await request.read()) or b"1")
        self.partitioned[full] = n
        for i in range(n):
            self.topics.setdefault(f"{full}-partition-{i}", _Topic())
        return web.Response(status=204)

    async def _admin_get_partitions(self, request):
        from aiohttp import web

        return web.json_response(
            {"partitions": self.partitioned.get(self._full(request), 0)}
        )

    async def _admin_delete(self, request):
        from aiohttp import web

        return web.Response(
            status=204 if self.topics.pop(self._full(request), None) else 404
        )

    async def _admin_delete_partitioned(self, request):
        from aiohttp import web

        full = self._full(request)
        n = self.partitioned.pop(full, None)
        if n is None:
            return web.Response(status=404)
        for i in range(n):
            self.topics.pop(f"{full}-partition-{i}", None)
        return web.Response(status=204)

    # -- binary protocol -----------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn = _Conn(self, writer)
        self._conns.add(conn)
        try:
            while True:
                header = await reader.readexactly(4)
                total = int.from_bytes(header, "big")
                body = await reader.readexactly(total)
                name, fields, metadata, payload = wire.split_frame(body)
                handler = getattr(self, f"_on_{name}", None)
                if handler is None:
                    log.warning("fake pulsar: unhandled command %s", name)
                    continue
                await handler(conn, fields, metadata, payload)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._conns.discard(conn)
            # consumer crash semantics: their unacked in-flight entries return
            # to the pool and get redelivered to surviving consumers
            for consumer_id in list(conn.consumers):
                await self._drop_consumer(conn, consumer_id)
            writer.close()

    async def _drop_consumer(self, conn: _Conn, consumer_id: int) -> None:
        entry = conn.consumers.pop(consumer_id, None)
        if entry is None:
            return
        topic_name, sub_name = entry
        topic = self.topics.get(topic_name)
        if topic is None:
            return
        sub = topic.subscriptions.get(sub_name)
        if sub is None:
            return
        sub.consumers = [
            c for c in sub.consumers
            if not (c.conn is conn and c.consumer_id == consumer_id)
        ]
        returned = [
            e
            for e, ref in sub.in_flight.items()
            if ref.conn is conn and ref.consumer_id == consumer_id
        ]
        for e in returned:
            del sub.in_flight[e]
        if not sub.durable and not sub.consumers:
            topic.subscriptions.pop(sub_name, None)
        elif returned:
            await self._pump(topic_name, sub)

    def _topic(self, name: str) -> _Topic:
        t = self.topics.get(name)
        if t is None:  # auto-create (broker default)
            t = _Topic()
            self.topics[name] = t
        return t

    async def _on_connect(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        await conn.send(
            wire.encode_command(
                "connected",
                {
                    "server_version": "fake-pulsar",
                    "protocol_version": wire.PROTOCOL_VERSION,
                    "max_message_size": 5 * 1024 * 1024,
                },
            )
        )

    async def _on_ping(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        await conn.send(wire.encode_command("pong", {}))

    async def _on_pong(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        pass

    async def _on_partitioned_metadata(
        self, conn: _Conn, fields: dict, metadata, payload
    ) -> None:
        await conn.send(
            wire.encode_command(
                "partitioned_metadata_response",
                {
                    "partitions": self.partitioned.get(fields["topic"], 0),
                    "request_id": fields["request_id"],
                    "response": 0,
                },
            )
        )

    async def _on_lookup(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        owner = self.lookup_redirects.get(fields["topic"])
        await conn.send(
            wire.encode_command(
                "lookup_response",
                {
                    "broker_service_url": owner or self.service_url,
                    "response": 0 if owner else 1,  # 0 redirect, 1 connect
                    "request_id": fields["request_id"],
                    "authoritative": 1,
                },
            )
        )

    async def _on_producer(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        self._producer_names += 1
        producer_id = int(fields["producer_id"])
        conn.producers[producer_id] = fields["topic"]
        self._topic(fields["topic"])
        await conn.send(
            wire.encode_command(
                "producer_success",
                {
                    "request_id": fields["request_id"],
                    "producer_name": fields.get(
                        "producer_name", f"fake-producer-{self._producer_names}"
                    ),
                    "last_sequence_id": -1,
                },
            )
        )

    async def _on_close_producer(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        conn.producers.pop(int(fields["producer_id"]), None)
        await conn.send(
            wire.encode_command("success", {"request_id": fields["request_id"]})
        )

    async def _on_send(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        producer_id = int(fields["producer_id"])
        topic_name = conn.producers.get(producer_id)
        if topic_name is None:
            await conn.send(
                wire.encode_command(
                    "send_error",
                    {
                        "producer_id": producer_id,
                        "sequence_id": fields["sequence_id"],
                        "error": 0,
                        "message": "unknown producer",
                    },
                )
            )
            return
        topic = self._topic(topic_name)
        entry_id = len(topic.entries)
        # store the re-encoded metadata verbatim so consumers get the same
        # properties/partition_key/publish_time the producer sent
        topic.entries.append(
            (wire.encode_message(wire.MESSAGE_METADATA, metadata or {}), payload)
        )
        await conn.send(
            wire.encode_command(
                "send_receipt",
                {
                    "producer_id": producer_id,
                    "sequence_id": fields["sequence_id"],
                    "message_id": {"ledger_id": 0, "entry_id": entry_id},
                },
            )
        )
        for sub in topic.subscriptions.values():
            await self._pump(topic_name, sub)

    async def _on_subscribe(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        topic_name = fields["topic"]
        topic = self._topic(topic_name)
        sub_name = fields["subscription"]
        durable = bool(fields.get("durable", 1))
        sub = topic.subscriptions.get(sub_name)
        if sub is None:
            sub = _Subscription(
                name=sub_name,
                sub_type=int(fields.get("sub_type", 1)),
                durable=durable,
            )
            if int(fields.get("initial_position", 0)) == 0:  # latest
                sub.acked = set(range(len(topic.entries)))
            topic.subscriptions[sub_name] = sub
        consumer_id = int(fields["consumer_id"])
        sub.consumers.append(_ConsumerRef(conn, consumer_id))
        conn.consumers[consumer_id] = (topic_name, sub_name)
        await conn.send(
            wire.encode_command("success", {"request_id": fields["request_id"]})
        )

    async def _on_close_consumer(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        await self._drop_consumer(conn, int(fields["consumer_id"]))
        await conn.send(
            wire.encode_command("success", {"request_id": fields["request_id"]})
        )

    async def _on_unsubscribe(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        consumer_id = int(fields["consumer_id"])
        entry = conn.consumers.get(consumer_id)
        if entry is not None:
            topic = self.topics.get(entry[0])
            if topic is not None:
                topic.subscriptions.pop(entry[1], None)
        await self._drop_consumer(conn, consumer_id)
        await conn.send(
            wire.encode_command("success", {"request_id": fields["request_id"]})
        )

    async def _on_flow(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        consumer_id = int(fields["consumer_id"])
        entry = conn.consumers.get(consumer_id)
        if entry is None:
            return
        topic_name, sub_name = entry
        topic = self.topics.get(topic_name)
        sub = topic.subscriptions.get(sub_name) if topic else None
        if sub is None:
            return
        for ref in sub.consumers:
            if ref.conn is conn and ref.consumer_id == consumer_id:
                ref.permits += int(fields["message_permits"])
        await self._pump(topic_name, sub)

    async def _on_ack(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        consumer_id = int(fields["consumer_id"])
        entry = conn.consumers.get(consumer_id)
        if entry is None:
            return
        topic_name, sub_name = entry
        topic = self.topics.get(topic_name)
        sub = topic.subscriptions.get(sub_name) if topic else None
        if sub is None:
            return
        mids = fields.get("message_id", [])
        if not isinstance(mids, list):
            mids = [mids]
        cumulative = int(fields.get("ack_type", 0)) == 1
        for mid in mids:
            entry_id = int(mid.get("entry_id", 0))
            if cumulative:
                for e in range(entry_id + 1):
                    sub.acked.add(e)
                    sub.in_flight.pop(e, None)
            else:
                sub.acked.add(entry_id)
                sub.in_flight.pop(entry_id, None)

    async def _on_seek(self, conn: _Conn, fields: dict, metadata, payload) -> None:
        consumer_id = int(fields["consumer_id"])
        entry = conn.consumers.get(consumer_id)
        if entry is not None:
            topic_name, sub_name = entry
            topic = self.topics.get(topic_name)
            sub = topic.subscriptions.get(sub_name) if topic else None
            if sub is not None:
                seek_entry = int(fields.get("message_id", {}).get("entry_id", -1))
                # cursor lands AFTER the seeked entry (resume convention)
                sub.acked = set(range(seek_entry + 1))
                sub.in_flight.clear()
                await self._pump(topic_name, sub)
        await conn.send(
            wire.encode_command("success", {"request_id": fields["request_id"]})
        )

    async def _on_get_last_message_id(
        self, conn: _Conn, fields: dict, metadata, payload
    ) -> None:
        consumer_id = int(fields["consumer_id"])
        entry = conn.consumers.get(consumer_id)
        last = -1
        if entry is not None:
            topic = self.topics.get(entry[0])
            if topic is not None:
                last = len(topic.entries) - 1
        await conn.send(
            wire.encode_command(
                "get_last_message_id_response",
                {
                    "last_message_id": {"ledger_id": 0, "entry_id": last},
                    "request_id": fields["request_id"],
                },
            )
        )

    # -- delivery ------------------------------------------------------------

    async def _pump(self, topic_name: str, sub: _Subscription) -> None:
        """Deliver every available entry to consumers with permits.

        Shared subscription: round-robin across consumers. Exclusive: only
        the first consumer receives."""
        topic = self.topics.get(topic_name)
        if topic is None or not sub.consumers:
            return
        for entry_id in range(len(topic.entries)):
            if entry_id in sub.acked or entry_id in sub.in_flight:
                continue
            ref = self._next_consumer(sub)
            if ref is None:
                return  # no permits anywhere — wait for FLOW
            metadata_bytes, payload = topic.entries[entry_id]
            sub.in_flight[entry_id] = ref
            ref.permits -= 1
            try:
                await ref.conn.send(
                    wire.encode_command(
                        "message",
                        {
                            "consumer_id": ref.consumer_id,
                            "message_id": {"ledger_id": 0, "entry_id": entry_id},
                        },
                    ),
                    metadata_bytes,
                    payload,
                )
            except (ConnectionError, RuntimeError):
                del sub.in_flight[entry_id]
                return

    def _next_consumer(self, sub: _Subscription) -> Optional[_ConsumerRef]:
        if not sub.consumers:
            return None
        if sub.sub_type == 0:  # exclusive
            ref = sub.consumers[0]
            return ref if ref.permits > 0 else None
        n = len(sub.consumers)
        for i in range(n):
            ref = sub.consumers[(sub.rr + i) % n]
            if ref.permits > 0:
                sub.rr = (sub.rr + i + 1) % n
                return ref
        return None
