"""In-memory broker: partitioned topics, consumer groups, ordered offset commit.

This is the reference implementation of the Topic SPI, mirroring the Kafka
semantics the framework depends on (reference `langstream-kafka-runtime/`):

- partitioned topics, records keyed → partition by hash (KafkaProducerWrapper);
- consumer groups with partition assignment + rebalance redelivery
  (KafkaConsumerWrapper.java:82-115);
- **manual ordered commit**: consumers track acked offsets out of order but the
  committed offset only advances over the contiguous prefix
  (KafkaConsumerWrapper.java:41-115,159-190 — `uncommittedOffsets` TreeSet);
- dead-letter convention: `<topic>-deadletter` (AgentRunner.java:282-284).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from langstream_tpu.api.record import Header, Record
from langstream_tpu.native import OffsetTracker, key_partition
from langstream_tpu.api.topics import (
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffsetPosition,
    TopicProducer,
    TopicReader,
    TopicReadResult,
)


@dataclass(frozen=True)
class ConsumedRecord:
    """A record as read from a topic — carries its provenance for commit."""

    value: Any
    key: Any
    headers: tuple[Header, ...]
    origin: str  # topic name
    timestamp: Optional[float]
    partition: int
    offset: int


@dataclass
class _Partition:
    records: list[ConsumedRecord] = field(default_factory=list)

    def append(self, topic: str, partition: int, record: Record) -> ConsumedRecord:
        stored = ConsumedRecord(
            value=record.value,
            key=record.key,
            headers=tuple(record.headers),
            origin=topic,
            timestamp=record.timestamp if record.timestamp is not None else time.time(),
            partition=partition,
            offset=len(self.records),
        )
        self.records.append(stored)
        return stored


@dataclass
class _Topic:
    name: str
    partitions: list[_Partition]
    # committed offset per (group, partition): next offset to deliver on restart
    committed: dict[tuple[str, int], int] = field(default_factory=dict)


class MemoryBroker:
    """One broker instance ≈ one streaming cluster. Async-safe within a loop."""

    _instances: dict[str, "MemoryBroker"] = {}

    def __init__(self) -> None:
        self.topics: dict[str, _Topic] = {}
        self._consumers: dict[str, list["MemoryTopicConsumer"]] = {}
        self._waiters: list[asyncio.Event] = []

    @classmethod
    def instance(cls, name: str = "default") -> "MemoryBroker":
        broker = cls._instances.get(name)
        if broker is None:
            broker = cls()
            cls._instances[name] = broker
        return broker

    @classmethod
    def reset(cls, name: Optional[str] = None) -> None:
        if name is None:
            cls._instances.clear()
        else:
            cls._instances.pop(name, None)

    # -- admin --------------------------------------------------------------

    def create_topic(self, name: str, partitions: int = 1) -> _Topic:
        if name not in self.topics:
            self.topics[name] = _Topic(
                name=name, partitions=[_Partition() for _ in range(max(partitions, 1))]
            )
        return self.topics[name]

    def delete_topic(self, name: str) -> None:
        self.topics.pop(name, None)

    def topic_exists(self, name: str) -> bool:
        return name in self.topics

    def _get_or_create(self, name: str) -> _Topic:
        return self.create_topic(name)

    # -- produce ------------------------------------------------------------

    def publish(self, topic_name: str, record: Record) -> ConsumedRecord:
        topic = self._get_or_create(topic_name)
        n = len(topic.partitions)
        if record.key is not None:
            part = key_partition(record.key, n)
        else:
            part = getattr(self, "_rr", 0) % n
            self._rr = part + 1
        stored = topic.partitions[part].append(topic_name, part, record)
        self._notify()
        return stored

    def _notify(self) -> None:
        for ev in self._waiters:
            ev.set()

    async def wait_for_data(self, timeout: float) -> None:
        ev = asyncio.Event()
        self._waiters.append(ev)
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            self._waiters.remove(ev)

    # -- consumer group management ------------------------------------------

    def join_group(self, group: str, consumer: "MemoryTopicConsumer") -> None:
        members = self._consumers.setdefault(group, [])
        members.append(consumer)
        self._rebalance(group)

    def leave_group(self, group: str, consumer: "MemoryTopicConsumer") -> None:
        members = self._consumers.get(group, [])
        if consumer in members:
            members.remove(consumer)
        self._rebalance(group)

    def _rebalance(self, group: str) -> None:
        """Round-robin partition assignment; reassigned consumers restart from
        the committed offset (rebalance redelivery, KafkaConsumerWrapper:82)."""
        members = self._consumers.get(group, [])
        if not members:
            return
        by_topic: dict[str, list[MemoryTopicConsumer]] = {}
        for c in members:
            by_topic.setdefault(c.topic_name, []).append(c)
        for topic_name, consumers in by_topic.items():
            topic = self._get_or_create(topic_name)
            for c in consumers:
                c._assigned.clear()
            for part, consumer in zip(
                range(len(topic.partitions)), itertools.cycle(consumers)
            ):
                consumer._assigned.append(part)
            for c in consumers:
                c._reset_to_committed()


class MemoryTopicConsumer(TopicConsumer):
    def __init__(
        self,
        broker: MemoryBroker,
        topic: str,
        group: str,
        poll_timeout: float = 0.1,
        max_records: int = 100,
    ) -> None:
        self.broker = broker
        self.topic_name = topic
        self.group = group
        self.poll_timeout = poll_timeout
        self.max_records = max_records
        self._assigned: list[int] = []
        self._fetch_pos: dict[int, int] = {}
        # contiguous-prefix commit bookkeeping per partition (C++ fast path
        # when the native extension is built; langstream_tpu.native)
        self._trackers: dict[int, OffsetTracker] = {}
        self._total_out = 0
        self._started = False

    async def start(self) -> None:
        self.broker._get_or_create(self.topic_name)
        self.broker.join_group(self.group, self)
        self._started = True

    async def close(self) -> None:
        if self._started:
            self.broker.leave_group(self.group, self)
            self._started = False

    def _reset_to_committed(self) -> None:
        topic = self.broker._get_or_create(self.topic_name)
        self._fetch_pos = {
            p: topic.committed.get((self.group, p), 0) for p in self._assigned
        }
        self._trackers = {
            p: OffsetTracker(topic.committed.get((self.group, p), 0))
            for p in self._assigned
        }

    async def read(self) -> list[Record]:
        out = self._poll()
        if not out:
            await self.broker.wait_for_data(self.poll_timeout)
            out = self._poll()
        self._total_out += len(out)
        return out

    def _poll(self) -> list[Record]:
        topic = self.broker._get_or_create(self.topic_name)
        out: list[Record] = []
        for p in self._assigned:
            pos = self._fetch_pos.get(p, 0)
            records = topic.partitions[p].records
            while pos < len(records) and len(out) < self.max_records:
                out.append(records[pos])
                pos += 1
            self._fetch_pos[p] = pos
        return out

    async def commit(self, records: list[Record]) -> None:
        """Ack records; advance the committed offset over contiguous prefixes
        only (the TreeSet logic of KafkaConsumerWrapper.commit:159-190)."""
        topic = self.broker._get_or_create(self.topic_name)
        for r in records:
            if not isinstance(r, ConsumedRecord):
                continue
            tracker = self._trackers.get(r.partition)
            if tracker is None:
                tracker = OffsetTracker(topic.committed.get((self.group, r.partition), 0))
                self._trackers[r.partition] = tracker
            topic.committed[(self.group, r.partition)] = tracker.ack(r.offset)

    def get_info(self) -> dict[str, Any]:
        topic = self.broker._get_or_create(self.topic_name)
        return {
            "topic": self.topic_name,
            "group": self.group,
            "assigned-partitions": list(self._assigned),
            "committed": {
                str(p): topic.committed.get((self.group, p), 0) for p in self._assigned
            },
        }

    @property
    def total_out(self) -> int:
        return self._total_out


class MemoryTopicProducer(TopicProducer):
    def __init__(self, broker: MemoryBroker, topic: str) -> None:
        self.broker = broker
        self.topic_name = topic
        self._total_in = 0

    async def start(self) -> None:
        self.broker._get_or_create(self.topic_name)

    async def write(self, record: Record) -> None:
        self.broker.publish(self.topic_name, record)
        self._total_in += 1

    @property
    def total_in(self) -> int:
        return self._total_in


class MemoryTopicReader(TopicReader):
    """Offset-addressed reader (gateway consume path — no group)."""

    def __init__(
        self,
        broker: MemoryBroker,
        topic: str,
        initial: TopicOffsetPosition,
        poll_timeout: float = 0.1,
    ) -> None:
        self.broker = broker
        self.topic_name = topic
        self.initial = initial
        self.poll_timeout = poll_timeout
        self._pos: dict[int, int] = {}

    async def start(self) -> None:
        topic = self.broker._get_or_create(self.topic_name)
        for p, part in enumerate(topic.partitions):
            if self.initial.position == TopicOffsetPosition.EARLIEST:
                self._pos[p] = 0
            elif self.initial.position == "absolute":
                self._pos[p] = self.initial.offsets.get(p, 0)
            else:  # latest
                self._pos[p] = len(part.records)

    def _poll(self) -> tuple[list[Record], list[dict[int, int]]]:
        topic = self.broker._get_or_create(self.topic_name)
        out: list[Record] = []
        offsets: list[dict[int, int]] = []
        for p, part in enumerate(topic.partitions):
            pos = self._pos.get(p, 0)
            while pos < len(part.records):
                out.append(part.records[pos])
                pos += 1
                resume = dict(self._pos)
                resume[p] = pos
                offsets.append(resume)
            self._pos[p] = pos
        return out, offsets

    async def read(self) -> TopicReadResult:
        out, offsets = self._poll()
        if not out:
            await self.broker.wait_for_data(self.poll_timeout)
            out, offsets = self._poll()
        return TopicReadResult(out, dict(self._pos), record_offsets=offsets)


class MemoryTopicAdmin(TopicAdmin):
    def __init__(self, broker: MemoryBroker) -> None:
        self.broker = broker

    async def create_topic(self, name: str, partitions: int = 1, options: Optional[dict] = None) -> None:
        self.broker.create_topic(name, partitions)

    async def delete_topic(self, name: str) -> None:
        self.broker.delete_topic(name)

    async def topic_exists(self, name: str) -> bool:
        return self.broker.topic_exists(name)


class MemoryTopicConnectionsRuntime(TopicConnectionsRuntime):
    def __init__(self, broker: Optional[MemoryBroker] = None) -> None:
        self.broker = broker if broker is not None else MemoryBroker.instance()

    async def init(self, streaming_cluster_config: dict[str, Any]) -> None:
        name = streaming_cluster_config.get("broker", "default")
        self.broker = MemoryBroker.instance(name)

    def create_consumer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicConsumer:
        config = config or {}
        return MemoryTopicConsumer(
            self.broker,
            topic,
            group=config.get("group", agent_id),
            poll_timeout=float(config.get("poll-timeout", 0.1)),
            max_records=int(config.get("max-records", 100)),
        )

    def create_producer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicProducer:
        return MemoryTopicProducer(self.broker, topic)

    def create_reader(
        self,
        topic: str,
        initial_position: TopicOffsetPosition = TopicOffsetPosition(),
        config: Optional[dict[str, Any]] = None,
    ) -> TopicReader:
        return MemoryTopicReader(self.broker, topic, initial_position)

    def create_topic_admin(self) -> TopicAdmin:
        return MemoryTopicAdmin(self.broker)
