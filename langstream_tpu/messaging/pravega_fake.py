"""Protocol-level fake Pravega: segment store (TCP, pravega_protocol codec)
plus controller REST (aiohttp) — the kafka_fake/pulsar_fake pattern.

Semantics modelled:
- segments are append-only byte logs; AppendBlockEnd appends atomically and
  acks with DataAppended (event_number echo, previous number tracked per
  writer), duplicate event numbers from the same writer are idempotently
  dropped (pravega's exactly-once append contract)
- ReadSegment returns bytes from an offset (bounded by suggested_length),
  with at_tail/end_of_segment flags
- controller REST: scope/stream CRUD with FIXED_NUM_SEGMENTS scaling,
  sealed-before-delete enforcement

Stands in for the reference's testcontainers Pravega (no JVM, no egress).
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Any, Optional

from langstream_tpu.messaging import pravega_protocol as wire

log = logging.getLogger(__name__)


@dataclass
class _Segment:
    data: bytearray = field(default_factory=bytearray)
    sealed: bool = False
    start_offset: int = 0  # truncation frontier: bytes below are gone
    # writer_id → last event number appended (idempotent replay guard)
    writers: dict = field(default_factory=dict)


class FakePravega:
    def __init__(self, host: str = "127.0.0.1") -> None:
        self.host = host
        self.port = 0
        self.rest_port = 0
        self.segments: dict[str, _Segment] = {}
        self.scopes: set[str] = set()
        self.streams: dict[str, dict] = {}  # "scope/stream" → config doc
        self._server: Optional[asyncio.base_events.Server] = None
        self._rest_runner: Any = None

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "FakePravega":
        self._server = await asyncio.start_server(self._serve, self.host, 0)
        self.port = self._server.sockets[0].getsockname()[1]

        from aiohttp import web

        app = web.Application()
        app.router.add_post("/v1/scopes", self._rest_create_scope)
        app.router.add_post("/v1/scopes/{scope}/streams", self._rest_create_stream)
        app.router.add_get("/v1/scopes/{scope}/streams/{stream}", self._rest_get_stream)
        app.router.add_put(
            "/v1/scopes/{scope}/streams/{stream}/state", self._rest_update_state
        )
        app.router.add_delete(
            "/v1/scopes/{scope}/streams/{stream}", self._rest_delete_stream
        )
        self._rest_runner = web.AppRunner(app)
        await self._rest_runner.setup()
        site = web.TCPSite(self._rest_runner, self.host, 0)
        await site.start()
        self.rest_port = site._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._rest_runner is not None:
            await self._rest_runner.cleanup()
            self._rest_runner = None

    @property
    def segment_store_url(self) -> str:
        return f"tcp://{self.host}:{self.port}"

    @property
    def controller_url(self) -> str:
        return f"http://{self.host}:{self.rest_port}"

    # -- controller REST ----------------------------------------------------

    async def _rest_create_scope(self, request):
        from aiohttp import web

        doc = await request.json()
        name = doc.get("scopeName", "")
        if name in self.scopes:
            return web.json_response({"scopeName": name}, status=409)
        self.scopes.add(name)
        return web.json_response({"scopeName": name}, status=201)

    async def _rest_create_stream(self, request):
        from aiohttp import web

        scope = request.match_info["scope"]
        doc = await request.json()
        stream = doc.get("streamName", "")
        key = f"{scope}/{stream}"
        if scope not in self.scopes:
            return web.json_response({"message": "no such scope"}, status=404)
        if key in self.streams:
            return web.json_response(self.streams[key], status=409)
        self.streams[key] = {
            "streamName": stream,
            "scopeName": scope,
            "scalingPolicy": doc.get(
                "scalingPolicy", {"type": "FIXED_NUM_SEGMENTS", "minSegments": 1}
            ),
            "state": "ACTIVE",
        }
        return web.json_response(self.streams[key], status=201)

    async def _rest_get_stream(self, request):
        from aiohttp import web

        key = f"{request.match_info['scope']}/{request.match_info['stream']}"
        doc = self.streams.get(key)
        if doc is None:
            return web.json_response({"message": "not found"}, status=404)
        return web.json_response(doc)

    async def _rest_update_state(self, request):
        from aiohttp import web

        key = f"{request.match_info['scope']}/{request.match_info['stream']}"
        doc = self.streams.get(key)
        if doc is None:
            return web.json_response({"message": "not found"}, status=404)
        body = await request.json()
        doc["state"] = body.get("streamState", doc["state"])
        if doc["state"] == "SEALED":
            for name, seg in self.segments.items():
                if name.startswith(key + "/"):
                    seg.sealed = True
        return web.json_response({"streamState": doc["state"]})

    async def _rest_delete_stream(self, request):
        from aiohttp import web

        key = f"{request.match_info['scope']}/{request.match_info['stream']}"
        doc = self.streams.get(key)
        if doc is None:
            return web.json_response({"message": "not found"}, status=404)
        if doc["state"] != "SEALED":
            return web.json_response({"message": "stream not sealed"}, status=412)
        del self.streams[key]
        for name in [n for n in self.segments if n.startswith(key + "/")]:
            del self.segments[name]
        return web.Response(status=204)

    # -- segment store ------------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        lock = asyncio.Lock()
        # per-CONNECTION writer routing (a real store's AppendProcessor):
        # SetupAppend binds a writer to a segment ON THIS SOCKET; appends
        # from a writer the connection never set up are rejected, which is
        # what forces clients to re-setup after a reconnect. (The DEDUP
        # state — last event number per writer — lives on the segment, as
        # real segment attributes do.)
        setups: dict = {}  # writer_id → segment name

        async def send(frame_bytes: bytes) -> None:
            async with lock:
                writer.write(frame_bytes)
                await writer.drain()

        try:
            while True:
                header = await reader.readexactly(8)
                type_, length = wire.parse_frame_header(header)
                payload = await reader.readexactly(length)
                name, f = wire.decode(type_, payload)
                handler = getattr(self, f"_on_{name}", None)
                if handler is None:
                    await send(wire.encode("error_message", {
                        "request_id": f.get("request_id", -1),
                        "message": f"unhandled {name}",
                    }))
                    continue
                f["_conn_setups"] = setups
                reply = await handler(f)
                if reply is not None:
                    await send(reply)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def _on_hello(self, f: dict) -> bytes:
        return wire.encode("hello", {})

    async def _on_keep_alive(self, f: dict) -> Optional[bytes]:
        return wire.encode("keep_alive", {})

    async def _on_create_segment(self, f: dict) -> bytes:
        name = f["segment"]
        if name in self.segments:
            return wire.encode("error_message", {
                "request_id": f["request_id"], "message": "segment exists",
            })
        self.segments[name] = _Segment()
        return wire.encode("segment_created", {
            "request_id": f["request_id"], "segment": name,
        })

    async def _on_setup_append(self, f: dict) -> bytes:
        seg = self.segments.get(f["segment"])
        if seg is None:
            return wire.encode("no_such_segment", {
                "request_id": f["request_id"], "segment": f["segment"],
            })
        f["_conn_setups"][f["writer_id"]] = f["segment"]
        last = seg.writers.setdefault(f["writer_id"], 0)
        return wire.encode("append_setup", {
            "request_id": f["request_id"],
            "segment": f["segment"],
            "writer_id": f["writer_id"],
            "last_event_number": last,
        })

    async def _on_append_block_end(self, f: dict) -> bytes:
        writer_id = f["writer_id"]
        # routing comes from THIS connection's setups, not global state
        name = f["_conn_setups"].get(writer_id)
        seg = self.segments.get(name) if name is not None else None
        if seg is None:
            return wire.encode("error_message", {
                "request_id": f["request_id"], "message": "writer not set up",
            })
        previous = seg.writers[writer_id]
        event_number = f["last_event_number"]
        if event_number > previous:  # idempotent: replays are dropped
            if seg.sealed:
                return wire.encode("error_message", {
                    "request_id": f["request_id"], "message": "segment sealed",
                })
            seg.data.extend(f["data"])
            seg.writers[writer_id] = event_number
        return wire.encode("data_appended", {
            "writer_id": writer_id,
            "event_number": event_number,
            "previous_event_number": previous,
            "request_id": f["request_id"],
        })

    async def _on_read_segment(self, f: dict) -> bytes:
        seg = self.segments.get(f["segment"])
        if seg is None:
            return wire.encode("no_such_segment", {
                "request_id": f["request_id"], "segment": f["segment"],
            })
        # reads below the truncation frontier resume AT the frontier; the
        # echoed offset tells the client where the returned bytes start
        offset = max(f["offset"], seg.start_offset)
        chunk = bytes(seg.data[offset : offset + f["suggested_length"]])
        at_tail = offset + len(chunk) >= len(seg.data)
        return wire.encode("segment_read", {
            "segment": f["segment"],
            "offset": offset,
            "at_tail": at_tail,
            "end_of_segment": seg.sealed and at_tail,
            "data": chunk,
            "request_id": f["request_id"],
        })

    async def _on_get_stream_segment_info(self, f: dict) -> bytes:
        seg = self.segments.get(f["segment"])
        return wire.encode("stream_segment_info", {
            "request_id": f["request_id"],
            "segment": f["segment"],
            "exists": seg is not None,
            "sealed": seg.sealed if seg else False,
            "write_offset": len(seg.data) if seg else 0,
            "start_offset": 0,
        })

    async def _on_delete_segment(self, f: dict) -> bytes:
        self.segments.pop(f["segment"], None)
        return wire.encode("segment_deleted", {
            "request_id": f["request_id"], "segment": f["segment"],
        })

    async def _on_truncate_segment(self, f: dict) -> bytes:
        seg = self.segments.get(f["segment"])
        if seg is None:
            return wire.encode("no_such_segment", {
                "request_id": f["request_id"], "segment": f["segment"],
            })
        new_start = max(seg.start_offset, min(int(f["offset"]), len(seg.data)))
        # blank the truncated range (offsets stay absolute; a real store
        # frees the backing extents the same way)
        seg.data[seg.start_offset : new_start] = b"\x00" * (
            new_start - seg.start_offset
        )
        seg.start_offset = new_start
        return wire.encode("segment_truncated", {
            "request_id": f["request_id"], "segment": f["segment"],
        })

    async def _on_seal_segment(self, f: dict) -> bytes:
        seg = self.segments.get(f["segment"])
        if seg is not None:
            seg.sealed = True
        return wire.encode("segment_sealed", {
            "request_id": f["request_id"], "segment": f["segment"],
        })
