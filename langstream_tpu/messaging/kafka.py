"""Kafka topic-connections runtime over a pure-asyncio wire-protocol client.

Parity: reference `langstream-kafka-runtime/` — consumer wrapper with manual
contiguous-prefix offset commit (KafkaConsumerWrapper.java:41-190), producer
wrapper with key partitioning, offset-addressed reader for the gateway, and
topic admin. No client library: the protocol codec is
``kafka_protocol.py`` (stdlib only) and works against a real broker or the
protocol-level fake (``kafka_fake.py`` — the `k8s/fake.py` testing pattern).

Design notes:
- Partition assignment is DYNAMIC when a ``group.id`` is set: the consumer
  speaks the JoinGroup/SyncGroup/Heartbeat group protocol
  (``KafkaGroupMembership`` below) with a client-side RangeAssignor, so
  replicas of the same agent split a topic's partitions and rebalance on
  membership change; commits are generation-fenced. With an explicit
  ``partitions`` list the consumer is static and uses offset storage only
  (OffsetCommit/OffsetFetch with generation -1 — the "simple consumer"
  convention).
- Commit bookkeeping is the same native OffsetTracker the memory broker
  uses: acks may arrive out of order, the committed offset only advances
  over the contiguous prefix.
- Values/keys serialize as UTF-8 for str, raw for bytes, compact JSON for
  anything else (decode tries UTF-8 first, falls back to raw bytes) —
  replacing the reference's Serde zoo with one honest rule.
"""

from __future__ import annotations

import asyncio
import functools
import itertools
import json
import time
from typing import Any, Optional

from langstream_tpu.api.record import Header, Record
from langstream_tpu.api.topics import (
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffsetPosition,
    TopicProducer,
    TopicReader,
    TopicReadResult,
)
from langstream_tpu.messaging import kafka_protocol as wire
from langstream_tpu.messaging.memory import ConsumedRecord
from langstream_tpu.native import OffsetTracker


class OffsetOutOfRange(RuntimeError):
    """Fetch offset fell outside the partition's log (retention truncated
    past a committed position); carries where so callers can reset."""

    def __init__(self, topic: str, partition: int) -> None:
        super().__init__(f"offset out of range for {topic}/{partition}")
        self.topic = topic
        self.partition = partition


class CommitFenced(RuntimeError):
    """OffsetCommit rejected by the coordinator (stale generation / unknown
    member): this replica was rebalanced away; it must rejoin, and the
    unacked records will be redelivered to the new partition owner."""


def _parse_bootstrap(bootstrap: str) -> list[tuple[str, int]]:
    """'host1:9092,host2:9093' / 'host' → [(host, port)] (default port 9092)."""
    out: list[tuple[str, int]] = []
    for entry in bootstrap.split(","):
        entry = entry.strip()
        if not entry:
            continue
        host, _, port = entry.rpartition(":")
        if host and port.isdigit():
            out.append((host, int(port)))
        else:
            out.append((entry, 9092))
    if not out:
        raise ValueError(f"empty bootstrap.servers {bootstrap!r}")
    return out


# transport headers carrying the Avro schema across the broker (schema-in-
# header v1: no registry needed; the canonical JSON is the intern key, so a
# downstream agent re-encodes under the ORIGINAL schema — the reference
# round-trips schemas through its serdes, KafkaProducerWrapper.java)
_AVRO_VALUE_SCHEMA_HEADER = "ls-avro-value-schema"
_AVRO_KEY_SCHEMA_HEADER = "ls-avro-key-schema"


@functools.lru_cache(maxsize=256)
def _schema_from_header(raw: bytes):
    """Memoized schema parse — a topic typically streams one fixed schema,
    and re-parsing JSON per consumed record would dominate hot-path CPU."""
    from langstream_tpu.api.avro import parse_schema

    return parse_schema(raw)


def _encode_datum(v: Any) -> Optional[bytes]:
    if v is None:
        return None
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode()
    from langstream_tpu.api.avro import AvroValue

    if isinstance(v, AvroValue):
        return v.encode()  # binary Avro; schema travels in the header
    return json.dumps(v, separators=(",", ":")).encode()


def _decode_datum(b: Optional[bytes]) -> Any:
    if b is None:
        return None
    try:
        return b.decode()
    except UnicodeDecodeError:
        return b


class KafkaConnection:
    """One broker connection; serial request/response with a lock (the
    runtime opens one connection per broker node per client)."""

    def __init__(self, host: str, port: int, client_id: str) -> None:
        self.host = host
        self.port = port
        self.client_id = client_id
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._correlation = itertools.count(1)

    async def connect(self) -> None:
        if self._writer is None:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port
            )

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001 — peer may already be gone
                pass
            self._writer = None
            self._reader = None

    async def call(self, api_key: int, payload: bytes) -> wire.Reader:
        async with self._lock:
            await self.connect()
            assert self._writer is not None and self._reader is not None
            cid = next(self._correlation)
            try:
                self._writer.write(
                    wire.encode_request(api_key, cid, self.client_id, payload)
                )
                await self._writer.drain()
                size = int.from_bytes(await self._reader.readexactly(4), "big")
                frame = await self._reader.readexactly(size)
            except BaseException:
                # a cancelled/failed mid-flight call leaves the stream with an
                # unread response; drop the connection so the next call
                # reconnects with clean framing
                await self.close()
                raise
            r = wire.Reader(frame)
            got = r.int32()
            if got != cid:
                raise RuntimeError(f"correlation mismatch: sent {cid} got {got}")
            return r


class KafkaClient:
    """Minimal cluster client: metadata-driven leader routing over
    per-node connections."""

    def __init__(self, bootstrap: str, client_id: str = "langstream-tpu") -> None:
        servers = _parse_bootstrap(bootstrap)
        host, port = servers[0]  # remaining entries are DNS-level fallbacks
        self._bootstrap = KafkaConnection(host, port, client_id)
        self._client_id = client_id
        self._nodes: dict[int, tuple[str, int]] = {}
        self._conns: dict[int, KafkaConnection] = {}
        # per (node, key) fetch connections: long-poll fetches get their own
        # socket per consumer so they never head-of-line block produces or
        # other consumers on the shared command connection
        self._fetch_conns: dict[tuple[int, int], KafkaConnection] = {}
        self._leaders: dict[tuple[str, int], int] = {}
        self._coordinators: dict[str, int] = {}

    async def close(self) -> None:
        await self._bootstrap.close()
        for conn in list(self._conns.values()) + list(self._fetch_conns.values()):
            await conn.close()
        self._conns.clear()
        self._fetch_conns.clear()

    async def _leader_conn(self, topic: str, partition: int) -> KafkaConnection:
        # leaders < 0 (LEADER_NOT_AVAILABLE) are never cached, so a missing
        # key is the only state to refresh; retry briefly for the transient
        # just-created-topic window
        for attempt in range(5):
            if (topic, partition) in self._leaders:
                break
            await self.metadata([topic])
            if (topic, partition) in self._leaders:
                break
            await asyncio.sleep(0.05 * (attempt + 1))
        node = self._leaders.get((topic, partition))
        if node is None:
            raise RuntimeError(f"no leader for {topic}/{partition}")
        conn = self._conns.get(node)
        if conn is None:
            host, port = self._nodes[node]
            conn = KafkaConnection(host, port, self._client_id)
            self._conns[node] = conn
        return conn

    def _fetch_conn(self, node: int, key: int) -> KafkaConnection:
        conn = self._fetch_conns.get((node, key))
        if conn is None:
            host, port = self._nodes[node]
            conn = KafkaConnection(host, port, self._client_id)
            self._fetch_conns[(node, key)] = conn
        return conn

    async def release_fetch_conns(self, key: int) -> None:
        """Close the per-consumer fetch sockets (consumer/reader close)."""
        for nk in [nk for nk in self._fetch_conns if nk[1] == key]:
            await self._fetch_conns.pop(nk).close()

    # -- apis ---------------------------------------------------------------

    async def ensure_topic(self, topic: str) -> list[int]:
        """Partition ids for ``topic``, creating it (1 partition) if absent —
        the client-side analogue of Kafka's auto.create.topics."""
        meta = await self.metadata([topic])
        if topic not in meta:
            await self.create_topic(topic, 1)
            meta = await self.metadata([topic])
        return meta.get(topic) or [0]

    async def metadata(self, topics: Optional[list[str]] = None) -> dict[str, list[int]]:
        """topic → partition ids; refreshes node + leader routing tables."""
        w = wire.Writer().array(topics, lambda w, t: w.string(t))
        r = await self._bootstrap.call(wire.METADATA, w.build())
        out: dict[str, list[int]] = {}
        for _ in range(r.int32()):  # brokers
            node, host, port = r.int32(), r.string(), r.int32()
            r.string()  # rack
            self._nodes[node] = (host or "localhost", port)
        r.int32()  # controller id
        for _ in range(r.int32()):  # topics
            err, name = r.int16(), r.string()
            r.boolean()  # is_internal
            parts: list[int] = []
            for _ in range(r.int32()):
                perr = r.int16()
                pid, leader = r.int32(), r.int32()
                r.array(lambda rr: rr.int32())  # replicas
                r.array(lambda rr: rr.int32())  # isr
                parts.append(pid)
                if perr == wire.NONE and leader >= 0:
                    self._leaders[(name, pid)] = leader
                else:  # transient LEADER_NOT_AVAILABLE — never cache -1
                    self._leaders.pop((name, pid), None)
            if err == wire.NONE and name is not None:
                out[name] = sorted(parts)
        return out

    async def produce(
        self, topic: str, partition: int, records: list[wire.WireRecord]
    ) -> int:
        """Append one batch; returns the assigned base offset."""
        batch = wire.encode_record_batch(records)
        w = wire.Writer()
        w.string(None)  # transactional_id
        w.int16(-1)  # acks: all
        w.int32(30_000)
        w.array(
            [(topic, partition, batch)],
            lambda w, t: w.string(t[0]).array(
                [t],
                lambda w2, t2: w2.int32(t2[1]).bytes_(t2[2]),
            ),
        )
        conn = await self._leader_conn(topic, partition)
        r = await conn.call(wire.PRODUCE, w.build())
        base_offset = -1
        for _ in range(r.int32()):
            r.string()  # topic
            for _ in range(r.int32()):
                r.int32()  # partition
                err = r.int16()
                base_offset = r.int64()
                r.int64()  # log_append_time
                if err != wire.NONE:
                    # leader may have moved: evict so the next call re-resolves
                    self._leaders.pop((topic, partition), None)
                    raise RuntimeError(f"produce to {topic}/{partition}: error {err}")
        r.int32()  # throttle
        return base_offset

    async def fetch(
        self,
        offsets: dict[tuple[str, int], int],
        max_wait_ms: int,
        max_partition_bytes: int = 4 * 1024 * 1024,
        conn_key: int = 0,
    ) -> dict[tuple[str, int], list[wire.WireRecord]]:
        """Fetch from each (topic, partition) at its offset. Partitions are
        grouped per leader node; one Fetch request per node."""
        by_node: dict[int, list[tuple[str, int]]] = {}
        for (topic, partition) in offsets:
            await self._leader_conn(topic, partition)  # ensure routing
            node = self._leaders[(topic, partition)]
            by_node.setdefault(node, []).append((topic, partition))

        out: dict[tuple[str, int], list[wire.WireRecord]] = {}
        for node, tps in by_node.items():
            by_topic: dict[str, list[int]] = {}
            for topic, partition in tps:
                by_topic.setdefault(topic, []).append(partition)
            w = wire.Writer()
            w.int32(-1)  # replica_id
            w.int32(max_wait_ms)
            w.int32(1)  # min_bytes
            w.int32(64 * 1024 * 1024)  # max_bytes
            w.int8(0)  # isolation: read_uncommitted
            w.array(
                sorted(by_topic.items()),
                lambda w, t: w.string(t[0]).array(
                    t[1],
                    lambda w2, p, _topic=t[0]: w2.int32(p)
                    .int64(offsets[(_topic, p)])
                    .int32(max_partition_bytes),
                ),
            )
            conn = self._fetch_conn(node, conn_key)
            r = await conn.call(wire.FETCH, w.build())
            r.int32()  # throttle
            for _ in range(r.int32()):
                topic = r.string() or ""
                for _ in range(r.int32()):
                    partition = r.int32()
                    err = r.int16()
                    r.int64()  # high watermark
                    r.int64()  # last stable
                    r.array(lambda rr: (rr.int64(), rr.int64()))  # aborted txns
                    data = r.bytes_() or b""
                    if err == wire.OFFSET_OUT_OF_RANGE:
                        raise OffsetOutOfRange(topic, partition)
                    if err in wire.RETRIABLE_FETCH_ERRORS:
                        # routine leader movement during failover: evict the
                        # cached route and poll again next loop (the Java
                        # client's retry semantics), not an application error
                        self._leaders.pop((topic, partition), None)
                        out.setdefault((topic, partition), [])
                        continue
                    if err != wire.NONE:
                        self._leaders.pop((topic, partition), None)
                        raise RuntimeError(f"fetch {topic}/{partition}: error {err}")
                    want = offsets[(topic, partition)]
                    recs = [
                        rec for rec in wire.decode_record_batches(data)
                        if rec.offset >= want  # batches may start earlier
                    ]
                    out[(topic, partition)] = recs
        return out

    async def list_offsets(self, topic: str, partition: int, timestamp: int) -> int:
        w = wire.Writer()
        w.int32(-1)
        w.array(
            [(topic, partition)],
            lambda w, t: w.string(t[0]).array(
                [t[1]], lambda w2, p: w2.int32(p).int64(timestamp)
            ),
        )
        conn = await self._leader_conn(topic, partition)
        r = await conn.call(wire.LIST_OFFSETS, w.build())
        offset = 0
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()  # partition
                err = r.int16()
                r.int64()  # timestamp
                offset = r.int64()
                if err != wire.NONE:
                    self._leaders.pop((topic, partition), None)
                    raise RuntimeError(f"list_offsets {topic}/{partition}: error {err}")
        return offset

    async def coordinator_node(self, group: str) -> int:
        """Group coordinator's node id, cached per group (the Java client's
        behavior) — heartbeats/commits must not serialize a FIND_COORDINATOR
        round-trip behind the shared bootstrap lock on every tick. Callers
        evict via ``invalidate_coordinator`` when a coordinator call fails."""
        cached = self._coordinators.get(group)
        if cached is not None:
            return cached
        w = wire.Writer().string(group).int8(0)
        r = await self._bootstrap.call(wire.FIND_COORDINATOR, w.build())
        r.int32()  # throttle
        err = r.int16()
        r.string()  # error message
        node, host, port = r.int32(), r.string(), r.int32()
        if err != wire.NONE:
            raise RuntimeError(f"find_coordinator({group}): error {err}")
        self._nodes[node] = (host or "localhost", port)
        self._coordinators[group] = node
        return node

    def invalidate_coordinator(self, group: str) -> None:
        self._coordinators.pop(group, None)

    async def find_coordinator(self, group: str) -> KafkaConnection:
        node = await self.coordinator_node(group)
        conn = self._conns.get(node)
        if conn is None:
            host, port = self._nodes[node]
            conn = KafkaConnection(host, port, self._client_id)
            self._conns[node] = conn
        return conn

    async def coordinator_conn(self, group: str, key: int) -> KafkaConnection:
        """Dedicated coordinator socket for one group member — JoinGroup and
        follower SyncGroup block server-side until the rebalance completes,
        and must never head-of-line block produce/commit traffic (or another
        member's join!) on the shared command connection."""
        node = await self.coordinator_node(group)
        return self._fetch_conn(node, key)

    async def offset_commit(
        self,
        group: str,
        topic: str,
        offsets: dict[int, int],
        generation: int = -1,
        member_id: str = "",
    ) -> None:
        """Commit offsets; generation -1 is the simple-consumer convention,
        a real generation is fenced by the coordinator (CommitFenced)."""
        w = wire.Writer()
        w.string(group)
        w.int32(generation)
        w.string(member_id)
        w.int64(-1)  # retention
        w.array(
            [topic],
            lambda w, t: w.string(t).array(
                sorted(offsets.items()),
                lambda w2, po: w2.int32(po[0]).int64(po[1]).string(None),
            ),
        )
        conn = await self.find_coordinator(group)
        r = await conn.call(wire.OFFSET_COMMIT, w.build())
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                partition = r.int32()
                err = r.int16()
                if err in (wire.ILLEGAL_GENERATION, wire.UNKNOWN_MEMBER_ID):
                    raise CommitFenced(f"offset_commit {topic}/{partition}: error {err}")
                if err != wire.NONE:
                    raise RuntimeError(f"offset_commit {topic}/{partition}: error {err}")

    # -- consumer group membership ------------------------------------------

    async def join_group(
        self,
        conn: KafkaConnection,
        group: str,
        member_id: str,
        topics: list[str],
        session_timeout_ms: int,
        rebalance_timeout_ms: int,
    ) -> tuple[int, int, Optional[str], str, list[tuple[str, bytes]]]:
        """JoinGroup v2 → (error, generation, leader, member_id, roster);
        roster (member_id, subscription bytes) is non-empty only for the
        elected leader, who must compute the assignment."""
        w = wire.Writer()
        w.string(group)
        w.int32(session_timeout_ms)
        w.int32(rebalance_timeout_ms)
        w.string(member_id)
        w.string("consumer")
        w.array(
            [("range", wire.encode_subscription(topics))],
            lambda w, p: w.string(p[0]).bytes_(p[1]),
        )
        r = await conn.call(wire.JOIN_GROUP, w.build())
        r.int32()  # throttle
        err = r.int16()
        generation = r.int32()
        r.string()  # protocol name
        leader = r.string()
        me = r.string() or ""
        roster = r.array(lambda rr: (rr.string() or "", rr.bytes_() or b""))
        return err, generation, leader, me, roster

    async def sync_group(
        self,
        conn: KafkaConnection,
        group: str,
        generation: int,
        member_id: str,
        assignments: list[tuple[str, bytes]],
    ) -> tuple[int, bytes]:
        w = wire.Writer()
        w.string(group)
        w.int32(generation)
        w.string(member_id)
        w.array(assignments, lambda w, a: w.string(a[0]).bytes_(a[1]))
        r = await conn.call(wire.SYNC_GROUP, w.build())
        r.int32()  # throttle
        err = r.int16()
        return err, r.bytes_() or b""

    async def heartbeat(
        self, conn: KafkaConnection, group: str, generation: int, member_id: str
    ) -> int:
        w = wire.Writer().string(group).int32(generation).string(member_id)
        r = await conn.call(wire.HEARTBEAT, w.build())
        r.int32()  # throttle
        return r.int16()

    async def leave_group(
        self, conn: KafkaConnection, group: str, member_id: str
    ) -> None:
        w = wire.Writer().string(group).string(member_id)
        r = await conn.call(wire.LEAVE_GROUP, w.build())
        r.int32()  # throttle
        r.int16()  # best-effort

    async def offset_fetch(self, group: str, topic: str, partitions: list[int]) -> dict[int, int]:
        w = wire.Writer()
        w.string(group)
        w.array(
            [topic],
            lambda w, t: w.string(t).array(partitions, lambda w2, p: w2.int32(p)),
        )
        conn = await self.find_coordinator(group)
        r = await conn.call(wire.OFFSET_FETCH, w.build())
        out: dict[int, int] = {}
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                partition = r.int32()
                offset = r.int64()
                r.string()  # metadata
                err = r.int16()
                if err == wire.NONE:
                    out[partition] = offset
        return out

    async def create_topic(self, name: str, partitions: int) -> None:
        w = wire.Writer()
        w.array(
            [name],
            lambda w, t: w.string(t)
            .int32(partitions)
            .int16(1)  # replication factor
            .array([], lambda w2, _: None)  # assignments
            .array([], lambda w2, _: None),  # configs
        )
        w.int32(30_000)
        r = await self._bootstrap.call(wire.CREATE_TOPICS, w.build())
        for _ in range(r.int32()):
            r.string()
            err = r.int16()
            if err not in (wire.NONE, wire.TOPIC_ALREADY_EXISTS):
                raise RuntimeError(f"create_topic {name}: error {err}")

    async def delete_topic(self, name: str) -> None:
        w = wire.Writer()
        w.array([name], lambda w, t: w.string(t))
        w.int32(30_000)
        r = await self._bootstrap.call(wire.DELETE_TOPICS, w.build())
        for _ in range(r.int32()):
            r.string()
            r.int16()  # best-effort


# ---------------------------------------------------------------------------
# SPI implementations
# ---------------------------------------------------------------------------


def _to_consumed(topic: str, partition: int, rec: wire.WireRecord) -> ConsumedRecord:
    value: Any = None
    key: Any = None
    value_schema = key_schema = None
    headers: list[Header] = []
    for k, v in rec.headers:
        if k == _AVRO_VALUE_SCHEMA_HEADER:
            value_schema = v
        elif k == _AVRO_KEY_SCHEMA_HEADER:
            key_schema = v
        else:
            headers.append(Header(k, _decode_datum(v)))
    if value_schema is not None or key_schema is not None:
        from langstream_tpu.api.avro import AvroValue, decode

        if value_schema is not None and rec.value is not None:
            schema = _schema_from_header(value_schema)
            value = AvroValue(schema, decode(schema, rec.value))
        else:
            value = _decode_datum(rec.value)
        if key_schema is not None and rec.key is not None:
            schema = _schema_from_header(key_schema)
            key = AvroValue(schema, decode(schema, rec.key))
        else:
            key = _decode_datum(rec.key)
    else:
        value = _decode_datum(rec.value)
        key = _decode_datum(rec.key)
    return ConsumedRecord(
        value=value,
        key=key,
        headers=tuple(headers),
        origin=topic,
        timestamp=rec.timestamp_ms / 1000.0,
        partition=partition,
        offset=rec.offset,
    )


def _to_wire(record: Record) -> wire.WireRecord:
    from langstream_tpu.api.avro import AvroValue

    headers = [(h.key, _encode_datum(h.value)) for h in record.headers]
    if isinstance(record.value, AvroValue):
        headers.append(
            (_AVRO_VALUE_SCHEMA_HEADER, record.value.schema.canonical().encode())
        )
    if isinstance(record.key, AvroValue):
        headers.append(
            (_AVRO_KEY_SCHEMA_HEADER, record.key.schema.canonical().encode())
        )
    return wire.WireRecord(
        key=_encode_datum(record.key),
        value=_encode_datum(record.value),
        # None header values stay null on the wire (varint -1) so they
        # round-trip identically to the memory transport
        headers=headers,
        timestamp_ms=int((record.timestamp or time.time()) * 1000),
    )


class KafkaGroupMembership:
    """Dynamic consumer-group membership: JoinGroup/SyncGroup to obtain a
    partition assignment, background Heartbeat to hold it, rejoin on any
    coordinator signal. This is what splits a topic's partitions across the
    planner's N pod replicas (the reference's #1 parallelism primitive —
    KafkaConsumerWrapper.java:41-115 rebalance listener semantics).

    The elected leader runs Kafka's RangeAssignor client-side (the real
    protocol's design: the broker treats subscriptions/assignments as opaque
    bytes and any member must be able to lead)."""

    def __init__(
        self,
        client: KafkaClient,
        group: str,
        topics: list[str],
        session_timeout: float = 10.0,
    ) -> None:
        self.client = client
        self.group = group
        self.topics = topics
        self.session_timeout = session_timeout
        self.member_id = ""
        self.generation = -1
        self.assignment: dict[str, list[int]] = {}
        self.rejoin_needed = True
        self._hb_task: Optional[asyncio.Task] = None
        self._conn_key = id(self)

    async def ensure_active(self) -> bool:
        """(Re)join if flagged; True when a rejoin happened (the caller must
        rebuild positions from committed offsets)."""
        if not self.rejoin_needed:
            return False
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None
        await self._join()
        self.rejoin_needed = False
        self._hb_task = asyncio.create_task(self._heartbeat_loop())
        return True

    async def _join(self) -> None:
        session_ms = int(self.session_timeout * 1000)
        rebalance_ms = session_ms * 2
        conn_failures = 0
        while True:
            try:
                conn = await self.client.coordinator_conn(self.group, self._conn_key)
                err, generation, leader, me, roster = await self.client.join_group(
                    conn, self.group, self.member_id, self.topics, session_ms, rebalance_ms
                )
            except (ConnectionError, OSError, EOFError):
                # coordinator moved or dropped: re-resolve and retry
                self.client.invalidate_coordinator(self.group)
                conn_failures += 1
                if conn_failures >= 5:
                    raise
                await asyncio.sleep(0.1 * conn_failures)
                continue
            if err == wire.UNKNOWN_MEMBER_ID:
                self.member_id = ""
                continue
            if err == wire.REBALANCE_IN_PROGRESS:
                await asyncio.sleep(0.05)
                continue
            if err != wire.NONE:
                raise RuntimeError(f"join_group({self.group}): error {err}")
            self.member_id = me
            assignments: list[tuple[str, bytes]] = []
            if me == leader:
                subs = [(mid, wire.decode_subscription(meta)) for mid, meta in roster]
                all_topics = sorted({t for _, ts in subs for t in ts})
                meta = await self.client.metadata(all_topics)
                parts = {t: meta.get(t, []) for t in all_topics}
                plan = wire.range_assign(subs, parts)
                assignments = [
                    (mid, wire.encode_assignment(a)) for mid, a in plan.items()
                ]
            err2, data = await self.client.sync_group(
                conn, self.group, generation, me, assignments
            )
            if err2 == wire.REBALANCE_IN_PROGRESS:
                continue
            if err2 in (wire.UNKNOWN_MEMBER_ID, wire.ILLEGAL_GENERATION):
                self.member_id = ""
                continue
            if err2 != wire.NONE:
                raise RuntimeError(f"sync_group({self.group}): error {err2}")
            self.generation = generation
            self.assignment = wire.decode_assignment(data) if data else {}
            return

    async def _heartbeat_loop(self) -> None:
        interval = max(self.session_timeout / 3.0, 0.05)
        while True:
            await asyncio.sleep(interval)
            try:
                conn = await self.client.coordinator_conn(self.group, self._conn_key)
                err = await self.client.heartbeat(
                    conn, self.group, self.generation, self.member_id
                )
            except Exception:  # noqa: BLE001 — coordinator gone: rejoin
                self.client.invalidate_coordinator(self.group)
                self.rejoin_needed = True
                return
            if err != wire.NONE:
                self.rejoin_needed = True
                return

    async def close(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None
        if self.member_id:
            try:
                conn = await self.client.coordinator_conn(self.group, self._conn_key)
                await self.client.leave_group(conn, self.group, self.member_id)
            except Exception:  # noqa: BLE001 — best-effort goodbye
                pass
        await self.client.release_fetch_conns(self._conn_key)


class KafkaTopicConsumer(TopicConsumer):
    def __init__(
        self,
        client: KafkaClient,
        topic: str,
        group: str,
        poll_timeout: float = 0.1,
        max_records: int = 100,
        partitions: Optional[list[int]] = None,
        session_timeout: float = 10.0,
    ) -> None:
        self.client = client
        self.topic_name = topic
        self.group = group
        self.poll_timeout = poll_timeout
        self.max_records = max_records
        self._explicit_partitions = partitions
        self._membership: Optional[KafkaGroupMembership] = None
        self._session_timeout = session_timeout
        self._assigned: list[int] = []
        self._fetch_pos: dict[int, int] = {}
        self._trackers: dict[int, OffsetTracker] = {}
        self._committed: dict[int, int] = {}
        self._total_out = 0
        self._rr_start = -1

    async def start(self) -> None:
        await self.client.ensure_topic(self.topic_name)
        if self._explicit_partitions is not None:
            # static assignment (operator-pinned slice): offsets only, no
            # group membership — Kafka's "simple consumer" mode
            self._reset_positions(
                self._explicit_partitions,
                await self.client.offset_fetch(
                    self.group, self.topic_name, self._explicit_partitions
                ),
            )
            return
        self._membership = KafkaGroupMembership(
            self.client,
            self.group,
            [self.topic_name],
            session_timeout=self._session_timeout,
        )
        await self._reassign()

    def _reset_positions(self, partitions: list[int], committed: dict[int, int]) -> None:
        self._assigned = sorted(partitions)
        self._fetch_pos.clear()
        self._trackers.clear()
        self._committed.clear()
        for p in self._assigned:
            start = max(committed.get(p, 0), 0)  # -1 = no committed offset
            self._fetch_pos[p] = start
            self._trackers[p] = OffsetTracker(start)
            self._committed[p] = start

    async def _reassign(self) -> None:
        assert self._membership is not None
        try:
            await self._membership.ensure_active()
            partitions = self._membership.assignment.get(self.topic_name, [])
            self._reset_positions(
                partitions,
                await self.client.offset_fetch(self.group, self.topic_name, partitions),
            )
        except BaseException:
            # positions were NOT rebuilt: without this flag the consumer
            # would keep fetching its pre-rebalance partitions under a valid
            # new generation — double consumption with unfenced commits
            self._membership.rejoin_needed = True
            raise

    async def close(self) -> None:
        # command connections are owned by the runtime's shared client;
        # this consumer's dedicated fetch sockets close with it
        if self._membership is not None:
            await self._membership.close()
        await self.client.release_fetch_conns(id(self))

    async def read(self) -> list[Record]:
        if self._membership is not None and self._membership.rejoin_needed:
            await self._reassign()
        if not self._assigned:
            # every partition is owned by other group members right now
            await asyncio.sleep(self.poll_timeout)
            return []
        try:
            got = await self.client.fetch(
                {(self.topic_name, p): self._fetch_pos[p] for p in self._assigned},
                max_wait_ms=int(self.poll_timeout * 1000),
                conn_key=id(self),
            )
        except OffsetOutOfRange as e:
            # retention truncated past our position: reset to earliest (the
            # standard auto.offset.reset recovery) and poll again next loop
            earliest = await self.client.list_offsets(
                e.topic, e.partition, wire.EARLIEST_TIMESTAMP
            )
            self._fetch_pos[e.partition] = earliest
            self._trackers[e.partition] = OffsetTracker(earliest)
            self._committed[e.partition] = earliest
            return []
        # rotate the partition start each read so a hot partition can't
        # starve the others under the max_records cap
        self._rr_start = (self._rr_start + 1) % max(len(self._assigned), 1)
        order = self._assigned[self._rr_start :] + self._assigned[: self._rr_start]
        out: list[Record] = []
        for partition in order:
            for rec in got.get((self.topic_name, partition), ()):
                if len(out) >= self.max_records:
                    break
                out.append(_to_consumed(self.topic_name, partition, rec))
                self._fetch_pos[partition] = rec.offset + 1
        self._total_out += len(out)
        return out

    async def commit(self, records: list[Record]) -> None:
        """Contiguous-prefix commit (KafkaConsumerWrapper.commit:159-190):
        out-of-order acks park in the tracker; only the prefix commits.
        Acks for partitions revoked by a rebalance are dropped — the new
        owner refetches from the last committed offset (at-least-once)."""
        to_commit: dict[int, int] = {}
        for r in records:
            if not isinstance(r, ConsumedRecord):
                continue
            tracker = self._trackers.get(r.partition)
            if tracker is None:
                if self._membership is not None:
                    continue  # revoked partition: let the new owner redeliver
                tracker = OffsetTracker(0)
                self._trackers[r.partition] = tracker
            new_committed = tracker.ack(r.offset)
            if new_committed != self._committed.get(r.partition):
                to_commit[r.partition] = new_committed
        if not to_commit:
            return
        generation, member = -1, ""
        if self._membership is not None:
            generation = self._membership.generation
            member = self._membership.member_id
        try:
            await self.client.offset_commit(
                self.group, self.topic_name, to_commit, generation, member
            )
        except CommitFenced:
            if self._membership is None:
                raise
            self._membership.rejoin_needed = True
            return
        self._committed.update(to_commit)

    def get_info(self) -> dict[str, Any]:
        return {
            "topic": self.topic_name,
            "group": self.group,
            "assigned-partitions": list(self._assigned),
            "committed": {str(p): self._committed.get(p, 0) for p in self._assigned},
        }

    @property
    def total_out(self) -> int:
        return self._total_out


class KafkaTopicProducer(TopicProducer):
    def __init__(self, client: KafkaClient, topic: str) -> None:
        self.client = client
        self.topic_name = topic
        self._partitions: Optional[list[int]] = None
        self._rr = 0
        self._total_in = 0

    async def start(self) -> None:
        self._partitions = await self.client.ensure_topic(self.topic_name)

    async def write(self, record: Record) -> None:
        if self._partitions is None:
            await self.start()
        assert self._partitions is not None
        n = len(self._partitions)
        if record.key is not None:
            # murmur2 (Kafka's DefaultPartitioner), NOT the platform FNV
            # hash: keyed records must co-partition with Java/librdkafka
            # producers sharing the topic
            key_bytes = _encode_datum(record.key) or b""
            part = self._partitions[wire.murmur2_partition(key_bytes, n)]
        else:
            part = self._partitions[self._rr % n]
            self._rr += 1
        await self.client.produce(self.topic_name, part, [_to_wire(record)])
        self._total_in += 1

    @property
    def total_in(self) -> int:
        return self._total_in


class KafkaTopicReader(TopicReader):
    """Offset-addressed reader (gateway consume path — no group)."""

    def __init__(
        self,
        client: KafkaClient,
        topic: str,
        initial: TopicOffsetPosition,
        poll_timeout: float = 0.1,
    ) -> None:
        self.client = client
        self.topic_name = topic
        self.initial = initial
        self.poll_timeout = poll_timeout
        self._pos: dict[int, int] = {}

    async def close(self) -> None:
        await self.client.release_fetch_conns(id(self))

    async def start(self) -> None:
        for p in await self.client.ensure_topic(self.topic_name):
            if self.initial.position == TopicOffsetPosition.EARLIEST:
                self._pos[p] = await self.client.list_offsets(
                    self.topic_name, p, wire.EARLIEST_TIMESTAMP
                )
            elif self.initial.position == "absolute":
                self._pos[p] = self.initial.offsets.get(p, 0)
            else:
                self._pos[p] = await self.client.list_offsets(
                    self.topic_name, p, wire.LATEST_TIMESTAMP
                )

    async def read(self) -> TopicReadResult:
        try:
            got = await self.client.fetch(
                {(self.topic_name, p): pos for p, pos in self._pos.items()},
                max_wait_ms=int(self.poll_timeout * 1000),
                conn_key=id(self),
            )
        except OffsetOutOfRange as e:
            self._pos[e.partition] = await self.client.list_offsets(
                e.topic, e.partition, wire.EARLIEST_TIMESTAMP
            )
            return TopicReadResult([], dict(self._pos), record_offsets=[])
        out: list[Record] = []
        offsets: list[dict[int, int]] = []
        for (topic, partition), recs in sorted(got.items()):
            for rec in recs:
                out.append(_to_consumed(topic, partition, rec))
                self._pos[partition] = rec.offset + 1
                offsets.append(dict(self._pos))
        return TopicReadResult(out, dict(self._pos), record_offsets=offsets)


class KafkaTopicAdmin(TopicAdmin):
    def __init__(self, client: KafkaClient) -> None:
        self.client = client

    async def create_topic(
        self, name: str, partitions: int = 1, options: Optional[dict] = None
    ) -> None:
        await self.client.create_topic(name, max(partitions, 1))

    async def delete_topic(self, name: str) -> None:
        await self.client.delete_topic(name)

    async def topic_exists(self, name: str) -> bool:
        meta = await self.client.metadata([name])
        return name in meta


class KafkaTopicConnectionsRuntime(TopicConnectionsRuntime):
    def __init__(self) -> None:
        self._bootstrap = "localhost:9092"
        self._consumer_defaults: dict[str, Any] = {}
        self._client: Optional[KafkaClient] = None

    async def init(self, streaming_cluster_config: dict[str, Any]) -> None:
        admin = streaming_cluster_config.get("admin", {})
        self._bootstrap = admin.get("bootstrap.servers", self._bootstrap)
        # streamingCluster.configuration.consumer: defaults merged under
        # every create_consumer config (reference's consumer config block)
        self._consumer_defaults = dict(streaming_cluster_config.get("consumer", {}))

    def client(self) -> KafkaClient:
        if self._client is None:
            self._client = KafkaClient(self._bootstrap)
        return self._client

    async def close(self) -> None:
        if self._client is not None:
            await self._client.close()
            self._client = None

    def create_consumer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicConsumer:
        config = {**self._consumer_defaults, **(config or {})}
        return KafkaTopicConsumer(
            self.client(),
            topic,
            group=config.get("group", agent_id),
            poll_timeout=float(config.get("poll-timeout", 0.1)),
            max_records=int(config.get("max-records", 100)),
            partitions=config.get("partitions"),
            session_timeout=float(config.get("session-timeout", 10.0)),
        )

    def create_producer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicProducer:
        return KafkaTopicProducer(self.client(), topic)

    def create_reader(
        self,
        topic: str,
        initial_position: TopicOffsetPosition = TopicOffsetPosition(),
        config: Optional[dict[str, Any]] = None,
    ) -> TopicReader:
        return KafkaTopicReader(self.client(), topic, initial_position)

    def create_topic_admin(self) -> TopicAdmin:
        return KafkaTopicAdmin(self.client())
