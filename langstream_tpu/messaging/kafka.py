"""Kafka topic-connections runtime (gated: requires a kafka client library).

Parity: reference `langstream-kafka-runtime/` — consumer wrapper with manual
contiguous-prefix offset commit (KafkaConsumerWrapper.java:41-190), producer
wrapper, dead-letter producer convention `<topic>-deadletter`.

The container image ships no kafka client; importing this module without
`aiokafka` (or `kafka-python`) raises ImportError, and the messaging registry
silently skips registration. The commit bookkeeping is identical to the
memory broker's (same `_pending` contiguous-prefix algorithm), so the ordered
at-least-once semantics are covered by the in-memory tests.
"""

from __future__ import annotations

try:
    import aiokafka  # type: ignore  # noqa: F401
except ImportError as e:  # pragma: no cover
    raise ImportError(
        "kafka streaming runtime requires the 'aiokafka' package, which is not "
        "installed in this image; use streamingCluster.type=memory"
    ) from e

from typing import Any, Optional

from langstream_tpu.api.topics import (
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffsetPosition,
    TopicProducer,
    TopicReader,
)


class KafkaTopicConnectionsRuntime(TopicConnectionsRuntime):  # pragma: no cover
    """Skeleton wired to aiokafka when available (not shipped in this image)."""

    def __init__(self) -> None:
        self._bootstrap: str = "localhost:9092"

    async def init(self, streaming_cluster_config: dict[str, Any]) -> None:
        admin = streaming_cluster_config.get("admin", {})
        self._bootstrap = admin.get("bootstrap.servers", self._bootstrap)

    def create_consumer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicConsumer:
        raise NotImplementedError("kafka data plane lands when a client lib is available")

    def create_producer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicProducer:
        raise NotImplementedError("kafka data plane lands when a client lib is available")

    def create_reader(
        self,
        topic: str,
        initial_position: TopicOffsetPosition = TopicOffsetPosition(),
        config: Optional[dict[str, Any]] = None,
    ) -> TopicReader:
        raise NotImplementedError("kafka data plane lands when a client lib is available")

    def create_topic_admin(self) -> TopicAdmin:
        raise NotImplementedError("kafka data plane lands when a client lib is available")
