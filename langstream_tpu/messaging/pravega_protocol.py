"""Pravega segment-store wire codec (WireCommands subset).

Parity: reference ``langstream-pravega-runtime`` delegates everything to the
official ``io.pravega`` client; this repo speaks the segment store's TCP
protocol directly, the same dependency-free approach as ``kafka_protocol``
/ ``pulsar_protocol``.

Framing (the Netty CommandEncoder convention): every message is

    [type  int32][length int32][payload ...]

with big-endian integers; payload fields follow Java ``DataOutput``
conventions — ``writeUTF`` strings (uint16 length + modified-UTF8 bytes,
plain UTF-8 here), int32/int64 big-endian, UUIDs as two int64s, byte
blocks length-prefixed with int32.

HONESTY NOTE (docs/COMPAT_RUNBOOK.md): the command *type codes and field
layouts* below are this repo's reconstruction of Pravega's WireCommands —
the conversation shapes (SetupAppend→AppendSetup, AppendBlockEnd→
DataAppended, ReadSegment→SegmentRead, …) follow the public protocol
documentation, but byte-level conformance against a real segment store is
unverified in this no-egress image. Both the client (pravega.py) and the
fake (pravega_fake.py) are built on THIS codec, so a future capture from a
real cluster can falsify it frame by frame.
"""

from __future__ import annotations

import struct
import uuid
from dataclasses import dataclass
from typing import Any, Optional

# command type codes (reconstructed WireCommandType enum subset)
HELLO = -127
WRONG_HOST = 0
SETUP_APPEND = 1
APPEND_SETUP = 2
APPEND_BLOCK_END = 4
DATA_APPENDED = 7
SEGMENT_IS_SEALED = 8
NO_SUCH_SEGMENT = 10
READ_SEGMENT = 22
SEGMENT_READ = 23
GET_STREAM_SEGMENT_INFO = 24
STREAM_SEGMENT_INFO = 25
CREATE_SEGMENT = 20
SEGMENT_CREATED = 21
DELETE_SEGMENT = 26
SEGMENT_DELETED = 27
SEAL_SEGMENT = 28
SEGMENT_SEALED = 29
TRUNCATE_SEGMENT = 30
SEGMENT_TRUNCATED = 31
KEEP_ALIVE = 100
ERROR_MESSAGE = -1

# the per-event header type code inside an append block / segment bytes
EVENT_TYPE_CODE = 0

WIRE_VERSION = 15  # protocol version advertised in HELLO
OLDEST_COMPATIBLE = 5


class Writer:
    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def int32(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">i", v))
        return self

    def int64(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">q", v))
        return self

    def bool_(self, v: bool) -> "Writer":
        self._parts.append(b"\x01" if v else b"\x00")
        return self

    def utf(self, s: str) -> "Writer":
        b = s.encode("utf-8")
        self._parts.append(struct.pack(">H", len(b)) + b)
        return self

    def uuid(self, u: uuid.UUID) -> "Writer":
        # two signed int64s (msb, lsb) — the Java UUID wire convention
        msb = (u.int >> 64) & 0xFFFFFFFFFFFFFFFF
        lsb = u.int & 0xFFFFFFFFFFFFFFFF
        self._parts.append(struct.pack(
            ">qq",
            msb - (1 << 64) if msb >= (1 << 63) else msb,
            lsb - (1 << 64) if lsb >= (1 << 63) else lsb,
        ))
        return self

    def block(self, b: bytes) -> "Writer":
        self._parts.append(struct.pack(">i", len(b)) + b)
        return self

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(b)
        return self

    def build(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    def __init__(self, data: bytes) -> None:
        self._d = data
        self._o = 0

    def int32(self) -> int:
        (v,) = struct.unpack_from(">i", self._d, self._o)
        self._o += 4
        return v

    def int64(self) -> int:
        (v,) = struct.unpack_from(">q", self._d, self._o)
        self._o += 8
        return v

    def bool_(self) -> bool:
        v = self._d[self._o] != 0
        self._o += 1
        return v

    def utf(self) -> str:
        (n,) = struct.unpack_from(">H", self._d, self._o)
        self._o += 2
        s = self._d[self._o : self._o + n].decode("utf-8")
        self._o += n
        return s

    def uuid(self) -> uuid.UUID:
        msb, lsb = struct.unpack_from(">qq", self._d, self._o)
        self._o += 16
        return uuid.UUID(int=((msb & 0xFFFFFFFFFFFFFFFF) << 64) | (lsb & 0xFFFFFFFFFFFFFFFF))

    def block(self) -> bytes:
        n = self.int32()
        b = self._d[self._o : self._o + n]
        self._o += n
        return b

    def rest(self) -> bytes:
        return self._d[self._o :]

    def remaining(self) -> int:
        return len(self._d) - self._o


def frame(type_: int, payload: bytes) -> bytes:
    return struct.pack(">ii", type_, len(payload)) + payload


def parse_frame_header(header: bytes) -> tuple[int, int]:
    """(type, payload length) from the 8-byte frame header."""
    return struct.unpack(">ii", header)


# -- command payload builders/parsers ---------------------------------------
# Each command is (type, dict) at the API boundary; codecs below.


def encode(command: str, f: dict[str, Any]) -> bytes:
    w = Writer()
    if command == "hello":
        return frame(HELLO, w.int32(f.get("high", WIRE_VERSION)).int32(f.get("low", OLDEST_COMPATIBLE)).build())
    if command == "setup_append":
        w.int64(f["request_id"]).uuid(f["writer_id"]).utf(f["segment"]).utf(f.get("token", ""))
        return frame(SETUP_APPEND, w.build())
    if command == "append_setup":
        w.int64(f["request_id"]).utf(f["segment"]).uuid(f["writer_id"]).int64(f["last_event_number"])
        return frame(APPEND_SETUP, w.build())
    if command == "append_block_end":
        w.uuid(f["writer_id"]).int32(f["size_of_whole_events"])
        w.block(f["data"]).int32(f["num_events"]).int64(f["last_event_number"]).int64(f["request_id"])
        return frame(APPEND_BLOCK_END, w.build())
    if command == "data_appended":
        w.uuid(f["writer_id"]).int64(f["event_number"]).int64(f.get("previous_event_number", -1)).int64(f["request_id"])
        return frame(DATA_APPENDED, w.build())
    if command == "create_segment":
        w.int64(f["request_id"]).utf(f["segment"]).int32(f.get("scale_type", 0)).int32(f.get("target_rate", 0)).utf(f.get("token", ""))
        return frame(CREATE_SEGMENT, w.build())
    if command == "segment_created":
        w.int64(f["request_id"]).utf(f["segment"])
        return frame(SEGMENT_CREATED, w.build())
    if command == "read_segment":
        w.utf(f["segment"]).int64(f["offset"]).int32(f["suggested_length"]).utf(f.get("token", "")).int64(f["request_id"])
        return frame(READ_SEGMENT, w.build())
    if command == "segment_read":
        w.utf(f["segment"]).int64(f["offset"]).bool_(f.get("at_tail", False)).bool_(f.get("end_of_segment", False))
        w.block(f["data"]).int64(f["request_id"])
        return frame(SEGMENT_READ, w.build())
    if command == "get_stream_segment_info":
        w.int64(f["request_id"]).utf(f["segment"]).utf(f.get("token", ""))
        return frame(GET_STREAM_SEGMENT_INFO, w.build())
    if command == "stream_segment_info":
        w.int64(f["request_id"]).utf(f["segment"]).bool_(f.get("exists", True)).bool_(f.get("sealed", False))
        w.int64(f.get("write_offset", 0)).int64(f.get("start_offset", 0))
        return frame(STREAM_SEGMENT_INFO, w.build())
    if command == "delete_segment":
        w.int64(f["request_id"]).utf(f["segment"]).utf(f.get("token", ""))
        return frame(DELETE_SEGMENT, w.build())
    if command == "segment_deleted":
        w.int64(f["request_id"]).utf(f["segment"])
        return frame(SEGMENT_DELETED, w.build())
    if command == "seal_segment":
        w.int64(f["request_id"]).utf(f["segment"]).utf(f.get("token", ""))
        return frame(SEAL_SEGMENT, w.build())
    if command == "truncate_segment":
        w.int64(f["request_id"]).utf(f["segment"]).int64(f["offset"]).utf(f.get("token", ""))
        return frame(TRUNCATE_SEGMENT, w.build())
    if command == "segment_truncated":
        w.int64(f["request_id"]).utf(f["segment"])
        return frame(SEGMENT_TRUNCATED, w.build())
    if command == "segment_sealed":
        w.int64(f["request_id"]).utf(f["segment"])
        return frame(SEGMENT_SEALED, w.build())
    if command == "no_such_segment":
        w.int64(f["request_id"]).utf(f["segment"])
        return frame(NO_SUCH_SEGMENT, w.build())
    if command == "keep_alive":
        return frame(KEEP_ALIVE, b"")
    if command == "error_message":
        w.int64(f.get("request_id", -1)).utf(f.get("message", ""))
        return frame(ERROR_MESSAGE, w.build())
    raise ValueError(f"unknown pravega command {command!r}")


def decode(type_: int, payload: bytes) -> tuple[str, dict[str, Any]]:
    r = Reader(payload)
    if type_ == HELLO:
        return "hello", {"high": r.int32(), "low": r.int32()}
    if type_ == SETUP_APPEND:
        return "setup_append", {
            "request_id": r.int64(), "writer_id": r.uuid(),
            "segment": r.utf(), "token": r.utf(),
        }
    if type_ == APPEND_SETUP:
        return "append_setup", {
            "request_id": r.int64(), "segment": r.utf(),
            "writer_id": r.uuid(), "last_event_number": r.int64(),
        }
    if type_ == APPEND_BLOCK_END:
        return "append_block_end", {
            "writer_id": r.uuid(), "size_of_whole_events": r.int32(),
            "data": r.block(), "num_events": r.int32(),
            "last_event_number": r.int64(), "request_id": r.int64(),
        }
    if type_ == DATA_APPENDED:
        return "data_appended", {
            "writer_id": r.uuid(), "event_number": r.int64(),
            "previous_event_number": r.int64(), "request_id": r.int64(),
        }
    if type_ == CREATE_SEGMENT:
        return "create_segment", {
            "request_id": r.int64(), "segment": r.utf(),
            "scale_type": r.int32(), "target_rate": r.int32(), "token": r.utf(),
        }
    if type_ == SEGMENT_CREATED:
        return "segment_created", {"request_id": r.int64(), "segment": r.utf()}
    if type_ == READ_SEGMENT:
        return "read_segment", {
            "segment": r.utf(), "offset": r.int64(),
            "suggested_length": r.int32(), "token": r.utf(),
            "request_id": r.int64(),
        }
    if type_ == SEGMENT_READ:
        return "segment_read", {
            "segment": r.utf(), "offset": r.int64(), "at_tail": r.bool_(),
            "end_of_segment": r.bool_(), "data": r.block(),
            "request_id": r.int64(),
        }
    if type_ == GET_STREAM_SEGMENT_INFO:
        return "get_stream_segment_info", {
            "request_id": r.int64(), "segment": r.utf(), "token": r.utf(),
        }
    if type_ == STREAM_SEGMENT_INFO:
        return "stream_segment_info", {
            "request_id": r.int64(), "segment": r.utf(), "exists": r.bool_(),
            "sealed": r.bool_(), "write_offset": r.int64(),
            "start_offset": r.int64(),
        }
    if type_ == DELETE_SEGMENT:
        return "delete_segment", {
            "request_id": r.int64(), "segment": r.utf(), "token": r.utf(),
        }
    if type_ == SEGMENT_DELETED:
        return "segment_deleted", {"request_id": r.int64(), "segment": r.utf()}
    if type_ == SEAL_SEGMENT:
        return "seal_segment", {
            "request_id": r.int64(), "segment": r.utf(), "token": r.utf(),
        }
    if type_ == TRUNCATE_SEGMENT:
        return "truncate_segment", {
            "request_id": r.int64(), "segment": r.utf(), "offset": r.int64(),
            "token": r.utf(),
        }
    if type_ == SEGMENT_TRUNCATED:
        return "segment_truncated", {"request_id": r.int64(), "segment": r.utf()}
    if type_ == SEGMENT_SEALED:
        return "segment_sealed", {"request_id": r.int64(), "segment": r.utf()}
    if type_ == NO_SUCH_SEGMENT:
        return "no_such_segment", {"request_id": r.int64(), "segment": r.utf()}
    if type_ == KEEP_ALIVE:
        return "keep_alive", {}
    if type_ == ERROR_MESSAGE:
        return "error_message", {"request_id": r.int64(), "message": r.utf()}
    raise ValueError(f"unknown pravega command type {type_}")


# -- event framing -----------------------------------------------------------
# Events inside append blocks AND inside segment bytes carry an 8-byte
# header: [typeCode int32 = 0][length int32][serialized event].


def frame_event(data: bytes) -> bytes:
    return struct.pack(">ii", EVENT_TYPE_CODE, len(data)) + data


def iter_events(data: bytes, base_offset: int = 0):
    """Yield (absolute_offset, event_bytes) for each WHOLE event in ``data``;
    a truncated tail (mid-event read cut) is ignored — the next read resumes
    at its offset."""
    o = 0
    n = len(data)
    while o + 8 <= n:
        type_, length = struct.unpack_from(">ii", data, o)
        if type_ != EVENT_TYPE_CODE:
            raise ValueError(f"corrupt event stream at offset {base_offset + o}")
        if o + 8 + length > n:
            break
        yield base_offset + o, data[o + 8 : o + 8 + length]
        o += 8 + length


@dataclass
class SegmentName:
    """scope/stream/<segment-number>.#epoch.<epoch>"""

    scope: str
    stream: str
    number: int
    epoch: int = 0

    @property
    def qualified(self) -> str:
        return f"{self.scope}/{self.stream}/{self.number}.#epoch.{self.epoch}"

    @staticmethod
    def parse(qualified: str) -> "SegmentName":
        scope, stream, tail = qualified.split("/", 2)
        num_part, _, epoch = tail.partition(".#epoch.")
        return SegmentName(scope, stream, int(num_part), int(epoch or 0))


def routing_key_segment(key: Optional[str], num_segments: int) -> int:
    """Routing key → segment: uniform hash onto [0, 1) then the fixed
    segment ranges [i/N, (i+1)/N). Reconstruction of the client's
    HashHelper.hashToRange (sha-256 based here; the real client uses a
    seeded murmur — byte-level parity pending a capture, but the CONTRACT
    — same key always lands on the same segment — holds)."""
    if key is None or num_segments <= 1:
        return 0
    import hashlib

    h = int.from_bytes(hashlib.sha256(key.encode()).digest()[:8], "big")
    return int((h / float(1 << 64)) * num_segments)
