"""Pravega topic-connections runtime (gated: requires the pravega client).

Parity: reference ``langstream-pravega/`` + ``langstream-pravega-runtime/``
(PravegaTopicConnectionsRuntimeProvider) — TopicConnections contracts over
Pravega streams. Gated exactly like the kafka/pulsar runtimes: the image
ships no client, so registration is skipped and ``streamingCluster.type:
pravega`` reports the known types instead.
"""

from __future__ import annotations

try:
    import pravega_client  # type: ignore  # noqa: F401
except ImportError as e:  # pragma: no cover
    raise ImportError(
        "pravega streaming runtime requires the 'pravega' client package, "
        "which is not installed in this image; use streamingCluster.type=memory"
    ) from e

from typing import Any, Optional

from langstream_tpu.api.topics import (
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffsetPosition,
    TopicProducer,
    TopicReader,
)


class PravegaTopicConnectionsRuntime(TopicConnectionsRuntime):  # pragma: no cover
    """Skeleton wired to the pravega client when available (not shipped here)."""

    def __init__(self) -> None:
        self._controller_uri = "tcp://localhost:9090"

    async def init(self, streaming_cluster_config: dict[str, Any]) -> None:
        client = streaming_cluster_config.get("client", {})
        self._controller_uri = client.get("controller-uri", self._controller_uri)

    def create_consumer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicConsumer:
        raise NotImplementedError("pravega data plane lands when a client lib is available")

    def create_producer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicProducer:
        raise NotImplementedError("pravega data plane lands when a client lib is available")

    def create_reader(
        self,
        topic: str,
        initial_position: TopicOffsetPosition = TopicOffsetPosition(),
        config: Optional[dict[str, Any]] = None,
    ) -> TopicReader:
        raise NotImplementedError("pravega data plane lands when a client lib is available")

    def create_topic_admin(self) -> TopicAdmin:
        raise NotImplementedError("pravega data plane lands when a client lib is available")
