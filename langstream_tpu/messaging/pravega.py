"""Pravega topic-connections runtime: dependency-free asyncio client.

Parity: reference ``langstream-pravega-runtime``
(PravegaTopicConnectionsRuntimeProvider.java:1 — EventStreamWriter/Reader +
ReaderGroup + StreamManager via the official io.pravega client) and
``langstream-pravega`` (planner half). This rebuild speaks the segment
store's TCP wire protocol directly (``pravega_protocol.py`` — the
kafka.py/pulsar.py pattern) and the controller's documented REST API for
stream CRUD, so the runtime ships with zero dependencies.

Behavior matched to the reference:
- topics are streams with ``ScalingPolicy.fixed(partitions)`` segments;
  admin CRUD creates the scope + stream (REST: POST /v1/scopes,
  POST /v1/scopes/{scope}/streams — PravegaTopicConnectionsRuntimeProvider
  .java:393-400) and the fixed segments on the segment store.
- records ride as JSON events ``{"key","value","headers","timestamp"}``
  (the reference serializes records through ObjectMapper the same way,
  :154-200) with writeEvent(routingKey, value) semantics: same key → same
  segment, ordered within the segment (:317-319).
- consumers form a subscription group that SPLITS segments across replicas
  (the reference gets this from Pravega reader groups, :127-128).
  Divergence, documented: reader-group coordination here is the platform's
  OWN, built from the same pravega primitive the official client's
  state-synchronizer uses — an event-sourced metadata stream per
  subscription (``_ls_sub_<stream>_<sub>``) carrying membership events and
  committed-offset snapshots (PravegaTopicConsumer docstring). The
  broker-visible protocol is unchanged.
- readers are offset-addressed (TopicReader + absolute seek) over pravega
  byte offsets per segment.

Wire-conformance caveat: see pravega_protocol.py's honesty note and
docs/COMPAT_RUNBOOK.md.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time
import uuid
from typing import Any, Optional
from urllib.parse import urlparse

from langstream_tpu.api.record import Header, Record
from langstream_tpu.api.topics import (
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffsetPosition,
    TopicProducer,
    TopicReadResult,
    TopicReader,
)
from langstream_tpu.messaging import pravega_protocol as wire
from langstream_tpu.messaging.memory import ConsumedRecord

log = logging.getLogger(__name__)

READ_CHUNK = 1 << 20  # suggested_length per ReadSegment


class PravegaError(RuntimeError):
    pass


def _record_to_event(record: Record) -> tuple[Optional[str], bytes]:
    """(routing key, serialized JSON event) — reference's ObjectMapper shape."""
    headers = {}
    for h in record.headers or ():
        v = h.value
        headers[h.key] = v.decode() if isinstance(v, bytes) else v
    key = record.key
    value = record.value
    doc = {
        "key": key.decode() if isinstance(key, bytes) else key,
        "value": value.decode() if isinstance(value, bytes) else value,
        "headers": headers,
        "timestamp": record.timestamp or time.time(),
    }
    routing = doc["key"]
    return (str(routing) if routing is not None else None), json.dumps(doc).encode()


def _event_to_record(topic: str, partition: int, offset: int, data: bytes) -> ConsumedRecord:
    doc = json.loads(data.decode())
    return ConsumedRecord(
        value=doc.get("value"),
        key=doc.get("key"),
        headers=tuple(Header(k, v) for k, v in (doc.get("headers") or {}).items()),
        origin=topic,
        timestamp=doc.get("timestamp"),
        partition=partition,
        offset=offset,
    )


class SegmentStoreConnection:
    """One TCP connection to a segment store; request/response correlated by
    request_id, append acks by (writer_id, event_number)."""

    def __init__(self, host: str, port: int) -> None:
        self.host, self.port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: dict[int, asyncio.Future] = {}
        self._acks: dict[tuple[uuid.UUID, int], asyncio.Future] = {}
        self._request_ids = itertools.count(1)
        self._write_lock = asyncio.Lock()
        self._dispatch: Optional[asyncio.Task] = None
        self.dead = False  # set when the dispatch loop exits; owner reconnects

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        await self._send(wire.encode("hello", {}))
        self._dispatch = asyncio.create_task(self._dispatch_loop())

    async def close(self) -> None:
        if self._dispatch is not None:
            self._dispatch.cancel()
            try:
                await self._dispatch
            except asyncio.CancelledError:
                pass
            self._dispatch = None
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self._writer = None

    async def _send(self, frame_bytes: bytes) -> None:
        assert self._writer is not None, "not connected"
        async with self._write_lock:
            self._writer.write(frame_bytes)
            await self._writer.drain()

    async def _read_frame(self) -> tuple[str, dict]:
        assert self._reader is not None
        header = await self._reader.readexactly(8)
        type_, length = wire.parse_frame_header(header)
        payload = await self._reader.readexactly(length)
        return wire.decode(type_, payload)

    async def _dispatch_loop(self) -> None:
        try:
            while True:
                name, fields = await self._read_frame()
                if name in ("hello", "keep_alive"):
                    continue
                if name == "data_appended":
                    key = (fields["writer_id"], fields["event_number"])
                    fut = self._acks.pop(key, None)
                    if fut is not None and not fut.done():
                        fut.set_result(fields)
                    continue
                rid = fields.get("request_id")
                if rid is not None:
                    fut = self._pending.pop(int(rid), None)
                    if fut is not None and not fut.done():
                        if name in ("error_message", "no_such_segment", "wrong_host"):
                            fut.set_exception(PravegaError(
                                f"{name}: {fields.get('message', fields.get('segment', ''))}"
                            ))
                        else:
                            fut.set_result((name, fields))
        except (asyncio.CancelledError, asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self.dead = True
            err = PravegaError("connection closed")
            for fut in list(self._pending.values()) + list(self._acks.values()):
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            self._acks.clear()

    async def request(self, command: str, fields: dict[str, Any]) -> tuple[str, dict]:
        request_id = next(self._request_ids)
        fields = {**fields, "request_id": request_id}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        try:
            await self._send(wire.encode(command, fields))
            return await asyncio.wait_for(fut, timeout=30)
        finally:
            self._pending.pop(request_id, None)

    async def append(
        self, writer_id: uuid.UUID, event_number: int, data: bytes, num_events: int
    ) -> dict:
        # request_id from the SHARED counter (never event_number: a small
        # integer that could collide with a concurrent request's id and
        # misroute an error reply); the one future is registered under BOTH
        # keys — DataAppended resolves it via _acks, an error_message /
        # no_such_segment reply via _pending
        request_id = next(self._request_ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._acks[(writer_id, event_number)] = fut
        self._pending[request_id] = fut
        try:
            await self._send(wire.encode("append_block_end", {
                "writer_id": writer_id,
                "size_of_whole_events": len(data),
                "data": data,
                "num_events": num_events,
                "last_event_number": event_number,
                "request_id": request_id,
            }))
            result = await asyncio.wait_for(fut, timeout=30)
            return result if isinstance(result, dict) else result[1]
        finally:
            self._acks.pop((writer_id, event_number), None)
            self._pending.pop(request_id, None)


class PravegaClient:
    """Controller REST (scope/stream CRUD) + one shared segment-store
    connection. Single-segment-store deployments (standalone / one node)
    take the address from config; multi-node segment discovery needs the
    controller's gRPC surface — out of scope, documented in the module
    docstring."""

    def __init__(
        self,
        controller_url: str = "http://localhost:10080",
        segment_store: str = "tcp://localhost:12345",
        scope: str = "langstream",
    ) -> None:
        self.controller_url = controller_url.rstrip("/")
        parsed = urlparse(segment_store)
        self.ss_host = parsed.hostname or "localhost"
        self.ss_port = parsed.port or 12345
        self.scope = scope
        self._conn: Optional[SegmentStoreConnection] = None
        self._lock = asyncio.Lock()
        self._http = None

    async def conn(self) -> SegmentStoreConnection:
        async with self._lock:
            if self._conn is not None and self._conn.dead:
                # transient store restart / socket drop: reconnect instead of
                # serving the dead connection forever (writers re-setup on
                # their next append via the error path)
                await self._conn.close()
                self._conn = None
            if self._conn is None:
                conn = SegmentStoreConnection(self.ss_host, self.ss_port)
                await conn.connect()
                self._conn = conn
            return self._conn

    async def close(self) -> None:
        if self._conn is not None:
            await self._conn.close()
            self._conn = None
        if self._http is not None and not self._http.closed:
            await self._http.close()
            self._http = None

    # -- controller REST ----------------------------------------------------

    async def _session(self):
        import aiohttp

        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession()
        return self._http

    async def rest(self, method: str, path: str, body: Optional[dict] = None) -> tuple[int, dict]:
        session = await self._session()
        async with session.request(
            method,
            f"{self.controller_url}/v1{path}",
            json=body,
            headers={"Accept": "application/json"},
        ) as resp:
            try:
                doc = await resp.json(content_type=None)
            except Exception:  # noqa: BLE001 — empty/non-json body
                doc = {}
            return resp.status, doc or {}

    async def ensure_scope(self) -> None:
        status, _ = await self.rest("POST", "/scopes", {"scopeName": self.scope})
        if status not in (201, 409):  # created | already exists
            raise PravegaError(f"create scope failed: HTTP {status}")

    async def create_stream(self, stream: str, segments: int) -> None:
        await self.ensure_scope()
        status, _ = await self.rest(
            "POST",
            f"/scopes/{self.scope}/streams",
            {
                "streamName": stream,
                "scopeName": self.scope,
                "scalingPolicy": {
                    "type": "FIXED_NUM_SEGMENTS",
                    "minSegments": max(1, segments),
                },
            },
        )
        if status not in (201, 409):
            raise PravegaError(f"create stream {stream} failed: HTTP {status}")
        conn = await self.conn()
        for number in range(max(1, segments)):
            name = wire.SegmentName(self.scope, stream, number).qualified
            try:
                await conn.request("create_segment", {"segment": name})
            except PravegaError:
                pass  # already exists

    async def delete_stream(self, stream: str) -> None:
        # the controller requires SEALED before delete
        await self.rest(
            "PUT",
            f"/scopes/{self.scope}/streams/{stream}/state",
            {"streamState": "SEALED"},
        )
        status, _ = await self.rest("DELETE", f"/scopes/{self.scope}/streams/{stream}")
        if status not in (204, 404):
            raise PravegaError(f"delete stream {stream} failed: HTTP {status}")

    async def stream_segments(self, stream: str) -> int:
        status, doc = await self.rest("GET", f"/scopes/{self.scope}/streams/{stream}")
        if status == 404:
            return 0
        return int(doc.get("scalingPolicy", {}).get("minSegments", 1))

    async def ensure_stream(self, stream: str) -> int:
        """Auto-create on first touch (the other runtimes' broker-side
        auto-create behavior); returns the segment count."""
        n = await self.stream_segments(stream)
        if n == 0:
            await self.create_stream(stream, 1)
            n = 1
        return n

    def segment(self, stream: str, number: int) -> str:
        return wire.SegmentName(self.scope, stream, number).qualified


class PravegaTopicProducer(TopicProducer):
    """EventStreamWriter semantics: routing key → fixed segment, append
    acks awaited per event (the reference's writeEvent().get())."""

    def __init__(self, client: PravegaClient, topic: str) -> None:
        self.client = client
        self.topic_name = topic
        self._writer_ids: dict[int, uuid.UUID] = {}
        self._event_numbers: dict[int, int] = {}
        self._segments = 0
        self._rr = 0
        self._total_in = 0

    async def start(self) -> None:
        self._segments = await self.client.ensure_stream(self.topic_name)
        conn = await self.client.conn()
        for number in range(self._segments):
            writer_id = uuid.uuid4()
            _, fields = await conn.request("setup_append", {
                "writer_id": writer_id,
                "segment": self.client.segment(self.topic_name, number),
            })
            self._writer_ids[number] = writer_id
            self._event_numbers[number] = int(fields.get("last_event_number", 0))

    async def close(self) -> None:
        self._writer_ids.clear()

    async def write(self, record: Record) -> None:
        if not self._writer_ids:
            await self.start()
        routing, data = _record_to_event(record)
        if routing is not None:
            number = wire.routing_key_segment(routing, self._segments)
        else:
            number = self._rr % self._segments
            self._rr += 1
        conn = await self.client.conn()
        self._event_numbers[number] += 1
        try:
            await conn.append(
                self._writer_ids[number],
                self._event_numbers[number],
                wire.frame_event(data),
                1,
            )
        except PravegaError:
            # connection was replaced (store restart): writers must re-setup
            # on the new socket, then the append retries exactly once
            await self.start()
            conn = await self.client.conn()
            self._event_numbers[number] += 1
            await conn.append(
                self._writer_ids[number],
                self._event_numbers[number],
                wire.frame_event(data),
                1,
            )
        self._total_in += 1

    @property
    def total_in(self) -> int:
        return self._total_in


_HEARTBEAT_EVERY = 2.0  # seconds between my heartbeat appends
_LIVENESS_WINDOW = 15.0  # member considered dead past this silence
_REFRESH_EVERY = 0.5  # how often read() re-derives the assignment


class PravegaTopicConsumer(TopicConsumer):
    """Subscription consumer with DYNAMIC segment splitting.

    Where the reference leans on the client library's ReaderGroup (a
    state-synchronizer segment), this consumer builds the same coordination
    from pravega primitives it already speaks: a single-segment metadata
    stream per (topic, subscription) carries an event-sourced log of
    membership events ({join, leave, heartbeat}) and committed-offset
    snapshots. Every member replays the log (incrementally — it remembers
    its read offset), derives the live member set, and takes the segments
    ``s where s % n_members == my_rank`` — all members compute the same
    assignment from the same log, so each segment has exactly one owner per
    converged view, and offsets snapshots hand work over on rebalance.
    Within a segment, delivery is ordered and commit advances over the
    contiguous acked prefix (the kafka OffsetTracker rule)."""

    def __init__(
        self,
        client: PravegaClient,
        topic: str,
        subscription: str,
        poll_timeout: float = 0.1,
        max_records: int = 100,
    ) -> None:
        self.client = client
        self.topic_name = topic
        self.subscription = subscription
        self.poll_timeout = poll_timeout
        self.max_records = max_records
        self.member_id = f"c-{uuid.uuid4().hex[:12]}"
        self._n_segments = 1
        self._positions: dict[int, int] = {}  # owned segment → next fetch offset
        # segment → {start offset → (end offset, acked)} in delivery order
        self._pending: dict[int, dict[int, tuple[int, bool]]] = {}
        self._committed: dict[int, int] = {}  # merged view for MY segments
        self._meta_stream = f"_ls_sub_{topic}_{subscription}"
        self._meta_offset = 0  # replay frontier in the metadata segment
        self._meta_base = 0  # truncation frontier last observed
        self._members: dict[str, float] = {}  # member → last seen ts
        self._snapshot_offsets: dict[str, int] = {}  # last offsets snapshot
        self._meta_writer: Optional[uuid.UUID] = None
        self._meta_event_number = 0
        self._last_heartbeat = 0.0
        self._last_refresh = 0.0
        self._total_out = 0

    # -- metadata log -------------------------------------------------------

    META_COMPACT_BYTES = 256 * 1024  # snapshot+truncate past this log size

    def _meta_segment(self) -> str:
        return self.client.segment(self._meta_stream, 0)

    async def _append_meta(self, doc: dict) -> None:
        """Append on a PERSISTENT writer (one setup per consumer lifetime,
        re-set-up only after a reconnect) — a fresh writer per append would
        grow the store's per-segment writer map unboundedly."""
        conn = await self.client.conn()
        if self._meta_writer is None:
            await self._setup_meta_writer(conn)
        self._meta_event_number += 1
        payload = wire.frame_event(json.dumps(doc).encode())
        try:
            await conn.append(self._meta_writer, self._meta_event_number, payload, 1)
        except PravegaError:
            conn = await self.client.conn()
            await self._setup_meta_writer(conn)
            self._meta_event_number += 1
            await conn.append(self._meta_writer, self._meta_event_number, payload, 1)

    async def _setup_meta_writer(self, conn: SegmentStoreConnection) -> None:
        self._meta_writer = uuid.uuid4()
        _, fields = await conn.request("setup_append", {
            "writer_id": self._meta_writer, "segment": self._meta_segment(),
        })
        self._meta_event_number = int(fields.get("last_event_number", 0))

    async def _replay_meta(self) -> None:
        """Fold new metadata events into the membership/offsets view. The
        store may answer a read below its truncation frontier with bytes
        from the frontier — the echoed offset says where they start."""
        conn = await self.client.conn()
        while True:
            _, fields = await conn.request("read_segment", {
                "segment": self._meta_segment(),
                "offset": self._meta_offset,
                "suggested_length": READ_CHUNK,
            })
            base = int(fields.get("offset", self._meta_offset))
            if base > self._meta_offset:  # jumped past a truncation
                self._meta_offset = base
                self._meta_base = base
            advanced = False
            for off, event in wire.iter_events(fields["data"], self._meta_offset):
                doc = json.loads(event.decode())
                kind = doc.get("type")
                if kind == "member":
                    member = doc["member"]
                    if doc["action"] == "leave":
                        self._members.pop(member, None)
                    else:  # join / heartbeat
                        self._members[member] = float(doc.get("ts", 0.0))
                elif kind == "offsets":
                    self._snapshot_offsets.update(
                        {k: int(v) for k, v in doc.get("offsets", {}).items()}
                    )
                elif kind == "snapshot":  # compaction point: replaces state
                    self._members = {
                        m: float(ts) for m, ts in doc.get("members", {}).items()
                    }
                    self._snapshot_offsets = {
                        k: int(v) for k, v in doc.get("offsets", {}).items()
                    }
                self._meta_offset = off + 8 + len(event)
                advanced = True
            if not advanced:
                return

    async def _compact_meta_if_due(self, live: list[str]) -> None:
        """Log compaction: when the un-truncated log grows past the cap, the
        LOWEST-ranked live member writes one snapshot record carrying the
        full folded state and truncates everything before it. Joiners then
        replay {snapshot, tail} instead of the whole history."""
        if self._meta_offset - self._meta_base < self.META_COMPACT_BYTES:
            return
        if not live or live[0] != self.member_id:
            return  # one compactor at a time is enough
        conn = await self.client.conn()
        _, info = await conn.request(
            "get_stream_segment_info", {"segment": self._meta_segment()}
        )
        snapshot_at = int(info.get("write_offset", self._meta_offset))
        await self._append_meta({
            "type": "snapshot",
            "members": self._members,
            "offsets": self._snapshot_offsets,
        })
        await conn.request("truncate_segment", {
            "segment": self._meta_segment(), "offset": snapshot_at,
        })
        self._meta_base = snapshot_at

    async def _refresh_assignment(self) -> None:
        await self._replay_meta()
        now = time.time()
        live = sorted(
            m for m, ts in self._members.items() if now - ts < _LIVENESS_WINDOW
        )
        if self.member_id not in live:
            live.append(self.member_id)
            live.sort()
        rank = live.index(self.member_id)
        mine = {s for s in range(self._n_segments) if s % len(live) == rank}
        await self._compact_meta_if_due(live)
        if mine != set(self._positions):
            # rebalance: drop lost segments (their unacked in-flight events
            # redeliver to the new owner — at-least-once), adopt gained ones
            # from the last committed snapshot
            for seg in list(self._positions):
                if seg not in mine:
                    del self._positions[seg]
                    self._pending.pop(seg, None)
                    self._committed.pop(seg, None)
            for seg in mine:
                if seg not in self._positions:
                    start = int(self._snapshot_offsets.get(str(seg), 0))
                    self._positions[seg] = start
                    self._committed[seg] = start
                    self._pending[seg] = {}
        self._last_refresh = asyncio.get_running_loop().time()

    async def _heartbeat_if_due(self) -> None:
        now = time.time()
        if now - self._last_heartbeat >= _HEARTBEAT_EVERY:
            self._last_heartbeat = now
            await self._append_meta({
                "type": "member", "member": self.member_id,
                "action": "heartbeat", "ts": now,
            })

    # -- SPI ----------------------------------------------------------------

    async def start(self) -> None:
        self._n_segments = await self.client.ensure_stream(self.topic_name)
        await self.client.create_stream(self._meta_stream, 1)
        self._last_heartbeat = time.time()
        await self._append_meta({
            "type": "member", "member": self.member_id,
            "action": "join", "ts": self._last_heartbeat,
        })
        await self._refresh_assignment()

    async def close(self) -> None:
        if not self._positions and not self._members:
            return
        try:
            await self._append_meta({
                "type": "member", "member": self.member_id, "action": "leave",
            })
        except (PravegaError, ConnectionError, asyncio.TimeoutError):
            log.warning("pravega consumer leave append failed", exc_info=True)
        self._positions.clear()
        self._pending.clear()
        self._members.clear()

    async def read(self) -> list[Record]:
        out: list[Record] = []
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.poll_timeout
        conn = await self.client.conn()
        while len(out) < self.max_records:
            if loop.time() - self._last_refresh >= _REFRESH_EVERY:
                await self._heartbeat_if_due()
                await self._refresh_assignment()
            got_any = False
            for number in list(self._positions):
                offset = self._positions[number]
                _, fields = await conn.request("read_segment", {
                    "segment": self.client.segment(self.topic_name, number),
                    "offset": offset,
                    "suggested_length": READ_CHUNK,
                })
                for off, event in wire.iter_events(fields["data"], offset):
                    end = off + 8 + len(event)
                    out.append(_event_to_record(self.topic_name, number, off, event))
                    self._pending[number][off] = (end, False)
                    self._positions[number] = end
                    got_any = True
                    if len(out) >= self.max_records:
                        break
            if not got_any:
                if out:
                    break
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                await asyncio.sleep(min(0.02, remaining))
        self._total_out += len(out)
        return out

    async def commit(self, records: list[Record]) -> None:
        """Mark acked, advance each owned segment's committed offset over
        the contiguous acked prefix, snapshot to the metadata log."""
        for r in records:
            if isinstance(r, ConsumedRecord):
                seg = self._pending.get(r.partition)
                if seg is not None and r.offset in seg:
                    seg[r.offset] = (seg[r.offset][0], True)
        changed = False
        for number, seg in self._pending.items():
            while True:
                head = self._committed.get(number, 0)
                entry = seg.get(head)
                if entry is None or not entry[1]:
                    break
                self._committed[number] = entry[0]
                del seg[head]
                changed = True
        if changed:
            await self._append_meta({
                "type": "offsets",
                "offsets": {str(k): v for k, v in self._committed.items()},
            })

    def get_info(self) -> dict[str, Any]:
        return {
            "topic": self.topic_name,
            "subscription": self.subscription,
            "member": self.member_id,
            "segments": sorted(self._positions),
            "committed": dict(self._committed),
        }

    @property
    def total_out(self) -> int:
        return self._total_out


class PravegaTopicReader(TopicReader):
    """Offset-addressed reader over ALL segments of a stream."""

    def __init__(
        self, client: PravegaClient, topic: str, initial_position: TopicOffsetPosition
    ) -> None:
        self.client = client
        self.topic_name = topic
        self.initial_position = initial_position
        self._positions: dict[int, int] = {}
        self._n = 1

    async def start(self) -> None:
        self._n = await self.client.ensure_stream(self.topic_name)
        conn = await self.client.conn()
        for number in range(self._n):
            p = number if self._n > 1 else -1
            seg = self.client.segment(self.topic_name, number)
            if self.initial_position.position == "absolute":
                self._positions[p] = int(self.initial_position.offsets.get(p, 0))
            elif self.initial_position.position == TopicOffsetPosition.LATEST:
                _, info = await conn.request("get_stream_segment_info", {"segment": seg})
                self._positions[p] = int(info.get("write_offset", 0))
            else:
                self._positions[p] = 0

    async def close(self) -> None:
        self._positions.clear()

    async def read(self) -> TopicReadResult:
        out: list[Record] = []
        record_offsets: list[dict[int, int]] = []
        conn = await self.client.conn()
        for p in list(self._positions):
            number = max(0, p)
            offset = self._positions[p]
            _, fields = await conn.request("read_segment", {
                "segment": self.client.segment(self.topic_name, number),
                "offset": offset,
                "suggested_length": READ_CHUNK,
            })
            for off, event in wire.iter_events(fields["data"], offset):
                end = off + 8 + len(event)
                out.append(_event_to_record(self.topic_name, p, off, event))
                self._positions[p] = end
                record_offsets.append(dict(self._positions))
        if not out:
            await asyncio.sleep(0.02)
        return TopicReadResult(out, dict(self._positions), record_offsets=record_offsets)


class PravegaTopicAdmin(TopicAdmin):
    """Stream CRUD over the controller REST API (the StreamManager surface,
    reference :393-400)."""

    def __init__(self, client: PravegaClient) -> None:
        self.client = client

    async def create_topic(
        self, name: str, partitions: int = 1, options: Optional[dict] = None
    ) -> None:
        await self.client.create_stream(name, partitions)

    async def delete_topic(self, name: str) -> None:
        await self.client.delete_stream(name)

    async def topic_exists(self, name: str) -> bool:
        return (await self.client.stream_segments(name)) > 0


class PravegaTopicConnectionsRuntime(TopicConnectionsRuntime):
    """``streamingCluster.type: pravega`` — config mirrors the reference's
    ``client`` block (controller-uri, scope; PravegaClientUtils.java:1) plus
    ``segment-store`` for the data plane."""

    def __init__(self) -> None:
        self.client: Optional[PravegaClient] = None

    async def init(self, streaming_cluster_config: dict[str, Any]) -> None:
        cfg = streaming_cluster_config.get("client", {}) or {}
        self.client = PravegaClient(
            controller_url=cfg.get(
                "controller-rest-uri", cfg.get("controller-uri", "http://localhost:10080")
            ),
            segment_store=cfg.get("segment-store", "tcp://localhost:12345"),
            scope=cfg.get("scope", "langstream"),
        )

    async def close(self) -> None:
        if self.client is not None:
            await self.client.close()
            self.client = None

    def create_consumer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicConsumer:
        config = config or {}
        return PravegaTopicConsumer(
            self.client,
            topic,
            subscription=config.get("subscription", agent_id or "langstream"),
        )

    def create_producer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicProducer:
        return PravegaTopicProducer(self.client, topic)

    def create_reader(
        self,
        topic: str,
        initial_position: TopicOffsetPosition = TopicOffsetPosition(),
        config: Optional[dict[str, Any]] = None,
    ) -> TopicReader:
        return PravegaTopicReader(self.client, topic, initial_position)

    def create_topic_admin(self) -> TopicAdmin:
        return PravegaTopicAdmin(self.client)
