"""Streaming-cluster runtime registry (reference TopicConnectionsRuntimeRegistry).

Maps `instance.streamingCluster.type` → TopicConnectionsRuntime. All four
broker runtimes — kafka, pulsar, pravega, memory — are dependency-free
(pure-asyncio wire-protocol clients / in-process broker) and always
register; the memory broker is the default local transport.
"""

from __future__ import annotations

from typing import Callable

from langstream_tpu.api.topics import TopicConnectionsRuntime


class TopicConnectionsRuntimeRegistry:
    _factories: dict[str, Callable[[], TopicConnectionsRuntime]] = {}

    @classmethod
    def register(cls, type_: str, factory: Callable[[], TopicConnectionsRuntime]) -> None:
        cls._factories[type_] = factory

    @classmethod
    def get(cls, type_: str) -> TopicConnectionsRuntime:
        cls._ensure_builtins()
        factory = cls._factories.get(type_)
        if factory is None:
            known = ", ".join(sorted(cls._factories))
            raise ValueError(f"unknown streaming cluster type {type_!r}; known: {known}")
        return factory()

    @classmethod
    def _ensure_builtins(cls) -> None:
        if "memory" not in cls._factories:
            # always required — an import failure here is a real bug and must
            # surface, not be masked as "unknown streaming cluster type"
            from langstream_tpu.messaging.memory import MemoryTopicConnectionsRuntime

            cls._factories["memory"] = MemoryTopicConnectionsRuntime
        if "kafka" not in cls._factories:
            # dependency-free (stdlib asyncio wire client): import
            # unconditionally so real regressions surface as tracebacks
            from langstream_tpu.messaging.kafka import KafkaTopicConnectionsRuntime

            cls._factories["kafka"] = KafkaTopicConnectionsRuntime
        if "pulsar" not in cls._factories:
            # same: wire-protocol client, no pulsar-client dependency
            from langstream_tpu.messaging.pulsar import PulsarTopicConnectionsRuntime

            cls._factories["pulsar"] = PulsarTopicConnectionsRuntime
        if "pravega" not in cls._factories:
            # same: segment-store wire client + controller REST, stdlib-only
            from langstream_tpu.messaging.pravega import (
                PravegaTopicConnectionsRuntime,
            )

            cls._factories["pravega"] = PravegaTopicConnectionsRuntime


def get_topic_connections_runtime(type_: str) -> TopicConnectionsRuntime:
    return TopicConnectionsRuntimeRegistry.get(type_)
