"""Streaming-cluster runtime registry (reference TopicConnectionsRuntimeRegistry).

Maps `instance.streamingCluster.type` → TopicConnectionsRuntime. The kafka
runtime registers itself only when a client library is importable (the image
ships none; the memory broker is the default transport).
"""

from __future__ import annotations

from typing import Callable

from langstream_tpu.api.topics import TopicConnectionsRuntime


class TopicConnectionsRuntimeRegistry:
    _factories: dict[str, Callable[[], TopicConnectionsRuntime]] = {}

    @classmethod
    def register(cls, type_: str, factory: Callable[[], TopicConnectionsRuntime]) -> None:
        cls._factories[type_] = factory

    @classmethod
    def get(cls, type_: str) -> TopicConnectionsRuntime:
        cls._ensure_builtins()
        factory = cls._factories.get(type_)
        if factory is None:
            known = ", ".join(sorted(cls._factories))
            raise ValueError(f"unknown streaming cluster type {type_!r}; known: {known}")
        return factory()

    @classmethod
    def _ensure_builtins(cls) -> None:
        if "memory" not in cls._factories:
            from langstream_tpu.messaging.memory import MemoryTopicConnectionsRuntime

            cls._factories["memory"] = MemoryTopicConnectionsRuntime
        if "kafka" not in cls._factories:
            try:
                from langstream_tpu.messaging.kafka import KafkaTopicConnectionsRuntime

                cls._factories["kafka"] = KafkaTopicConnectionsRuntime
            except ImportError:
                pass


def get_topic_connections_runtime(type_: str) -> TopicConnectionsRuntime:
    return TopicConnectionsRuntimeRegistry.get(type_)
