"""Kafka wire protocol: dependency-free binary codec over the documented
protocol (kafka.apache.org/protocol).

Implements the fixed, pre-flexible API versions the runtime needs — enough
for a full data plane (produce / fetch / offsets / coordinator / admin)
against a real broker or the protocol-level fake in ``kafka_fake.py``:

  Produce v3, Fetch v4, ListOffsets v1, Metadata v1, OffsetCommit v2,
  OffsetFetch v1, FindCoordinator v1, CreateTopics v0, DeleteTopics v0

plus the record batch v2 format (varint records, CRC32C).

Parity: replaces the reference's Java kafka-clients dependency
(`langstream-kafka-runtime/`); the SEMANTICS the runtime layers on top
(contiguous-prefix commit, KafkaConsumerWrapper.java:41-190) live in
``kafka.py``, not here. This module is deliberately a pure codec.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Optional

# api keys
PRODUCE = 0
FETCH = 1
LIST_OFFSETS = 2
METADATA = 3
OFFSET_COMMIT = 8
OFFSET_FETCH = 9
FIND_COORDINATOR = 10
JOIN_GROUP = 11
HEARTBEAT = 12
LEAVE_GROUP = 13
SYNC_GROUP = 14
CREATE_TOPICS = 19
DELETE_TOPICS = 20

API_VERSIONS = {
    PRODUCE: 3,
    FETCH: 4,
    LIST_OFFSETS: 1,
    METADATA: 1,
    OFFSET_COMMIT: 2,
    OFFSET_FETCH: 1,
    FIND_COORDINATOR: 1,
    JOIN_GROUP: 2,  # v2: adds rebalance_timeout, pre-flexible
    HEARTBEAT: 1,
    LEAVE_GROUP: 1,
    SYNC_GROUP: 1,
    CREATE_TOPICS: 0,
    DELETE_TOPICS: 0,
}

# error codes (subset)
NONE = 0
UNKNOWN_TOPIC_OR_PARTITION = 3
OFFSET_OUT_OF_RANGE = 1
NOT_LEADER_FOR_PARTITION = 6
REPLICA_NOT_AVAILABLE = 9
ILLEGAL_GENERATION = 22
UNKNOWN_MEMBER_ID = 25
REBALANCE_IN_PROGRESS = 27
TOPIC_ALREADY_EXISTS = 36

# fetch errors the Java client silently retries after a metadata refresh
# (routine leader movement during broker restart/failover)
RETRIABLE_FETCH_ERRORS = frozenset(
    {NOT_LEADER_FOR_PARTITION, REPLICA_NOT_AVAILABLE, UNKNOWN_TOPIC_OR_PARTITION}
)

EARLIEST_TIMESTAMP = -2
LATEST_TIMESTAMP = -1


# CRC32C (Castagnoli) — record batch v2 checksum; C++ on the produce hot
# path with a pure-Python fallback (langstream_tpu.native)
from langstream_tpu.native import crc32c  # noqa: E402


# ---------------------------------------------------------------------------
# Primitive codec
# ---------------------------------------------------------------------------


class Writer:
    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(b)
        return self

    def int8(self, v: int) -> "Writer":
        return self.raw(struct.pack(">b", v))

    def int16(self, v: int) -> "Writer":
        return self.raw(struct.pack(">h", v))

    def int32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">i", v))

    def int64(self, v: int) -> "Writer":
        return self.raw(struct.pack(">q", v))

    def uint32(self, v: int) -> "Writer":
        return self.raw(struct.pack(">I", v))

    def boolean(self, v: bool) -> "Writer":
        return self.int8(1 if v else 0)

    def string(self, s: Optional[str]) -> "Writer":
        if s is None:
            return self.int16(-1)
        b = s.encode()
        return self.int16(len(b)).raw(b)

    def bytes_(self, b: Optional[bytes]) -> "Writer":
        if b is None:
            return self.int32(-1)
        return self.int32(len(b)).raw(b)

    def array(self, items, encode) -> "Writer":
        if items is None:
            return self.int32(-1)
        self.int32(len(items))
        for item in items:
            encode(self, item)
        return self

    def varint(self, v: int) -> "Writer":
        # zigzag
        return self.uvarint((v << 1) ^ (v >> 31))

    def varlong(self, v: int) -> "Writer":
        return self.uvarint((v << 1) ^ (v >> 63))

    def uvarint(self, v: int) -> "Writer":
        out = bytearray()
        v &= 0xFFFFFFFFFFFFFFFF
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        return self.raw(bytes(out))

    def build(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def raw(self, n: int) -> bytes:
        out = self.data[self.pos : self.pos + n]
        if len(out) != n:
            raise EOFError(f"need {n} bytes at {self.pos}, have {len(out)}")
        self.pos += n
        return out

    def int8(self) -> int:
        return struct.unpack(">b", self.raw(1))[0]

    def int16(self) -> int:
        return struct.unpack(">h", self.raw(2))[0]

    def int32(self) -> int:
        return struct.unpack(">i", self.raw(4))[0]

    def int64(self) -> int:
        return struct.unpack(">q", self.raw(8))[0]

    def uint32(self) -> int:
        return struct.unpack(">I", self.raw(4))[0]

    def boolean(self) -> bool:
        return self.int8() != 0

    def string(self) -> Optional[str]:
        n = self.int16()
        if n < 0:
            return None
        return self.raw(n).decode()

    def bytes_(self) -> Optional[bytes]:
        n = self.int32()
        if n < 0:
            return None
        return self.raw(n)

    def array(self, decode) -> list:
        n = self.int32()
        if n < 0:
            return []
        return [decode(self) for _ in range(n)]

    def uvarint(self) -> int:
        shift = 0
        out = 0
        while True:
            b = self.raw(1)[0]
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def varint(self) -> int:
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)

    varlong = varint

    def remaining(self) -> int:
        return len(self.data) - self.pos


# ---------------------------------------------------------------------------
# Record batch v2
# ---------------------------------------------------------------------------


@dataclass
class WireRecord:
    key: Optional[bytes]
    value: Optional[bytes]
    headers: list[tuple[str, bytes]] = field(default_factory=list)
    timestamp_ms: int = 0
    offset: int = 0  # absolute, filled on decode / assigned by broker


def encode_record_batch(records: list[WireRecord], base_offset: int = 0) -> bytes:
    """One record batch v2 (magic=2) containing ``records``."""
    base_ts = records[0].timestamp_ms if records else 0
    max_ts = max((r.timestamp_ms for r in records), default=0)

    body = Writer()
    body.int16(0)  # attributes: no compression, no transaction
    body.int32(len(records) - 1)  # lastOffsetDelta
    body.int64(base_ts)
    body.int64(max_ts)
    body.int64(-1)  # producerId
    body.int16(-1)  # producerEpoch
    body.int32(-1)  # baseSequence
    body.int32(len(records))
    for i, rec in enumerate(records):
        r = Writer()
        r.int8(0)  # record attributes
        r.varlong(rec.timestamp_ms - base_ts)
        r.varint(i)  # offsetDelta
        if rec.key is None:
            r.varint(-1)
        else:
            r.varint(len(rec.key)).raw(rec.key)
        if rec.value is None:
            r.varint(-1)
        else:
            r.varint(len(rec.value)).raw(rec.value)
        r.varint(len(rec.headers))
        for hk, hv in rec.headers:
            kb = hk.encode()
            r.varint(len(kb)).raw(kb)
            if hv is None:
                r.varint(-1)
            else:
                r.varint(len(hv)).raw(hv)
        rb = r.build()
        body.varint(len(rb)).raw(rb)
    payload = body.build()

    out = Writer()
    out.int64(base_offset)
    out.int32(4 + 1 + 4 + len(payload))  # partitionLeaderEpoch..end
    out.int32(-1)  # partitionLeaderEpoch
    out.int8(2)  # magic
    out.uint32(crc32c(payload))
    out.raw(payload)
    return out.build()


def decode_record_batches(data: bytes) -> list[WireRecord]:
    """Decode a (possibly partial) sequence of record batches; a trailing
    truncated batch (broker may cut at max_bytes) is ignored."""
    out: list[WireRecord] = []
    r = Reader(data)
    while r.remaining() >= 12:
        base_offset = r.int64()
        length = r.int32()
        if r.remaining() < length:
            break  # truncated tail
        batch = Reader(r.raw(length))
        batch.int32()  # partitionLeaderEpoch
        magic = batch.int8()
        if magic != 2:
            raise ValueError(f"unsupported record batch magic {magic}")
        batch.uint32()  # crc — trusted (TCP checksums; fake broker is local)
        attributes = batch.int16()
        if attributes & 0x07:
            raise ValueError("compressed record batches not supported")
        batch.int32()  # lastOffsetDelta
        base_ts = batch.int64()
        batch.int64()  # maxTimestamp
        batch.int64()  # producerId
        batch.int16()  # producerEpoch
        batch.int32()  # baseSequence
        n = batch.int32()
        for _ in range(n):
            rec_len = batch.varint()
            rec = Reader(batch.raw(rec_len))
            rec.int8()  # attributes
            ts_delta = rec.varlong()
            offset_delta = rec.varint()
            klen = rec.varint()
            key = rec.raw(klen) if klen >= 0 else None
            vlen = rec.varint()
            value = rec.raw(vlen) if vlen >= 0 else None
            headers = []
            for _ in range(rec.varint()):
                hklen = rec.varint()
                hk = rec.raw(hklen).decode()
                hvlen = rec.varint()
                hv = rec.raw(hvlen) if hvlen >= 0 else None
                headers.append((hk, hv))
            out.append(
                WireRecord(
                    key=key,
                    value=value,
                    headers=headers,
                    timestamp_ms=base_ts + ts_delta,
                    offset=base_offset + offset_delta,
                )
            )
    return out


# ---------------------------------------------------------------------------
# Consumer protocol (the embedded metadata/assignment format the Java
# "consumer" protocol type exchanges through JoinGroup/SyncGroup — the
# group coordinator treats both as opaque bytes)
# ---------------------------------------------------------------------------


def encode_subscription(topics: list[str]) -> bytes:
    """ConsumerProtocolSubscription v0: version, topics[], user_data."""
    return Writer().int16(0).array(sorted(topics), lambda w, t: w.string(t)).bytes_(None).build()


def decode_subscription(data: bytes) -> list[str]:
    r = Reader(data)
    r.int16()  # version
    return [t for t in r.array(lambda rr: rr.string()) if t is not None]


def encode_assignment(assignment: dict[str, list[int]]) -> bytes:
    """ConsumerProtocolAssignment v0: version, [topic, partitions[]], user_data."""
    w = Writer().int16(0)
    w.array(
        sorted(assignment.items()),
        lambda w, kv: w.string(kv[0]).array(sorted(kv[1]), lambda w2, p: w2.int32(p)),
    )
    return w.bytes_(None).build()


def decode_assignment(data: bytes) -> dict[str, list[int]]:
    r = Reader(data)
    r.int16()  # version
    out: dict[str, list[int]] = {}
    for _ in range(r.int32()):
        topic = r.string() or ""
        out[topic] = r.array(lambda rr: rr.int32())
    return out


def range_assign(
    members: list[tuple[str, list[str]]], partitions: dict[str, list[int]]
) -> dict[str, dict[str, list[int]]]:
    """Kafka's RangeAssignor: per topic, sort subscribed members and hand
    each a contiguous slice; the first ``extra`` members get one more.
    member_id → topic → partition ids."""
    out: dict[str, dict[str, list[int]]] = {m: {} for m, _ in members}
    topics = sorted({t for _, subs in members for t in subs})
    for topic in topics:
        subscribers = sorted(m for m, subs in members if topic in subs)
        parts = sorted(partitions.get(topic, []))
        if not subscribers or not parts:
            continue
        per, extra = divmod(len(parts), len(subscribers))
        pos = 0
        for i, member in enumerate(subscribers):
            n = per + (1 if i < extra else 0)
            if n:
                out[member][topic] = parts[pos : pos + n]
            pos += n
    return out


def murmur2(data: bytes) -> int:
    """Kafka's murmur2 (32-bit, seed 0x9747b28c) — the default partitioner
    hash, so keyed records co-partition with Java/librdkafka producers."""
    length = len(data)
    seed = 0x9747B28C
    m = 0x5BD1E995
    mask = 0xFFFFFFFF
    h = (seed ^ length) & mask
    n = length & ~0x3
    for i in range(0, n, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * m) & mask
        k ^= k >> 24
        k = (k * m) & mask
        h = (h * m) & mask
        h ^= k
    rem = length & 0x3
    if rem == 3:
        h ^= (data[n + 2] & 0xFF) << 16
    if rem >= 2:
        h ^= (data[n + 1] & 0xFF) << 8
    if rem >= 1:
        h ^= data[n] & 0xFF
        h = (h * m) & mask
    h ^= h >> 13
    h = (h * m) & mask
    h ^= h >> 15
    return h


def murmur2_partition(key: bytes, num_partitions: int) -> int:
    """toPositive(murmur2(key)) % numPartitions — DefaultPartitioner."""
    return (murmur2(key) & 0x7FFFFFFF) % num_partitions


# ---------------------------------------------------------------------------
# Request framing
# ---------------------------------------------------------------------------


def encode_request(
    api_key: int, correlation_id: int, client_id: str, payload: bytes
) -> bytes:
    header = (
        Writer()
        .int16(api_key)
        .int16(API_VERSIONS[api_key])
        .int32(correlation_id)
        .string(client_id)
        .build()
    )
    frame = header + payload
    return struct.pack(">i", len(frame)) + frame


def decode_request_header(r: Reader) -> tuple[int, int, int, Optional[str]]:
    """(api_key, api_version, correlation_id, client_id)"""
    return r.int16(), r.int16(), r.int32(), r.string()
