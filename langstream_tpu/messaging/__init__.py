"""L2 — messaging runtimes (broker transports between agents).

The in-memory broker is the local/default transport; `kafka.py` is a real
Kafka data plane over a dependency-free asyncio wire-protocol client
(`kafka_protocol.py`), testable against the protocol-level fake broker
(`kafka_fake.py`). Intra-agent device communication is NOT here — that's
`parallel/` (ICI collectives), mirroring the reference's L2/L4 split.
"""

from langstream_tpu.messaging.registry import (
    TopicConnectionsRuntimeRegistry,
    get_topic_connections_runtime,
)
from langstream_tpu.messaging.memory import MemoryBroker, MemoryTopicConnectionsRuntime

__all__ = [
    "MemoryBroker",
    "MemoryTopicConnectionsRuntime",
    "TopicConnectionsRuntimeRegistry",
    "get_topic_connections_runtime",
]
