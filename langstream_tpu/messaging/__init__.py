"""L2 — messaging runtimes (broker transports between agents).

The in-memory broker is the reference implementation (plays the role Kafka
plays in the reference: SURVEY §2.3); `kafka.py` is an optional runtime gated
on an installed kafka client. Intra-agent device communication is NOT here —
that's `parallel/` (ICI collectives), mirroring the reference's L2/L4 split.
"""

from langstream_tpu.messaging.registry import (
    TopicConnectionsRuntimeRegistry,
    get_topic_connections_runtime,
)
from langstream_tpu.messaging.memory import MemoryBroker, MemoryTopicConnectionsRuntime

__all__ = [
    "MemoryBroker",
    "MemoryTopicConnectionsRuntime",
    "TopicConnectionsRuntimeRegistry",
    "get_topic_connections_runtime",
]
