"""Protocol-level fake Kafka broker for tests (the `k8s/fake.py` pattern).

Speaks the same wire subset the client in ``kafka.py`` does — Produce v3,
Fetch v4, ListOffsets v1, Metadata v1, OffsetCommit v2, OffsetFetch v1,
FindCoordinator v1, CreateTopics v0, DeleteTopics v0 — over a real asyncio
socket, storing record batches exactly as a broker log does (batches are
fetched back verbatim from the requested offset's containing batch onward,
so the client's "skip records below fetch_offset" path is exercised).

This stands in for the reference's testcontainers Kafka (KafkaContainerTest
tier) in an image with no JVM and no network egress.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Optional

from langstream_tpu.messaging import kafka_protocol as wire


@dataclass
class _PartitionLog:
    batches: list[tuple[int, int, bytes]] = field(default_factory=list)
    # (base_offset, record_count, batch_bytes)
    next_offset: int = 0

    def append(self, records: list[wire.WireRecord]) -> int:
        base = self.next_offset
        data = wire.encode_record_batch(records, base_offset=base)
        self.batches.append((base, len(records), data))
        self.next_offset += len(records)
        return base

    def read_from(self, offset: int) -> bytes:
        out = []
        for base, count, data in self.batches:
            if base + count > offset:  # batch contains offsets >= requested
                out.append(data)
        return b"".join(out)


class FakeKafkaBroker:
    """Single-node fake broker; node id 0, coordinator for every group."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.topics: dict[str, list[_PartitionLog]] = {}
        self.committed: dict[tuple[str, str, int], int] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._data_event = asyncio.Event()
        self._writers: set[asyncio.StreamWriter] = set()
        # protocol-visible knobs for tests
        self.auto_create_topics = True

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "FakeKafkaBroker":
        self._server = await asyncio.start_server(self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # force-close live client connections — wait_closed() waits for
            # every handler, and a leaked client would park it forever
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    # -- storage ------------------------------------------------------------

    def _topic(self, name: str, create: Optional[bool] = None) -> Optional[list[_PartitionLog]]:
        create = self.auto_create_topics if create is None else create
        t = self.topics.get(name)
        if t is None and create:
            t = [_PartitionLog()]
            self.topics[name] = t
        return t

    # -- connection handling -------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        try:
            while True:
                try:
                    size = int.from_bytes(await reader.readexactly(4), "big")
                except asyncio.IncompleteReadError:
                    return
                frame = await reader.readexactly(size)
                r = wire.Reader(frame)
                api_key, version, correlation, _client = wire.decode_request_header(r)
                handler = self._HANDLERS.get(api_key)
                if handler is None:
                    raise RuntimeError(f"fake broker: unsupported api {api_key}")
                body = await handler(self, r, version)
                out = wire.Writer().int32(correlation).raw(body).build()
                writer.write(len(out).to_bytes(4, "big") + out)
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            return
        finally:
            self._writers.discard(writer)
            writer.close()

    # -- handlers ------------------------------------------------------------

    async def _metadata(self, r: wire.Reader, version: int) -> bytes:
        topics = r.array(lambda rr: rr.string())
        if not topics:
            topics = sorted(self.topics)
        w = wire.Writer()
        w.array(
            [(0, self.host, self.port)],
            lambda w, b: w.int32(b[0]).string(b[1]).int32(b[2]).string(None),
        )
        w.int32(0)  # controller
        w.int32(len(topics))
        for name in topics:
            parts = self._topic(name, create=False)
            if parts is None:
                w.int16(wire.UNKNOWN_TOPIC_OR_PARTITION).string(name).boolean(False)
                w.int32(0)
                continue
            w.int16(wire.NONE).string(name).boolean(False)
            w.int32(len(parts))
            for pid in range(len(parts)):
                w.int16(wire.NONE).int32(pid).int32(0)  # leader = node 0
                w.array([0], lambda w2, x: w2.int32(x))  # replicas
                w.array([0], lambda w2, x: w2.int32(x))  # isr
        return w.build()

    async def _produce(self, r: wire.Reader, version: int) -> bytes:
        r.string()  # transactional id
        r.int16()  # acks
        r.int32()  # timeout
        responses = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                data = r.bytes_() or b""
                records = wire.decode_record_batches(data)
                parts = self._topic(topic)
                assert parts is not None
                while partition >= len(parts):
                    parts.append(_PartitionLog())
                base = parts[partition].append(records)
                responses.append((topic, partition, wire.NONE, base))
        self._data_event.set()
        self._data_event = asyncio.Event()
        w = wire.Writer()
        w.int32(len(responses))
        for topic, partition, err, base in responses:
            w.string(topic)
            w.int32(1)
            w.int32(partition).int16(err).int64(base).int64(-1)
        w.int32(0)  # throttle
        return w.build()

    async def _fetch(self, r: wire.Reader, version: int) -> bytes:
        r.int32()  # replica
        max_wait = r.int32()
        r.int32()  # min bytes
        r.int32()  # max bytes
        r.int8()  # isolation
        wants: list[tuple[str, int, int]] = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                offset = r.int64()
                r.int32()  # partition max bytes
                wants.append((topic, partition, offset))

        def collect() -> list[tuple[str, int, int, bytes]]:
            out = []
            for topic, partition, offset in wants:
                parts = self._topic(topic)
                log = parts[partition] if parts and partition < len(parts) else None
                data = log.read_from(offset) if log is not None else b""
                out.append((topic, partition, log.next_offset if log else 0, data))
            return out

        got = collect()
        if not any(d for *_x, d in got) and max_wait > 0:
            event = self._data_event
            try:
                await asyncio.wait_for(event.wait(), max_wait / 1000.0)
                got = collect()
            except asyncio.TimeoutError:
                pass

        w = wire.Writer()
        w.int32(0)  # throttle
        by_topic: dict[str, list[tuple[int, int, bytes]]] = {}
        for topic, partition, hw, data in got:
            by_topic.setdefault(topic, []).append((partition, hw, data))
        w.int32(len(by_topic))
        for topic, plist in by_topic.items():
            w.string(topic)
            w.int32(len(plist))
            for partition, hw, data in plist:
                w.int32(partition).int16(wire.NONE).int64(hw).int64(hw)
                w.array([], lambda w2, _: None)  # aborted txns
                w.bytes_(data)
        return w.build()

    async def _list_offsets(self, r: wire.Reader, version: int) -> bytes:
        r.int32()  # replica
        answers = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                ts = r.int64()
                parts = self._topic(topic)
                log = parts[partition] if parts and partition < len(parts) else None
                if ts == wire.EARLIEST_TIMESTAMP:
                    offset = 0
                else:
                    offset = log.next_offset if log else 0
                answers.append((topic, partition, offset))
        w = wire.Writer()
        w.int32(len(answers))
        for topic, partition, offset in answers:
            w.string(topic).int32(1)
            w.int32(partition).int16(wire.NONE).int64(-1).int64(offset)
        return w.build()

    async def _find_coordinator(self, r: wire.Reader, version: int) -> bytes:
        r.string()  # group
        r.int8()  # type
        return (
            wire.Writer()
            .int32(0)  # throttle
            .int16(wire.NONE)
            .string(None)
            .int32(0)
            .string(self.host)
            .int32(self.port)
            .build()
        )

    async def _offset_commit(self, r: wire.Reader, version: int) -> bytes:
        group = r.string() or ""
        r.int32()  # generation
        r.string()  # member
        r.int64()  # retention
        acks = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                offset = r.int64()
                r.string()  # metadata
                self.committed[(group, topic, partition)] = offset
                acks.append((topic, partition))
        w = wire.Writer()
        w.int32(len(acks))
        for topic, partition in acks:
            w.string(topic).int32(1).int32(partition).int16(wire.NONE)
        return w.build()

    async def _offset_fetch(self, r: wire.Reader, version: int) -> bytes:
        group = r.string() or ""
        answers = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                offset = self.committed.get((group, topic, partition), -1)
                answers.append((topic, partition, offset))
        w = wire.Writer()
        w.int32(len(answers))
        for topic, partition, offset in answers:
            w.string(topic).int32(1)
            w.int32(partition).int64(offset).string(None).int16(wire.NONE)
        return w.build()

    async def _create_topics(self, r: wire.Reader, version: int) -> bytes:
        results = []
        for _ in range(r.int32()):
            name = r.string() or ""
            partitions = r.int32()
            r.int16()  # replication
            r.array(lambda rr: None)  # assignments
            r.array(lambda rr: None)  # configs
            if name in self.topics:
                results.append((name, wire.TOPIC_ALREADY_EXISTS))
            else:
                self.topics[name] = [_PartitionLog() for _ in range(max(partitions, 1))]
                results.append((name, wire.NONE))
        r.int32()  # timeout
        w = wire.Writer()
        w.array(results, lambda w, t: w.string(t[0]).int16(t[1]))
        return w.build()

    async def _delete_topics(self, r: wire.Reader, version: int) -> bytes:
        results = []
        for name in r.array(lambda rr: rr.string()):
            self.topics.pop(name or "", None)
            results.append((name, wire.NONE))
        r.int32()  # timeout
        w = wire.Writer()
        w.array(results, lambda w, t: w.string(t[0]).int16(t[1]))
        return w.build()

    _HANDLERS = {
        wire.METADATA: _metadata,
        wire.PRODUCE: _produce,
        wire.FETCH: _fetch,
        wire.LIST_OFFSETS: _list_offsets,
        wire.FIND_COORDINATOR: _find_coordinator,
        wire.OFFSET_COMMIT: _offset_commit,
        wire.OFFSET_FETCH: _offset_fetch,
        wire.CREATE_TOPICS: _create_topics,
        wire.DELETE_TOPICS: _delete_topics,
    }
