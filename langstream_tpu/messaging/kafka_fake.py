"""Protocol-level fake Kafka broker for tests (the `k8s/fake.py` pattern).

Speaks the same wire subset the client in ``kafka.py`` does — Produce v3,
Fetch v4, ListOffsets v1, Metadata v1, OffsetCommit v2, OffsetFetch v1,
FindCoordinator v1, CreateTopics v0, DeleteTopics v0 — over a real asyncio
socket, storing record batches exactly as a broker log does (batches are
fetched back verbatim from the requested offset's containing batch onward,
so the client's "skip records below fetch_offset" path is exercised).

This stands in for the reference's testcontainers Kafka (KafkaContainerTest
tier) in an image with no JVM and no network egress.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Optional

from langstream_tpu.messaging import kafka_protocol as wire


@dataclass
class _GroupMember:
    member_id: str
    subscription: bytes = b""
    session_timeout_ms: int = 10_000
    rebalance_timeout_ms: int = 20_000
    last_heartbeat: float = 0.0
    join_future: Optional[asyncio.Future] = None
    sync_future: Optional[asyncio.Future] = None


@dataclass
class _Group:
    """Coordinator state for one consumer group (GroupCoordinator semantics:
    Empty → PreparingRebalance → CompletingRebalance → Stable)."""

    state: str = "Empty"
    generation: int = 0
    leader: Optional[str] = None
    protocol_name: Optional[str] = None
    members: dict[str, _GroupMember] = field(default_factory=dict)
    assignments: dict[str, bytes] = field(default_factory=dict)
    completer: Optional[asyncio.Task] = None
    member_seq: int = 0


@dataclass
class _PartitionLog:
    batches: list[tuple[int, int, bytes]] = field(default_factory=list)
    # (base_offset, record_count, batch_bytes)
    next_offset: int = 0

    def append(self, records: list[wire.WireRecord]) -> int:
        base = self.next_offset
        data = wire.encode_record_batch(records, base_offset=base)
        self.batches.append((base, len(records), data))
        self.next_offset += len(records)
        return base

    def read_from(self, offset: int) -> bytes:
        out = []
        for base, count, data in self.batches:
            if base + count > offset:  # batch contains offsets >= requested
                out.append(data)
        return b"".join(out)


class FakeKafkaBroker:
    """Single-node fake broker; node id 0, coordinator for every group."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self.topics: dict[str, list[_PartitionLog]] = {}
        self.committed: dict[tuple[str, str, int], int] = {}
        self.groups: dict[str, _Group] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._data_event = asyncio.Event()
        self._writers: set[asyncio.StreamWriter] = set()
        self._sweeper: Optional[asyncio.Task] = None
        # protocol-visible knobs for tests
        self.auto_create_topics = True
        # one-shot fetch error injection: (topic, partition) → error code
        # (e.g. NOT_LEADER_FOR_PARTITION to simulate failover)
        self.fetch_errors: dict[tuple[str, int], int] = {}

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> "FakeKafkaBroker":
        self._server = await asyncio.start_server(self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._sweeper = asyncio.create_task(self._session_sweeper())
        return self

    async def stop(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            self._sweeper = None
        for group in self.groups.values():
            if group.completer is not None:
                group.completer.cancel()
        if self._server is not None:
            self._server.close()
            # force-close live client connections — wait_closed() waits for
            # every handler, and a leaked client would park it forever
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def bootstrap(self) -> str:
        return f"{self.host}:{self.port}"

    # -- storage ------------------------------------------------------------

    def _topic(self, name: str, create: Optional[bool] = None) -> Optional[list[_PartitionLog]]:
        create = self.auto_create_topics if create is None else create
        t = self.topics.get(name)
        if t is None and create:
            t = [_PartitionLog()]
            self.topics[name] = t
        return t

    # -- connection handling -------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        try:
            while True:
                try:
                    size = int.from_bytes(await reader.readexactly(4), "big")
                except asyncio.IncompleteReadError:
                    return
                frame = await reader.readexactly(size)
                r = wire.Reader(frame)
                api_key, version, correlation, _client = wire.decode_request_header(r)
                handler = self._HANDLERS.get(api_key)
                if handler is None:
                    raise RuntimeError(f"fake broker: unsupported api {api_key}")
                body = await handler(self, r, version)
                out = wire.Writer().int32(correlation).raw(body).build()
                writer.write(len(out).to_bytes(4, "big") + out)
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            return
        finally:
            self._writers.discard(writer)
            writer.close()

    # -- handlers ------------------------------------------------------------

    async def _metadata(self, r: wire.Reader, version: int) -> bytes:
        topics = r.array(lambda rr: rr.string())
        if not topics:
            topics = sorted(self.topics)
        w = wire.Writer()
        w.array(
            [(0, self.host, self.port)],
            lambda w, b: w.int32(b[0]).string(b[1]).int32(b[2]).string(None),
        )
        w.int32(0)  # controller
        w.int32(len(topics))
        for name in topics:
            parts = self._topic(name, create=False)
            if parts is None:
                w.int16(wire.UNKNOWN_TOPIC_OR_PARTITION).string(name).boolean(False)
                w.int32(0)
                continue
            w.int16(wire.NONE).string(name).boolean(False)
            w.int32(len(parts))
            for pid in range(len(parts)):
                w.int16(wire.NONE).int32(pid).int32(0)  # leader = node 0
                w.array([0], lambda w2, x: w2.int32(x))  # replicas
                w.array([0], lambda w2, x: w2.int32(x))  # isr
        return w.build()

    async def _produce(self, r: wire.Reader, version: int) -> bytes:
        r.string()  # transactional id
        r.int16()  # acks
        r.int32()  # timeout
        responses = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                data = r.bytes_() or b""
                records = wire.decode_record_batches(data)
                parts = self._topic(topic)
                assert parts is not None
                while partition >= len(parts):
                    parts.append(_PartitionLog())
                base = parts[partition].append(records)
                responses.append((topic, partition, wire.NONE, base))
        self._data_event.set()
        self._data_event = asyncio.Event()
        w = wire.Writer()
        w.int32(len(responses))
        for topic, partition, err, base in responses:
            w.string(topic)
            w.int32(1)
            w.int32(partition).int16(err).int64(base).int64(-1)
        w.int32(0)  # throttle
        return w.build()

    async def _fetch(self, r: wire.Reader, version: int) -> bytes:
        r.int32()  # replica
        max_wait = r.int32()
        r.int32()  # min bytes
        r.int32()  # max bytes
        r.int8()  # isolation
        wants: list[tuple[str, int, int]] = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                offset = r.int64()
                r.int32()  # partition max bytes
                wants.append((topic, partition, offset))

        def collect() -> list[tuple[str, int, int, bytes]]:
            out = []
            for topic, partition, offset in wants:
                parts = self._topic(topic)
                log = parts[partition] if parts and partition < len(parts) else None
                data = log.read_from(offset) if log is not None else b""
                out.append((topic, partition, log.next_offset if log else 0, data))
            return out

        got = collect()
        if not any(d for *_x, d in got) and max_wait > 0:
            event = self._data_event
            try:
                await asyncio.wait_for(event.wait(), max_wait / 1000.0)
                got = collect()
            except asyncio.TimeoutError:
                pass

        w = wire.Writer()
        w.int32(0)  # throttle
        by_topic: dict[str, list[tuple[int, int, bytes]]] = {}
        for topic, partition, hw, data in got:
            by_topic.setdefault(topic, []).append((partition, hw, data))
        w.int32(len(by_topic))
        for topic, plist in by_topic.items():
            w.string(topic)
            w.int32(len(plist))
            for partition, hw, data in plist:
                err = self.fetch_errors.pop((topic, partition), wire.NONE)
                w.int32(partition).int16(err).int64(hw).int64(hw)
                w.array([], lambda w2, _: None)  # aborted txns
                w.bytes_(b"" if err != wire.NONE else data)
        return w.build()

    async def _list_offsets(self, r: wire.Reader, version: int) -> bytes:
        r.int32()  # replica
        answers = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                ts = r.int64()
                parts = self._topic(topic)
                log = parts[partition] if parts and partition < len(parts) else None
                if ts == wire.EARLIEST_TIMESTAMP:
                    offset = 0
                else:
                    offset = log.next_offset if log else 0
                answers.append((topic, partition, offset))
        w = wire.Writer()
        w.int32(len(answers))
        for topic, partition, offset in answers:
            w.string(topic).int32(1)
            w.int32(partition).int16(wire.NONE).int64(-1).int64(offset)
        return w.build()

    async def _find_coordinator(self, r: wire.Reader, version: int) -> bytes:
        r.string()  # group
        r.int8()  # type
        return (
            wire.Writer()
            .int32(0)  # throttle
            .int16(wire.NONE)
            .string(None)
            .int32(0)
            .string(self.host)
            .int32(self.port)
            .build()
        )

    async def _offset_commit(self, r: wire.Reader, version: int) -> bytes:
        group_id = r.string() or ""
        generation = r.int32()
        member_id = r.string() or ""
        r.int64()  # retention
        # generation -1 is the simple-consumer convention (no membership
        # fencing); a real generation is checked against the live group so a
        # zombie replica can't commit after being rebalanced away
        err = wire.NONE
        if generation >= 0:
            group = self.groups.get(group_id)
            if group is None or member_id not in group.members:
                err = wire.UNKNOWN_MEMBER_ID
            elif generation != group.generation:
                err = wire.ILLEGAL_GENERATION
        acks = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                offset = r.int64()
                r.string()  # metadata
                if err == wire.NONE:
                    self.committed[(group_id, topic, partition)] = offset
                acks.append((topic, partition))
        w = wire.Writer()
        w.int32(len(acks))
        for topic, partition in acks:
            w.string(topic).int32(1).int32(partition).int16(err)
        return w.build()

    async def _offset_fetch(self, r: wire.Reader, version: int) -> bytes:
        group = r.string() or ""
        answers = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                offset = self.committed.get((group, topic, partition), -1)
                answers.append((topic, partition, offset))
        w = wire.Writer()
        w.int32(len(answers))
        for topic, partition, offset in answers:
            w.string(topic).int32(1)
            w.int32(partition).int64(offset).string(None).int16(wire.NONE)
        return w.build()

    # -- group coordinator ---------------------------------------------------

    def _trigger_rebalance(self, group: _Group) -> None:
        """Move to PreparingRebalance and spawn the join-barrier completer.
        Pending SyncGroup waiters are bounced with REBALANCE_IN_PROGRESS so
        they rejoin under the new generation."""
        for m in group.members.values():
            if m.sync_future is not None and not m.sync_future.done():
                m.sync_future.set_result((wire.REBALANCE_IN_PROGRESS, b""))
                m.sync_future = None
        if group.state == "PreparingRebalance":
            return
        group.state = "PreparingRebalance"
        group.completer = asyncio.create_task(self._complete_join(group))

    async def _complete_join(self, group: _Group) -> None:
        """Wait for every known member to rejoin (or its rebalance timeout),
        evict stragglers, bump the generation, and answer all joiners."""
        loop = asyncio.get_running_loop()
        timeout = max(
            (m.rebalance_timeout_ms for m in group.members.values()), default=3000
        )
        deadline = loop.time() + timeout / 1000.0
        while loop.time() < deadline:
            if group.members and all(
                m.join_future is not None for m in group.members.values()
            ):
                break
            await asyncio.sleep(0.01)
        for mid in [m for m, st in group.members.items() if st.join_future is None]:
            del group.members[mid]
        if not group.members:
            group.state = "Empty"
            group.leader = None
            return
        group.generation += 1
        group.leader = sorted(group.members)[0]
        group.state = "CompletingRebalance"
        roster = [(mid, m.subscription) for mid, m in sorted(group.members.items())]
        now = loop.time()
        for mid, m in group.members.items():
            m.last_heartbeat = now
            fut, m.join_future = m.join_future, None
            if fut is not None and not fut.done():
                fut.set_result((group.generation, group.leader, roster))

    async def _join_group(self, r: wire.Reader, version: int) -> bytes:
        group_id = r.string() or ""
        session_timeout = r.int32()
        rebalance_timeout = r.int32() if version >= 1 else session_timeout
        member_id = r.string() or ""
        protocol_type = r.string() or ""
        protocols = []
        for _ in range(r.int32()):
            protocols.append((r.string() or "", r.bytes_() or b""))

        group = self.groups.setdefault(group_id, _Group())
        if not member_id:
            group.member_seq += 1
            member_id = f"member-{group.member_seq}"
        member = group.members.get(member_id)
        if member is None:
            member = _GroupMember(member_id)
            group.members[member_id] = member
        member.session_timeout_ms = session_timeout
        member.rebalance_timeout_ms = rebalance_timeout
        member.subscription = protocols[0][1] if protocols else b""
        member.last_heartbeat = asyncio.get_running_loop().time()
        group.protocol_name = protocols[0][0] if protocols else "range"
        member.join_future = asyncio.get_running_loop().create_future()
        self._trigger_rebalance(group)

        try:
            generation, leader, roster = await asyncio.wait_for(
                member.join_future, timeout=rebalance_timeout / 1000.0 + 1.0
            )
        except asyncio.TimeoutError:
            group.members.pop(member_id, None)
            return (
                wire.Writer().int32(0).int16(wire.REBALANCE_IN_PROGRESS)
                .int32(-1).string(None).string(None).string(member_id)
                .int32(0).build()
            )
        w = wire.Writer()
        w.int32(0)  # throttle
        w.int16(wire.NONE)
        w.int32(generation)
        w.string(group.protocol_name)
        w.string(leader)
        w.string(member_id)
        members = roster if member_id == leader else []
        w.array(members, lambda w2, m: w2.string(m[0]).bytes_(m[1]))
        return w.build()

    async def _sync_group(self, r: wire.Reader, version: int) -> bytes:
        group_id = r.string() or ""
        generation = r.int32()
        member_id = r.string() or ""
        assignments = []
        for _ in range(r.int32()):
            assignments.append((r.string() or "", r.bytes_() or b""))

        def reply(err: int, data: bytes = b"") -> bytes:
            return wire.Writer().int32(0).int16(err).bytes_(data).build()

        group = self.groups.get(group_id)
        if group is None or member_id not in group.members:
            return reply(wire.UNKNOWN_MEMBER_ID)
        if generation != group.generation:
            return reply(wire.ILLEGAL_GENERATION)
        if group.state == "PreparingRebalance":
            return reply(wire.REBALANCE_IN_PROGRESS)
        member = group.members[member_id]
        if member_id == group.leader:
            # leader distributes: store (late followers read it from the
            # group), resolve every parked follower, then Stable
            group.assignments = dict(assignments)
            group.state = "Stable"
            for mid, m in group.members.items():
                data = group.assignments.get(mid, b"")
                if m.sync_future is not None and not m.sync_future.done():
                    m.sync_future.set_result((wire.NONE, data))
                    m.sync_future = None
            return reply(wire.NONE, group.assignments.get(member_id, b""))
        if group.state == "Stable":
            # follower syncing after the leader already distributed (the
            # common ordering): serve its stored slice
            return reply(wire.NONE, group.assignments.get(member_id, b""))
        member.sync_future = asyncio.get_running_loop().create_future()
        try:
            err, data = await asyncio.wait_for(
                member.sync_future, timeout=member.rebalance_timeout_ms / 1000.0 + 1.0
            )
        except asyncio.TimeoutError:
            return reply(wire.REBALANCE_IN_PROGRESS)
        return reply(err, data)

    async def _heartbeat(self, r: wire.Reader, version: int) -> bytes:
        group_id = r.string() or ""
        generation = r.int32()
        member_id = r.string() or ""
        group = self.groups.get(group_id)
        err = wire.NONE
        if group is None or member_id not in group.members:
            err = wire.UNKNOWN_MEMBER_ID
        elif generation != group.generation:
            err = wire.ILLEGAL_GENERATION
        elif group.state == "PreparingRebalance":
            err = wire.REBALANCE_IN_PROGRESS
        if group is not None and member_id in group.members:
            group.members[member_id].last_heartbeat = asyncio.get_running_loop().time()
        return wire.Writer().int32(0).int16(err).build()

    async def _leave_group(self, r: wire.Reader, version: int) -> bytes:
        group_id = r.string() or ""
        member_id = r.string() or ""
        group = self.groups.get(group_id)
        if group is not None and member_id in group.members:
            del group.members[member_id]
            if group.members:
                self._trigger_rebalance(group)
            else:
                group.state = "Empty"
                group.leader = None
        return wire.Writer().int32(0).int16(wire.NONE).build()

    async def _session_sweeper(self) -> None:
        """Evict members whose session timed out (crashed without
        LeaveGroup) and hand their partitions to the survivors."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(0.1)
            now = loop.time()
            for group in self.groups.values():
                expired = [
                    mid
                    for mid, m in group.members.items()
                    if m.join_future is None
                    and now - m.last_heartbeat > m.session_timeout_ms / 1000.0
                ]
                if expired:
                    for mid in expired:
                        del group.members[mid]
                    if group.members:
                        self._trigger_rebalance(group)
                    else:
                        group.state = "Empty"
                        group.leader = None

    async def _create_topics(self, r: wire.Reader, version: int) -> bytes:
        results = []
        for _ in range(r.int32()):
            name = r.string() or ""
            partitions = r.int32()
            r.int16()  # replication
            r.array(lambda rr: None)  # assignments
            r.array(lambda rr: None)  # configs
            if name in self.topics:
                results.append((name, wire.TOPIC_ALREADY_EXISTS))
            else:
                self.topics[name] = [_PartitionLog() for _ in range(max(partitions, 1))]
                results.append((name, wire.NONE))
        r.int32()  # timeout
        w = wire.Writer()
        w.array(results, lambda w, t: w.string(t[0]).int16(t[1]))
        return w.build()

    async def _delete_topics(self, r: wire.Reader, version: int) -> bytes:
        results = []
        for name in r.array(lambda rr: rr.string()):
            self.topics.pop(name or "", None)
            results.append((name, wire.NONE))
        r.int32()  # timeout
        w = wire.Writer()
        w.array(results, lambda w, t: w.string(t[0]).int16(t[1]))
        return w.build()

    _HANDLERS = {
        wire.METADATA: _metadata,
        wire.PRODUCE: _produce,
        wire.FETCH: _fetch,
        wire.LIST_OFFSETS: _list_offsets,
        wire.FIND_COORDINATOR: _find_coordinator,
        wire.OFFSET_COMMIT: _offset_commit,
        wire.OFFSET_FETCH: _offset_fetch,
        wire.JOIN_GROUP: _join_group,
        wire.SYNC_GROUP: _sync_group,
        wire.HEARTBEAT: _heartbeat,
        wire.LEAVE_GROUP: _leave_group,
        wire.CREATE_TOPICS: _create_topics,
        wire.DELETE_TOPICS: _delete_topics,
    }
