"""Pulsar topic-connections runtime (gated: requires the pulsar client).

Parity: reference ``langstream-pulsar/`` + ``langstream-pulsar-runtime/``
(PulsarTopicConnectionsRuntimeProvider, 760 LoC) — same TopicConnections
contracts on Pulsar topics/subscriptions.

The container image ships no pulsar client; importing this module without
``pulsar`` raises ImportError and the registry silently skips registration
(``streamingCluster.type: pulsar`` then reports the known types). The
ordered-commit semantics are identical to the in-memory broker's
(contiguous-prefix via langstream_tpu.native.OffsetTracker), so they are
covered by the memory-broker tests.
"""

from __future__ import annotations

try:
    import pulsar  # type: ignore  # noqa: F401
except ImportError as e:  # pragma: no cover
    raise ImportError(
        "pulsar streaming runtime requires the 'pulsar-client' package, which "
        "is not installed in this image; use streamingCluster.type=memory"
    ) from e

from typing import Any, Optional

from langstream_tpu.api.topics import (
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffsetPosition,
    TopicProducer,
    TopicReader,
)


class PulsarTopicConnectionsRuntime(TopicConnectionsRuntime):  # pragma: no cover
    """Skeleton wired to the pulsar client when available (not shipped here)."""

    def __init__(self) -> None:
        self._service_url = "pulsar://localhost:6650"

    async def init(self, streaming_cluster_config: dict[str, Any]) -> None:
        self._service_url = streaming_cluster_config.get(
            "service-url", self._service_url
        )

    def create_consumer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicConsumer:
        raise NotImplementedError("pulsar data plane lands when a client lib is available")

    def create_producer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicProducer:
        raise NotImplementedError("pulsar data plane lands when a client lib is available")

    def create_reader(
        self,
        topic: str,
        initial_position: TopicOffsetPosition = TopicOffsetPosition(),
        config: Optional[dict[str, Any]] = None,
    ) -> TopicReader:
        raise NotImplementedError("pulsar data plane lands when a client lib is available")

    def create_topic_admin(self) -> TopicAdmin:
        raise NotImplementedError("pulsar data plane lands when a client lib is available")
