"""Pulsar topic-connections runtime over a pure-asyncio wire-protocol client.

Parity: reference ``langstream-pulsar/`` + ``langstream-pulsar-runtime/``
(`PulsarTopicConnectionsRuntimeProvider.java`) — consumer with explicit ack,
producer with key routing, offset-addressed reader for the gateway, admin
topic CRUD. No client library: the binary protocol codec is
``pulsar_protocol.py`` (stdlib only) and works against a real broker or the
protocol-level fake (``pulsar_fake.py``).

Design notes:
- One multiplexed connection per broker (Pulsar's model): producers,
  consumers and requests share it; the reader task dispatches by
  consumer_id / request_id / (producer_id, sequence_id).
- Work splitting across replicas uses a SHARED subscription named after the
  agent id — the broker round-robins messages among the subscription's
  consumers, pulsar's native analog of a Kafka consumer group. Acks are
  individual (per message id), so out-of-order acks need no client-side
  prefix tracker; the broker's cursor owns redelivery.
- Partitioned topics are N internal topics named ``{topic}-partition-{i}``
  (Pulsar's own model). The producer routes keyed messages by Java
  ``String.hashCode`` (pulsar's default key router) and round-robins the
  rest; the consumer subscribes to every partition sub-topic.
- Values/keys serialize exactly like the Kafka runtime (UTF-8 str, raw
  bytes, compact JSON, Avro-with-schema-property) so apps can switch
  brokers without re-encoding.
- Topic admin is the REST API (``/admin/v2/persistent/...``) like the
  reference's PulsarAdmin — the binary protocol has no topic CRUD.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time
import uuid
from collections import deque
from typing import Any, Optional
from urllib.parse import urlparse

from langstream_tpu.api.record import Header, Record
from langstream_tpu.api.topics import (
    TopicAdmin,
    TopicConnectionsRuntime,
    TopicConsumer,
    TopicOffsetPosition,
    TopicProducer,
    TopicReader,
    TopicReadResult,
)
from langstream_tpu.messaging import pulsar_protocol as wire
from langstream_tpu.messaging.kafka import (
    _AVRO_KEY_SCHEMA_HEADER,
    _AVRO_VALUE_SCHEMA_HEADER,
    _decode_datum,
    _encode_datum,
    _schema_from_header,
)
from langstream_tpu.messaging.memory import ConsumedRecord

log = logging.getLogger(__name__)

SUB_EXCLUSIVE = 0
SUB_SHARED = 1
POSITION_LATEST = 0
POSITION_EARLIEST = 1


def java_string_hash(s: str) -> int:
    """Java ``String.hashCode`` — pulsar's default key router hash, kept so
    keyed records co-partition with JVM producers sharing the topic."""
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & 0xFFFFFFFF
    if h >= 1 << 31:
        h -= 1 << 32
    return h


def full_topic(name: str, tenant: str = "public", namespace: str = "default") -> str:
    if "://" in name:
        return name
    return f"persistent://{tenant}/{namespace}/{name}"


def _pack_mid(ledger_id: int, entry_id: int) -> int:
    """Message id → opaque int for the reader's offset map (gateway resume).
    32 bits of entry per ledger (brokers roll ledgers long before 4G
    entries; a guard raises rather than silently aliasing a different
    message the way the old 20-bit packing could)."""
    if not 0 <= entry_id < 1 << 32:
        raise ValueError(f"entry_id {entry_id} exceeds the 32-bit packing")
    return (ledger_id << 32) | entry_id


def _unpack_mid(packed: int) -> tuple[int, int]:
    return packed >> 32, packed & 0xFFFFFFFF


class PulsarProtocolError(RuntimeError):
    pass


class PulsarConnection:
    """One multiplexed broker connection (CONNECT handshake + dispatch)."""

    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._pending: dict[int, asyncio.Future] = {}  # request_id → future
        self._receipts: dict[tuple[int, int], asyncio.Future] = {}
        self._consumer_queues: dict[int, asyncio.Queue] = {}
        self._write_lock = asyncio.Lock()
        self._request_ids = itertools.count(1)
        self.max_message_size = 5 * 1024 * 1024
        # set when the dispatch loop exits: the client discards dead
        # connections and re-dials instead of reusing a poisoned one
        # (mirrors pravega's reconnect handling)
        self.dead = False

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        await self._send(
            wire.encode_command(
                "connect",
                {
                    "client_version": "langstream-tpu",
                    "protocol_version": wire.PROTOCOL_VERSION,
                },
            )
        )
        name, fields, _, _ = await self._read_frame()
        if name != "connected":
            raise PulsarProtocolError(f"expected CONNECTED, got {name}: {fields}")
        self.max_message_size = int(
            fields.get("max_message_size", self.max_message_size)
        )
        self._reader_task = asyncio.create_task(self._dispatch_loop())

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._reader_task = None
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None

    # -- plumbing -----------------------------------------------------------

    async def _send(
        self, command: bytes, metadata: bytes = b"", payload: bytes = b""
    ) -> None:
        assert self._writer is not None, "not connected"
        data = (
            wire.payload_frame(command, metadata, payload)
            if metadata
            else wire.frame(command)
        )
        async with self._write_lock:
            self._writer.write(data)
            await self._writer.drain()

    async def _read_frame(self) -> tuple[str, dict, Optional[dict], bytes]:
        assert self._reader is not None
        header = await self._reader.readexactly(4)
        total = int.from_bytes(header, "big")
        body = await self._reader.readexactly(total)
        return wire.split_frame(body)

    async def _dispatch_loop(self) -> None:
        try:
            while True:
                name, fields, metadata, payload = await self._read_frame()
                if name == "ping":
                    await self._send(wire.encode_command("pong", {}))
                elif name == "message":
                    queue = self._consumer_queues.get(int(fields["consumer_id"]))
                    if queue is not None:
                        queue.put_nowait((fields, metadata, payload))
                elif name == "send_receipt":
                    key = (int(fields["producer_id"]), int(fields["sequence_id"]))
                    fut = self._receipts.pop(key, None)
                    if fut is not None and not fut.done():
                        fut.set_result(fields)
                elif name == "send_error":
                    key = (int(fields["producer_id"]), int(fields["sequence_id"]))
                    fut = self._receipts.pop(key, None)
                    if fut is not None and not fut.done():
                        fut.set_exception(
                            PulsarProtocolError(fields.get("message", "send error"))
                        )
                elif "request_id" in fields:
                    fut = self._pending.pop(int(fields["request_id"]), None)
                    if fut is not None and not fut.done():
                        if name == "error":
                            fut.set_exception(
                                PulsarProtocolError(fields.get("message", "error"))
                            )
                        else:
                            fut.set_result((name, fields))
        except (asyncio.CancelledError, asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self.dead = True
            err = PulsarProtocolError("connection closed")
            for fut in list(self._pending.values()) + list(self._receipts.values()):
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            self._receipts.clear()

    async def request(self, name: str, fields: dict[str, Any]) -> tuple[str, dict]:
        request_id = next(self._request_ids)
        fields = {**fields, "request_id": request_id}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        try:
            await self._send(wire.encode_command(name, fields))
            return await asyncio.wait_for(fut, timeout=30)
        finally:
            # wait_for cancellation/timeouts must not leak the entry: ids are
            # never reused, so nothing else would ever pop it
            self._pending.pop(request_id, None)

    async def send_message(
        self,
        producer_id: int,
        sequence_id: int,
        metadata: dict[str, Any],
        payload: bytes,
    ) -> dict:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._receipts[(producer_id, sequence_id)] = fut
        try:
            await self._send(
                wire.encode_command(
                    "send",
                    {
                        "producer_id": producer_id,
                        "sequence_id": sequence_id,
                        "num_messages": 1,
                    },
                ),
                wire.encode_message(wire.MESSAGE_METADATA, metadata),
                payload,
            )
            return await asyncio.wait_for(fut, timeout=30)
        finally:
            self._receipts.pop((producer_id, sequence_id), None)

    def register_consumer(self, consumer_id: int) -> asyncio.Queue:
        queue: asyncio.Queue = asyncio.Queue()
        self._consumer_queues[consumer_id] = queue
        return queue

    def drop_consumer(self, consumer_id: int) -> None:
        self._consumer_queues.pop(consumer_id, None)

    async def fire(self, name: str, fields: dict[str, Any]) -> None:
        await self._send(wire.encode_command(name, fields))


class PulsarClient:
    """Shared connection + id allocation + admin REST."""

    def __init__(
        self,
        service_url: str = "pulsar://localhost:6650",
        admin_url: str = "http://localhost:8080",
        tenant: str = "public",
        namespace: str = "default",
    ) -> None:
        parsed = urlparse(service_url)
        self.host = parsed.hostname or "localhost"
        self.port = parsed.port or 6650
        self.admin_url = admin_url.rstrip("/")
        self.tenant = tenant
        self.namespace = namespace
        # one shared connection per broker address: the service_url broker is
        # the lookup entry point; topic traffic goes to each topic's OWNER
        # broker (conn_for_topic), which in a multi-broker cluster is not
        # necessarily the one service_url points at
        self._conns: dict[tuple[str, int], PulsarConnection] = {}
        self._topic_conns: dict[str, PulsarConnection] = {}
        self._ids = itertools.count(1)
        self._lock = asyncio.Lock()

    def full(self, topic: str) -> str:
        return full_topic(topic, self.tenant, self.namespace)

    async def _conn_to(self, host: str, port: int) -> PulsarConnection:
        async with self._lock:
            conn = self._conns.get((host, port))
            if conn is not None and conn.dead:
                # dropped broker connection: discard and re-dial — reusing
                # it would fail every request with "connection closed" until
                # process restart. Topic→conn cache entries pointing at the
                # dead object are purged so conn_for_topic re-LOOKUPs.
                await conn.close()
                self._conns.pop((host, port), None)
                for topic in [
                    t for t, c in self._topic_conns.items() if c is conn
                ]:
                    self._topic_conns.pop(topic, None)
                conn = None
            if conn is None:
                conn = PulsarConnection(host, port)
                await conn.connect()
                self._conns[(host, port)] = conn
            return conn

    async def conn(self) -> PulsarConnection:
        """The lookup/metadata connection (the service_url broker)."""
        return await self._conn_to(self.host, self.port)

    async def conn_for_topic(self, topic: str) -> PulsarConnection:
        """LOOKUP the topic's owner broker and return a connection to it,
        following redirects (response 0 = redirect, 1 = connect here).
        ``topic`` must be a fully-qualified data topic name."""
        cached = self._topic_conns.get(topic)
        if cached is not None and not cached.dead:
            return cached
        if cached is not None:
            self._topic_conns.pop(topic, None)
        conn = await self.conn()
        authoritative = 0
        for _ in range(8):
            _, fields = await conn.request(
                "lookup", {"topic": topic, "authoritative": authoritative}
            )
            response = int(fields.get("response", 2))
            if response == 2:
                raise PulsarProtocolError(f"lookup failed for {topic}")
            url = fields.get("broker_service_url") or ""
            parsed = urlparse(url) if url else None
            host = (parsed.hostname if parsed else None) or self.host
            port = (parsed.port if parsed else None) or self.port
            target = await self._conn_to(host, port)
            if response == 1:  # connect: this broker owns the topic
                self._topic_conns[topic] = target
                return target
            conn = target  # redirect: re-issue the lookup there
            authoritative = int(fields.get("authoritative", 0))
        raise PulsarProtocolError(f"lookup redirect loop for {topic}")

    def next_id(self) -> int:
        return next(self._ids)

    async def close(self) -> None:
        for conn in self._conns.values():
            await conn.close()
        self._conns.clear()
        self._topic_conns.clear()
        http = getattr(self, "_http", None)
        if http is not None and not http.closed:
            await http.close()

    async def partitions(self, topic: str) -> int:
        """0 = non-partitioned; N>0 = partitioned with N sub-topics."""
        conn = await self.conn()
        _, fields = await conn.request(
            "partitioned_metadata", {"topic": self.full(topic)}
        )
        return int(fields.get("partitions", 0))

    def data_topics(self, topic: str, partitions: int) -> list[str]:
        base = self.full(topic)
        if partitions <= 0:
            return [base]
        return [f"{base}-partition-{i}" for i in range(partitions)]

    # -- admin REST (the PulsarAdmin surface) -------------------------------

    async def _admin_session(self):
        import aiohttp

        if getattr(self, "_http", None) is None or self._http.closed:
            self._http = aiohttp.ClientSession()
        return self._http

    async def admin_request(
        self, method: str, path: str, body: Optional[bytes] = None
    ) -> tuple[int, bytes]:
        session = await self._admin_session()
        async with session.request(
            method,
            f"{self.admin_url}/admin/v2{path}",
            data=body,
            headers={"Content-Type": "application/json"},
        ) as resp:
            return resp.status, await resp.read()


def _record_to_payload(
    record: Record,
) -> tuple[bytes, Optional[str], list[dict], bool]:
    """Record → (payload, partition_key, properties, key_is_b64). Avro
    schemas travel as
    properties (pulsar analog of the Kafka runtime's schema headers)."""
    from langstream_tpu.api.avro import AvroValue

    properties: list[dict] = []
    for h in record.headers:
        encoded = _encode_datum(h.value)
        properties.append(
            {
                "key": h.key,
                "value": encoded.decode("utf-8", "replace") if encoded else "",
            }
        )
    if isinstance(record.value, AvroValue):
        properties.append(
            {
                "key": _AVRO_VALUE_SCHEMA_HEADER,
                "value": record.value.schema.canonical(),
            }
        )
    if isinstance(record.key, AvroValue):
        properties.append(
            {"key": _AVRO_KEY_SCHEMA_HEADER, "value": record.key.schema.canonical()}
        )
    payload = _encode_datum(record.value) or b""
    key_bytes = _encode_datum(record.key)
    partition_key: Optional[str] = None
    key_b64 = False
    if key_bytes is not None:
        try:
            partition_key = key_bytes.decode("utf-8")
        except UnicodeDecodeError:
            # binary keys (e.g. Avro) ride base64 with the
            # partition_key_b64_encoded flag — pulsar's own convention, so
            # JVM clients hash/route the same b64 string
            import base64

            partition_key = base64.b64encode(key_bytes).decode()
            key_b64 = True
    return payload, partition_key, properties, key_b64


def _message_to_consumed(
    topic: str,
    partition: int,
    local_offset: int,
    metadata: dict,
    payload: bytes,
) -> ConsumedRecord:
    properties = {
        p.get("key", ""): p.get("value", "") for p in metadata.get("properties", [])
    }
    value_schema = properties.pop(_AVRO_VALUE_SCHEMA_HEADER, None)
    key_schema = properties.pop(_AVRO_KEY_SCHEMA_HEADER, None)
    value: Any
    if value_schema:
        from langstream_tpu.api.avro import AvroValue, decode

        schema = _schema_from_header(value_schema.encode())
        value = AvroValue(schema, decode(schema, payload))
    else:
        value = _decode_datum(payload if payload else None)
    key: Any = metadata.get("partition_key")
    key_bytes: Optional[bytes] = None
    if key is not None and metadata.get("partition_key_b64_encoded"):
        import base64

        key_bytes = base64.b64decode(key)
        key = _decode_datum(key_bytes)
    if key_schema and key is not None:
        from langstream_tpu.api.avro import AvroValue, decode

        schema = _schema_from_header(key_schema.encode())
        raw = key_bytes if key_bytes is not None else str(key).encode()
        key = AvroValue(schema, decode(schema, raw))
    headers = tuple(Header(k, v) for k, v in properties.items())
    publish_time = metadata.get("publish_time")
    return ConsumedRecord(
        value=value,
        key=key,
        headers=headers,
        origin=topic,
        timestamp=(publish_time / 1000.0) if publish_time else time.time(),
        partition=partition,
        offset=local_offset,
    )


def _explode_frame(
    metadata: dict, payload: bytes
) -> list[tuple[dict, bytes, int, int]]:
    """One wire frame → its logical messages as (metadata, payload,
    batch_index, batch_emitted) tuples.

    JVM/official producers batch by default (MessageMetadata
    ``num_messages_in_batch`` > 1, payload = repeated
    [size][SingleMessageMetadata][bytes]); treating the whole payload as one
    record would hand agents concatenated garbage. batch_index is -1 for
    unbatched frames. Unsupported compression raises explicitly instead of
    decoding noise."""
    codec = int(metadata.get("compression", 0) or 0)
    if codec != 0:
        raise PulsarProtocolError(
            f"unsupported pulsar compression codec {codec} (this runtime "
            "implements NONE; configure the producer with compression "
            "disabled)"
        )
    n = int(metadata.get("num_messages_in_batch", 1) or 1)
    if n <= 1:
        return [(metadata, payload, -1, 1)]
    entries: list[tuple[dict, bytes, int, int]] = []
    raw = wire.split_batch(payload, n)
    emitted = sum(1 for smm, _ in raw if not smm.get("compacted_out"))
    for i, (smm, data) in enumerate(raw):
        if smm.get("compacted_out"):
            continue
        merged = dict(metadata)
        merged.pop("num_messages_in_batch", None)
        # per-entry metadata is authoritative inside a batch
        merged["properties"] = smm.get("properties", [])
        merged.pop("partition_key", None)
        merged.pop("partition_key_b64_encoded", None)
        if not smm.get("null_partition_key") and "partition_key" in smm:
            merged["partition_key"] = smm["partition_key"]
            if smm.get("partition_key_b64_encoded"):
                merged["partition_key_b64_encoded"] = 1
        if smm.get("event_time"):
            merged["publish_time"] = smm["event_time"]
        if smm.get("null_value"):
            data = b""
        entries.append((merged, data, i, emitted))
    return entries


async def _flow_replenish(sub: dict[str, Any], queue_size: int) -> None:
    """Half-empty permit refill (the standard pulsar client cadence) against
    the subscription's OWNER-broker connection. Shared by the consumer and
    the reader so the grant arithmetic can't drift between them."""
    sub["permits"] -= 1
    if sub["permits"] <= queue_size // 2:
        grant = queue_size - sub["permits"]
        await sub["conn"].fire(
            "flow", {"consumer_id": sub["consumer_id"], "message_permits": grant}
        )
        sub["permits"] += grant


class PulsarTopicConsumer(TopicConsumer):
    """Shared-subscription consumer (the replica work-splitting mode).

    Tracks delivered-but-unacked message ids by a consumer-local index so
    ``commit`` can translate the platform's record acks back into pulsar
    individual acks."""

    def __init__(
        self,
        client: PulsarClient,
        topic: str,
        subscription: str,
        poll_timeout: float = 0.1,
        max_records: int = 100,
        receiver_queue_size: int = 1000,
    ) -> None:
        self.client = client
        self.topic_name = topic
        self.subscription = subscription
        self.poll_timeout = poll_timeout
        self.max_records = max_records
        self.receiver_queue_size = receiver_queue_size
        self._subs: dict[int, dict[str, Any]] = {}  # partition → sub state
        self._offsets = itertools.count(0)
        self._inflight: dict[tuple[int, int], dict] = {}  # (partition, local) → ack info
        # (consumer_id, ledger, entry) → emitted batch entries still unacked
        self._batch_left: dict[tuple[int, int, int], int] = {}
        # exploded batch entries past a read() call's max_records cap
        self._spill: deque = deque()
        self._total_out = 0

    async def start(self) -> None:
        n = await self.client.partitions(self.topic_name)
        for partition, topic in enumerate(self.client.data_topics(self.topic_name, n)):
            conn = await self.client.conn_for_topic(topic)
            consumer_id = self.client.next_id()
            queue = conn.register_consumer(consumer_id)
            await conn.request(
                "subscribe",
                {
                    "topic": topic,
                    "subscription": self.subscription,
                    "sub_type": SUB_SHARED,
                    "consumer_id": consumer_id,
                    "consumer_name": f"{self.subscription}-{uuid.uuid4().hex[:8]}",
                    "durable": 1,
                    "initial_position": POSITION_EARLIEST,
                },
            )
            await conn.fire(
                "flow",
                {
                    "consumer_id": consumer_id,
                    "message_permits": self.receiver_queue_size,
                },
            )
            self._subs[partition if n else -1] = {
                "consumer_id": consumer_id,
                "queue": queue,
                "permits": self.receiver_queue_size,
                "topic": topic,
                "conn": conn,
            }

    async def close(self) -> None:
        for sub in self._subs.values():
            conn = sub["conn"]
            try:
                await conn.request(
                    "close_consumer", {"consumer_id": sub["consumer_id"]}
                )
            except PulsarProtocolError:
                pass
            conn.drop_consumer(sub["consumer_id"])
        self._subs.clear()

    async def _replenish(self, sub: dict[str, Any]) -> None:
        await _flow_replenish(sub, self.receiver_queue_size)

    async def _resubscribe(self, partition: int, sub: dict[str, Any]) -> None:
        """Re-establish a subscription whose broker connection dropped: new
        LOOKUP (ownership may have moved), fresh registration on the new
        connection, full permit grant. Delivered-but-unacked messages
        redeliver through the broker cursor (at-least-once), so the
        pre-drop delivery state is DISCARDED here: stale _inflight entries
        become commit no-ops, stale _batch_left counts would otherwise ack
        a redelivered batch after its FIRST commit (data loss), and spilled
        not-yet-returned entries would duplicate the redelivery."""
        log.warning(
            "pulsar consumer resubscribing to %s after connection loss",
            sub["topic"],
        )
        cid = sub["consumer_id"]
        self._inflight = {
            k: v for k, v in self._inflight.items() if v["consumer_id"] != cid
        }
        self._batch_left = {
            k: v for k, v in self._batch_left.items() if k[0] != cid
        }
        self._spill = deque(e for e in self._spill if e[0] != partition)
        conn = await self.client.conn_for_topic(sub["topic"])
        queue = conn.register_consumer(cid)
        await conn.request(
            "subscribe",
            {
                "topic": sub["topic"],
                "subscription": self.subscription,
                "sub_type": SUB_SHARED,
                "consumer_id": cid,
                "consumer_name": f"{self.subscription}-{uuid.uuid4().hex[:8]}",
                "durable": 1,
                "initial_position": POSITION_EARLIEST,
            },
        )
        await conn.fire(
            "flow",
            {
                "consumer_id": cid,
                "message_permits": self.receiver_queue_size,
            },
        )
        sub.update(
            {"conn": conn, "queue": queue, "permits": self.receiver_queue_size}
        )

    def _emit(self, entry: tuple) -> Record:
        partition, consumer_id, mid, entry_md, entry_payload, bindex, emitted = entry
        local = next(self._offsets)
        self._inflight[(partition, local)] = {
            "consumer_id": consumer_id,
            "message_id": mid,
            "batch_index": bindex,
            "batch_emitted": emitted,
        }
        return _message_to_consumed(
            self.topic_name, partition, local, entry_md, entry_payload
        )

    async def read(self) -> list[Record]:
        out: list[Record] = []
        deadline = asyncio.get_running_loop().time() + self.poll_timeout
        while len(out) < self.max_records:
            # dead-connection handling FIRST: _resubscribe discards the
            # dropped partition's spilled entries (the broker will redeliver
            # them), so the spill must not be emitted before that check runs
            for partition, sub in self._subs.items():
                if sub["conn"].dead:
                    await self._resubscribe(partition, sub)
            # batch entries beyond a previous call's max_records cap wait in
            # the spill and are returned FIRST — a 100-entry JVM batch must
            # not overrun the caller's cap, nor lose its tail
            while self._spill and len(out) < self.max_records:
                out.append(self._emit(self._spill.popleft()))
            if len(out) >= self.max_records:
                break
            got_any = False
            for partition, sub in self._subs.items():
                try:
                    fields, metadata, payload = sub["queue"].get_nowait()
                except asyncio.QueueEmpty:
                    continue
                got_any = True
                mid = fields.get("message_id", {})
                for entry_md, entry_payload, bindex, emitted in _explode_frame(
                    metadata or {}, payload
                ):
                    entry = (
                        partition, sub["consumer_id"], mid, entry_md,
                        entry_payload, bindex, emitted,
                    )
                    if len(out) < self.max_records:
                        out.append(self._emit(entry))
                    else:
                        self._spill.append(entry)
                await self._replenish(sub)
                if len(out) >= self.max_records:
                    break
            if not got_any:
                if out:
                    break
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                await asyncio.sleep(min(0.01, remaining))
        self._total_out += len(out)
        return out

    async def commit(self, records: list[Record]) -> None:
        """Individual acks per message id — the broker cursor owns redelivery,
        so out-of-order acks need no client-side prefix tracking (unlike the
        Kafka runtime's contiguous-prefix commit).

        Batched messages (one wire id covering several records) ack the id
        once EVERY emitted entry of the batch has been committed — the
        broker redelivers whole batches, so an early per-entry ack would
        drop its uncommitted siblings."""
        by_consumer: dict[int, list[dict]] = {}
        for r in records:
            if not isinstance(r, ConsumedRecord):
                continue
            entry = self._inflight.pop((r.partition, r.offset), None)
            if entry is None:
                continue
            mid = entry["message_id"]
            if entry["batch_index"] >= 0:
                key = (
                    entry["consumer_id"],
                    int(mid.get("ledger_id", 0)),
                    int(mid.get("entry_id", 0)),
                )
                left = self._batch_left.get(key, entry["batch_emitted"]) - 1
                if left > 0:
                    self._batch_left[key] = left
                    continue
                self._batch_left.pop(key, None)
            by_consumer.setdefault(entry["consumer_id"], []).append(mid)
        if not by_consumer:
            return
        conns = {s["consumer_id"]: s["conn"] for s in self._subs.values()}
        for consumer_id, mids in by_consumer.items():
            await conns[consumer_id].fire(
                "ack",
                {"consumer_id": consumer_id, "ack_type": 0, "message_id": mids},
            )

    def get_info(self) -> dict[str, Any]:
        return {
            "topic": self.topic_name,
            "subscription": self.subscription,
            "partitions": sorted(self._subs),
            "inflight": len(self._inflight),
        }

    @property
    def total_out(self) -> int:
        return self._total_out


class PulsarTopicProducer(TopicProducer):
    def __init__(self, client: PulsarClient, topic: str) -> None:
        self.client = client
        self.topic_name = topic
        self._producers: list[dict] = []  # one per partition (or single)
        self._sequences = itertools.count(0)
        self._rr = 0
        self._total_in = 0

    async def start(self) -> None:
        n = await self.client.partitions(self.topic_name)
        for topic in self.client.data_topics(self.topic_name, n):
            conn = await self.client.conn_for_topic(topic)
            producer_id = self.client.next_id()
            _, fields = await conn.request(
                "producer", {"topic": topic, "producer_id": producer_id}
            )
            self._producers.append(
                {
                    "producer_id": producer_id,
                    "name": fields.get("producer_name", f"producer-{producer_id}"),
                    "topic": topic,
                    "conn": conn,
                }
            )

    async def close(self) -> None:
        for producer in self._producers:
            try:
                await producer["conn"].request(
                    "close_producer", {"producer_id": producer["producer_id"]}
                )
            except PulsarProtocolError:
                pass
        self._producers.clear()

    async def write(self, record: Record) -> None:
        if not self._producers:
            await self.start()
        try:
            await self._write_once(record)
        except (PulsarProtocolError, ConnectionError) as e:
            if "connection closed" not in str(e):
                raise
            # broker connection dropped mid-write: re-LOOKUP the owners
            # (the client has discarded the dead connection), re-register
            # the producers, retry ONCE — unlimited retries would mask a
            # down cluster
            log.warning("pulsar producer reconnecting after: %s", e)
            self._producers.clear()
            await self.start()
            await self._write_once(record)

    async def _write_once(self, record: Record) -> None:
        payload, partition_key, properties, key_b64 = _record_to_payload(record)
        n = len(self._producers)
        if partition_key is not None and n > 1:
            producer = self._producers[java_string_hash(partition_key) % n]
        else:
            producer = self._producers[self._rr % n]
            self._rr += 1
        sequence_id = next(self._sequences)
        metadata: dict[str, Any] = {
            "producer_name": producer["name"],
            "sequence_id": sequence_id,
            "publish_time": int((record.timestamp or time.time()) * 1000),
            "properties": properties,
            "uncompressed_size": len(payload),
        }
        if partition_key is not None:
            metadata["partition_key"] = partition_key
            if key_b64:
                metadata["partition_key_b64_encoded"] = 1
        await producer["conn"].send_message(
            producer["producer_id"], sequence_id, metadata, payload
        )
        self._total_in += 1

    @property
    def total_in(self) -> int:
        return self._total_in


class PulsarTopicReader(TopicReader):
    """Offset-addressed reader: non-durable exclusive subscription (pulsar's
    Reader is exactly this under the hood) with SEEK for absolute resume."""

    def __init__(
        self,
        client: PulsarClient,
        topic: str,
        initial_position: TopicOffsetPosition,
    ) -> None:
        self.client = client
        self.topic_name = topic
        self.initial_position = initial_position
        self.receiver_queue_size = 1000
        self._subs: dict[int, dict[str, Any]] = {}
        self._pos: dict[int, int] = {}

    async def start(self) -> None:
        n = await self.client.partitions(self.topic_name)
        position = self.initial_position
        for partition, topic in enumerate(self.client.data_topics(self.topic_name, n)):
            p = partition if n else -1
            conn = await self.client.conn_for_topic(topic)
            consumer_id = self.client.next_id()
            queue = conn.register_consumer(consumer_id)
            await conn.request(
                "subscribe",
                {
                    "topic": topic,
                    "subscription": f"reader-{uuid.uuid4().hex[:12]}",
                    "sub_type": SUB_EXCLUSIVE,
                    "consumer_id": consumer_id,
                    "consumer_name": f"reader-{consumer_id}",
                    "durable": 0,
                    "initial_position": (
                        POSITION_EARLIEST
                        if position.position != TopicOffsetPosition.LATEST
                        else POSITION_LATEST
                    ),
                },
            )
            if position.position == "absolute":
                packed = position.offsets.get(p)
                if packed is not None:
                    ledger_id, entry_id = _unpack_mid(packed)
                    await conn.request(
                        "seek",
                        {
                            "consumer_id": consumer_id,
                            "message_id": {
                                "ledger_id": ledger_id,
                                "entry_id": entry_id,
                            },
                        },
                    )
                    self._pos[p] = packed
            await conn.fire(
                "flow",
                {
                    "consumer_id": consumer_id,
                    "message_permits": self.receiver_queue_size,
                },
            )
            self._subs[p] = {
                "consumer_id": consumer_id,
                "queue": queue,
                "permits": self.receiver_queue_size,
                "conn": conn,
                "topic": topic,
            }

    async def close(self) -> None:
        for sub in self._subs.values():
            conn = sub["conn"]
            try:
                await conn.request(
                    "close_consumer", {"consumer_id": sub["consumer_id"]}
                )
            except PulsarProtocolError:
                pass
            conn.drop_consumer(sub["consumer_id"])
        self._subs.clear()

    async def _resubscribe(self, partition: int, sub: dict[str, Any]) -> None:
        """Reader reconnect: fresh non-durable subscription + SEEK back to
        the last delivered position, so resume semantics survive a broker
        connection drop. With no delivered position yet, the configured
        initial position is honored — a LATEST tail-follower must not
        replay the whole retained backlog after a drop."""
        log.warning(
            "pulsar reader resubscribing to %s after connection loss",
            sub["topic"],
        )
        packed = self._pos.get(partition)
        conn = await self.client.conn_for_topic(sub["topic"])
        queue = conn.register_consumer(sub["consumer_id"])
        await conn.request(
            "subscribe",
            {
                "topic": sub["topic"],
                "subscription": f"reader-{uuid.uuid4().hex[:12]}",
                "sub_type": SUB_EXCLUSIVE,
                "consumer_id": sub["consumer_id"],
                "consumer_name": f"reader-{sub['consumer_id']}",
                "durable": 0,
                "initial_position": (
                    POSITION_LATEST
                    if packed is None
                    and self.initial_position.position
                    == TopicOffsetPosition.LATEST
                    else POSITION_EARLIEST
                ),
            },
        )
        if packed is not None:
            ledger_id, entry_id = _unpack_mid(packed)
            await conn.request(
                "seek",
                {
                    "consumer_id": sub["consumer_id"],
                    "message_id": {"ledger_id": ledger_id, "entry_id": entry_id},
                },
            )
        await conn.fire(
            "flow",
            {
                "consumer_id": sub["consumer_id"],
                "message_permits": self.receiver_queue_size,
            },
        )
        sub.update(
            {"conn": conn, "queue": queue, "permits": self.receiver_queue_size}
        )

    async def read(self) -> TopicReadResult:
        out: list[Record] = []
        record_offsets: list[dict[int, int]] = []
        for _ in range(10):
            got_any = False
            for partition, sub in self._subs.items():
                if sub["conn"].dead:
                    await self._resubscribe(partition, sub)
                try:
                    fields, metadata, payload = sub["queue"].get_nowait()
                except asyncio.QueueEmpty:
                    continue
                got_any = True
                mid = fields.get("message_id", {})
                packed = _pack_mid(
                    int(mid.get("ledger_id", 0)), int(mid.get("entry_id", 0))
                )
                self._pos[partition] = packed
                # batched frames emit one record per entry; the resume
                # offset is frame-granular (SEEK re-reads the whole batch)
                for entry_md, entry_payload, _, _ in _explode_frame(
                    metadata or {}, payload
                ):
                    out.append(
                        _message_to_consumed(
                            self.topic_name, partition, packed, entry_md,
                            entry_payload,
                        )
                    )
                    record_offsets.append(dict(self._pos))
                # without the refill the reader stalls permanently after the
                # initial grant drains
                await _flow_replenish(sub, self.receiver_queue_size)
            if not got_any:
                if out:
                    break
                await asyncio.sleep(0.02)
        return TopicReadResult(out, dict(self._pos), record_offsets=record_offsets)


class PulsarTopicAdmin(TopicAdmin):
    """Topic CRUD over the admin REST API (the PulsarAdmin surface)."""

    def __init__(self, client: PulsarClient) -> None:
        self.client = client

    def _path(self, name: str) -> str:
        return f"/persistent/{self.client.tenant}/{self.client.namespace}/{name}"

    async def create_topic(
        self, name: str, partitions: int = 1, options: Optional[dict] = None
    ) -> None:
        if partitions > 1:
            status, body = await self.client.admin_request(
                "PUT", self._path(name) + "/partitions", str(partitions).encode()
            )
        else:
            status, body = await self.client.admin_request("PUT", self._path(name))
        if status not in (200, 204, 409):  # 409 = already exists
            raise RuntimeError(f"create_topic {name}: {status} {body[:200]!r}")

    async def delete_topic(self, name: str) -> None:
        status, body = await self.client.admin_request(
            "DELETE", self._path(name) + "/partitions"
        )
        if status == 404:  # not partitioned → plain topic delete
            status, body = await self.client.admin_request("DELETE", self._path(name))
        if status not in (200, 204, 404):
            raise RuntimeError(f"delete_topic {name}: {status} {body[:200]!r}")

    async def topic_exists(self, name: str) -> bool:
        status, body = await self.client.admin_request(
            "GET", f"/persistent/{self.client.tenant}/{self.client.namespace}"
        )
        if status != 200:
            return False
        topics = json.loads(body)
        full = self.client.full(name)
        return any(
            t == full or t.startswith(full + "-partition-") for t in topics
        )


class PulsarTopicConnectionsRuntime(TopicConnectionsRuntime):
    """`streamingCluster.type: pulsar` (reference
    PulsarTopicConnectionsRuntimeProvider)."""

    def __init__(self) -> None:
        self._client: Optional[PulsarClient] = None
        self._config: dict[str, Any] = {}

    async def init(self, streaming_cluster_config: dict[str, Any]) -> None:
        self._config = streaming_cluster_config or {}

    def client(self) -> PulsarClient:
        if self._client is None:
            cfg = self._config
            service = cfg.get("service", {}).get("serviceUrl") or cfg.get(
                "service-url", "pulsar://localhost:6650"
            )
            admin = cfg.get("admin", {}).get("serviceUrl") or cfg.get(
                "admin-url", "http://localhost:8080"
            )
            self._client = PulsarClient(
                service_url=service,
                admin_url=admin,
                tenant=cfg.get("default-tenant", "public"),
                namespace=cfg.get("default-namespace", "default"),
            )
        return self._client

    async def close(self) -> None:
        if self._client is not None:
            await self._client.close()
            self._client = None

    def create_consumer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicConsumer:
        config = config or {}
        return PulsarTopicConsumer(
            self.client(),
            topic,
            subscription=config.get("subscription", config.get("group", agent_id)),
            poll_timeout=float(config.get("poll-timeout", 0.1)),
            max_records=int(config.get("max-records", 100)),
        )

    def create_producer(
        self, agent_id: str, topic: str, config: Optional[dict[str, Any]] = None
    ) -> TopicProducer:
        return PulsarTopicProducer(self.client(), topic)

    def create_reader(
        self,
        topic: str,
        initial_position: TopicOffsetPosition = TopicOffsetPosition(),
        config: Optional[dict[str, Any]] = None,
    ) -> TopicReader:
        return PulsarTopicReader(self.client(), topic, initial_position)

    def create_topic_admin(self) -> TopicAdmin:
        return PulsarTopicAdmin(self.client())
