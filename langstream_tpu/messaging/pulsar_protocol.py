"""Pulsar binary wire protocol — stdlib-only codec.

Parity: reference `langstream-pulsar-runtime/` speaks to Pulsar through the
official client; this rebuild speaks the broker's binary protocol directly
(the `kafka_protocol.py` approach). The protocol is protobuf-framed
(`PulsarApi.proto`):

    simple command frame:   [totalSize u32][commandSize u32][BaseCommand]
    payload command frame:  [totalSize u32][commandSize u32][BaseCommand]
                            [magic 0x0e01][crc32c u32]
                            [metadataSize u32][MessageMetadata][payload]

where crc32c covers everything after the checksum field. Only the message
fields this runtime uses are modelled; unknown fields are skipped on decode
(standard protobuf forward-compat), so a real broker's richer responses
parse fine.

The protobuf codec here is generic and schema-driven (field tables below),
NOT generated code — there is no protoc dependency and no .proto files at
runtime.
"""

from __future__ import annotations

import struct
from typing import Any, Optional

# ---------------------------------------------------------------------------
# varint + generic protobuf codec
# ---------------------------------------------------------------------------


def write_varint(n: int) -> bytes:
    out = bytearray()
    if n < 0:
        n &= (1 << 64) - 1  # protobuf negative ints are 10-byte varints
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


# Field spec kinds: "varint" (ints/bools/enums), "string", "bytes",
# ("msg", SCHEMA). A trailing "*" on the name marks a repeated field.
Schema = dict[int, tuple[str, Any]]


def encode_message(schema: Schema, values: dict[str, Any]) -> bytes:
    out = bytearray()
    for field_no, (name, kind) in schema.items():
        repeated = name.endswith("*")
        key = name.rstrip("*")
        if key not in values or values[key] is None:
            continue
        items = values[key] if repeated else [values[key]]
        for item in items:
            if kind == "varint":
                out += write_varint(field_no << 3 | 0)
                out += write_varint(int(item))
            elif kind == "string":
                data = item.encode() if isinstance(item, str) else bytes(item)
                out += write_varint(field_no << 3 | 2)
                out += write_varint(len(data))
                out += data
            elif kind == "bytes":
                out += write_varint(field_no << 3 | 2)
                out += write_varint(len(item))
                out += bytes(item)
            elif isinstance(kind, tuple) and kind[0] == "msg":
                body = encode_message(kind[1], item)
                out += write_varint(field_no << 3 | 2)
                out += write_varint(len(body))
                out += body
            else:  # pragma: no cover - schema bug
                raise TypeError(f"bad field kind {kind!r}")
    return bytes(out)


def decode_message(schema: Schema, buf: bytes) -> dict[str, Any]:
    values: dict[str, Any] = {}
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = read_varint(buf, pos)
        field_no, wire_type = tag >> 3, tag & 7
        spec = schema.get(field_no)
        if wire_type == 0:
            raw, pos = read_varint(buf, pos)
            decoded: Any = raw
        elif wire_type == 2:
            length, pos = read_varint(buf, pos)
            chunk = buf[pos : pos + length]
            pos += length
            if spec is None:
                continue
            kind = spec[1]
            if kind == "string":
                decoded = chunk.decode("utf-8", "replace")
            elif kind == "bytes":
                decoded = chunk
            elif isinstance(kind, tuple) and kind[0] == "msg":
                decoded = decode_message(kind[1], chunk)
            else:
                decoded = chunk
        elif wire_type == 5:  # fixed32 — skip (unused by the modelled fields)
            pos += 4
            continue
        elif wire_type == 1:  # fixed64 — skip
            pos += 8
            continue
        else:  # pragma: no cover - malformed
            raise ValueError(f"unsupported wire type {wire_type}")
        if spec is None:
            continue
        name = spec[0]
        if name.endswith("*"):
            values.setdefault(name.rstrip("*"), []).append(decoded)
        else:
            values[name.rstrip("*")] = decoded
    return values


# ---------------------------------------------------------------------------
# message schemas (field numbers from pulsar's PulsarApi.proto)
# ---------------------------------------------------------------------------

MESSAGE_ID: Schema = {
    1: ("ledger_id", "varint"),
    2: ("entry_id", "varint"),
    3: ("partition", "varint"),
    4: ("batch_index", "varint"),
}

KEY_VALUE: Schema = {1: ("key", "string"), 2: ("value", "string")}
KEY_BYTES_VALUE: Schema = {1: ("key", "string"), 2: ("value", "bytes")}

CONNECT: Schema = {
    1: ("client_version", "string"),
    2: ("auth_method", "varint"),
    3: ("auth_data", "bytes"),
    4: ("protocol_version", "varint"),
    5: ("auth_method_name", "string"),
}
CONNECTED: Schema = {
    1: ("server_version", "string"),
    2: ("protocol_version", "varint"),
    3: ("max_message_size", "varint"),
}
SUBSCRIBE: Schema = {
    1: ("topic", "string"),
    2: ("subscription", "string"),
    3: ("sub_type", "varint"),  # 0 exclusive, 1 shared, 2 failover, 3 key_shared
    4: ("consumer_id", "varint"),
    5: ("request_id", "varint"),
    6: ("consumer_name", "string"),
    8: ("durable", "varint"),
    9: ("start_message_id", ("msg", MESSAGE_ID)),
    13: ("initial_position", "varint"),  # 0 latest, 1 earliest
}
PRODUCER: Schema = {
    1: ("topic", "string"),
    2: ("producer_id", "varint"),
    3: ("request_id", "varint"),
    4: ("producer_name", "string"),
}
SEND: Schema = {
    1: ("producer_id", "varint"),
    2: ("sequence_id", "varint"),
    3: ("num_messages", "varint"),
}
SEND_RECEIPT: Schema = {
    1: ("producer_id", "varint"),
    2: ("sequence_id", "varint"),
    3: ("message_id", ("msg", MESSAGE_ID)),
}
SEND_ERROR: Schema = {
    1: ("producer_id", "varint"),
    2: ("sequence_id", "varint"),
    3: ("error", "varint"),
    4: ("message", "string"),
}
MESSAGE: Schema = {
    1: ("consumer_id", "varint"),
    2: ("message_id", ("msg", MESSAGE_ID)),
    3: ("redelivery_count", "varint"),
}
ACK: Schema = {
    1: ("consumer_id", "varint"),
    2: ("ack_type", "varint"),  # 0 individual, 1 cumulative
    3: ("message_id*", ("msg", MESSAGE_ID)),
}
FLOW: Schema = {
    1: ("consumer_id", "varint"),
    2: ("message_permits", "varint"),
}
UNSUBSCRIBE: Schema = {
    1: ("consumer_id", "varint"),
    2: ("request_id", "varint"),
}
SUCCESS: Schema = {1: ("request_id", "varint")}
ERROR: Schema = {
    1: ("request_id", "varint"),
    2: ("error", "varint"),
    3: ("message", "string"),
}
CLOSE_PRODUCER: Schema = {
    1: ("producer_id", "varint"),
    2: ("request_id", "varint"),
}
CLOSE_CONSUMER: Schema = {
    1: ("consumer_id", "varint"),
    2: ("request_id", "varint"),
}
PRODUCER_SUCCESS: Schema = {
    1: ("request_id", "varint"),
    2: ("producer_name", "string"),
    3: ("last_sequence_id", "varint"),
}
PING: Schema = {}
PONG: Schema = {}
PARTITIONED_METADATA: Schema = {
    1: ("topic", "string"),
    2: ("request_id", "varint"),
}
PARTITIONED_METADATA_RESPONSE: Schema = {
    1: ("partitions", "varint"),
    2: ("request_id", "varint"),
    3: ("response", "varint"),  # 0 success, 1 failed
}
LOOKUP: Schema = {
    1: ("topic", "string"),
    2: ("request_id", "varint"),
    3: ("authoritative", "varint"),
}
LOOKUP_RESPONSE: Schema = {
    1: ("broker_service_url", "string"),
    3: ("response", "varint"),  # 0 redirect, 1 connect, 2 failed
    4: ("request_id", "varint"),
    5: ("authoritative", "varint"),
}
SEEK: Schema = {
    1: ("consumer_id", "varint"),
    2: ("request_id", "varint"),
    3: ("message_id", ("msg", MESSAGE_ID)),
    4: ("message_publish_time", "varint"),
}
GET_LAST_MESSAGE_ID: Schema = {
    1: ("consumer_id", "varint"),
    2: ("request_id", "varint"),
}
GET_LAST_MESSAGE_ID_RESPONSE: Schema = {
    1: ("last_message_id", ("msg", MESSAGE_ID)),
    2: ("request_id", "varint"),
}

MESSAGE_METADATA: Schema = {
    1: ("producer_name", "string"),
    2: ("sequence_id", "varint"),
    3: ("publish_time", "varint"),
    4: ("properties*", ("msg", KEY_VALUE)),
    6: ("partition_key", "string"),
    8: ("compression", "varint"),  # CompressionType enum; 0 = NONE
    9: ("uncompressed_size", "varint"),
    11: ("num_messages_in_batch", "varint"),
    15: ("partition_key_b64_encoded", "varint"),  # key is base64 of raw bytes
}

# PulsarApi.proto SingleMessageMetadata — one per entry of a batched payload
# (JVM producers batch by default; each entry is [4-byte size][this][payload])
SINGLE_MESSAGE_METADATA: Schema = {
    1: ("properties*", ("msg", KEY_VALUE)),
    2: ("partition_key", "string"),
    3: ("payload_size", "varint"),
    4: ("compacted_out", "varint"),
    5: ("event_time", "varint"),
    6: ("partition_key_b64_encoded", "varint"),
    8: ("sequence_id", "varint"),
    9: ("null_value", "varint"),
    10: ("null_partition_key", "varint"),
}


def split_batch(payload: bytes, n: int) -> list[tuple[dict[str, Any], bytes]]:
    """Split a batched message payload into ``n`` (SingleMessageMetadata,
    entry payload) pairs — the spec layout is
    ``[int32 metadata_size][SingleMessageMetadata][payload_size bytes]``
    repeated, sizes big-endian."""
    out: list[tuple[dict[str, Any], bytes]] = []
    off = 0
    for _ in range(n):
        if off + 4 > len(payload):
            raise ValueError(
                f"truncated batch payload: {len(payload)} bytes, "
                f"entry header at {off}"
            )
        size = int.from_bytes(payload[off : off + 4], "big")
        off += 4
        smm = decode_message(SINGLE_MESSAGE_METADATA, payload[off : off + size])
        off += size
        psize = int(smm.get("payload_size", 0))
        out.append((smm, payload[off : off + psize]))
        off += psize
    return out

# BaseCommand type enum values + the field that carries each sub-command
_COMMANDS: dict[str, tuple[int, int, Schema]] = {
    # name: (type enum, BaseCommand field number, schema)
    "connect": (2, 2, CONNECT),
    "connected": (3, 3, CONNECTED),
    "subscribe": (4, 4, SUBSCRIBE),
    "producer": (5, 5, PRODUCER),
    "send": (6, 6, SEND),
    "send_receipt": (7, 7, SEND_RECEIPT),
    "send_error": (8, 8, SEND_ERROR),
    "message": (9, 9, MESSAGE),
    "ack": (10, 10, ACK),
    "flow": (11, 11, FLOW),
    "unsubscribe": (12, 12, UNSUBSCRIBE),
    "success": (13, 13, SUCCESS),
    "error": (14, 14, ERROR),
    "close_producer": (15, 15, CLOSE_PRODUCER),
    "close_consumer": (16, 16, CLOSE_CONSUMER),
    "producer_success": (17, 17, PRODUCER_SUCCESS),
    "ping": (18, 18, PING),
    "pong": (19, 19, PONG),
    "partitioned_metadata": (21, 21, PARTITIONED_METADATA),
    "partitioned_metadata_response": (22, 22, PARTITIONED_METADATA_RESPONSE),
    "lookup": (23, 23, LOOKUP),
    "lookup_response": (24, 24, LOOKUP_RESPONSE),
    "seek": (28, 28, SEEK),
    "get_last_message_id": (29, 29, GET_LAST_MESSAGE_ID),
    "get_last_message_id_response": (30, 30, GET_LAST_MESSAGE_ID_RESPONSE),
}
_TYPE_TO_NAME = {type_: name for name, (type_, _, _) in _COMMANDS.items()}

PROTOCOL_VERSION = 21
MAGIC = b"\x0e\x01"


def encode_command(name: str, fields: dict[str, Any]) -> bytes:
    type_enum, field_no, schema = _COMMANDS[name]
    body = encode_message(schema, fields)
    out = bytearray()
    out += write_varint(1 << 3 | 0)  # BaseCommand.type
    out += write_varint(type_enum)
    out += write_varint(field_no << 3 | 2)
    out += write_varint(len(body))
    out += body
    return bytes(out)


def decode_command(buf: bytes) -> tuple[str, dict[str, Any]]:
    pos = 0
    type_enum: Optional[int] = None
    sub: dict[int, bytes] = {}
    while pos < len(buf):
        tag, pos = read_varint(buf, pos)
        field_no, wire_type = tag >> 3, tag & 7
        if wire_type == 0:
            val, pos = read_varint(buf, pos)
            if field_no == 1:
                type_enum = val
        elif wire_type == 2:
            length, pos = read_varint(buf, pos)
            sub[field_no] = buf[pos : pos + length]
            pos += length
        else:  # pragma: no cover - malformed
            raise ValueError(f"unexpected wire type {wire_type} in BaseCommand")
    if type_enum is None:
        raise ValueError("BaseCommand without type")
    name = _TYPE_TO_NAME.get(type_enum)
    if name is None:
        return f"unknown_{type_enum}", {}
    _, field_no, schema = _COMMANDS[name]
    body = sub.get(field_no, b"")
    return name, decode_message(schema, body)


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) — pulsar checksums payload frames with it; zlib only
# has IEEE crc32, so table-driven here
# ---------------------------------------------------------------------------

_CRC32C_POLY = 0x82F63B78
_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def frame(command: bytes) -> bytes:
    """Simple command frame."""
    return struct.pack(">II", 4 + len(command), len(command)) + command


def payload_frame(command: bytes, metadata: bytes, payload: bytes) -> bytes:
    """SEND / MESSAGE frame with metadata + payload and crc32c."""
    checked = struct.pack(">I", len(metadata)) + metadata + payload
    crc = crc32c(checked)
    rest = MAGIC + struct.pack(">I", crc) + checked
    total = 4 + len(command) + len(rest)
    return struct.pack(">II", total, len(command)) + command + rest


def split_frame(data: bytes) -> tuple[str, dict, Optional[dict], bytes]:
    """Decode one frame body (after totalSize): returns
    (command name, command fields, metadata or None, payload)."""
    (command_size,) = struct.unpack_from(">I", data, 0)
    name, fields = decode_command(data[4 : 4 + command_size])
    rest = data[4 + command_size :]
    if not rest:
        return name, fields, None, b""
    if rest[:2] != MAGIC:
        raise ValueError("payload frame without magic")
    (crc,) = struct.unpack_from(">I", rest, 2)
    checked = rest[6:]
    if crc32c(checked) != crc:
        raise ValueError("crc32c mismatch on payload frame")
    (metadata_size,) = struct.unpack_from(">I", checked, 0)
    metadata = decode_message(MESSAGE_METADATA, checked[4 : 4 + metadata_size])
    payload = checked[4 + metadata_size :]
    return name, fields, metadata, payload
