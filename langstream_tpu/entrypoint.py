"""Pod entry point — role dispatch for the runtime image.

Parity: reference ``runtime/Main.java:42-45`` (``agent-runtime |
agent-code-download | deployer-runtime | application-setup``) plus the
control-plane/gateway roles the reference runs as separate Spring apps.

Roles that run standalone here:
- ``agent-runtime``: one physical agent replica driven by the
  RuntimePodConfiguration JSON the deployer wrote into the pod Secret
  (mounted at ``$POD_CONFIGURATION``); serves /metrics + /info on :8080.
- ``control-plane``: REST control plane over a disk-backed store
  (``$STORAGE_ROOT``), with the gateway embedded.
- ``run-local``: whole platform in one process (delegates to the CLI).

Real-cluster roles (reference Main.java:42-45 + the JOSDK operator app),
all backed by the stdlib ``k8s/client.py`` API client (kubeconfig /
in-cluster / KUBE_API_SERVER auth):
- ``operator``: level-based reconcile loop over Application/Agent CRs.
- ``deployer-runtime`` / ``application-setup``: the two reconcile-phase
  Jobs, runnable as real cluster Jobs.
- ``agent-code-download``: init-container that unpacks the app archive
  from the control plane into the shared code volume.

Usage: ``python -m langstream_tpu.entrypoint <role> [args...]``
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys
from typing import Any

log = logging.getLogger(__name__)


def build_agent_node(pod: dict[str, Any]):
    """RuntimePodConfiguration ``agent`` section → AgentNode."""
    from langstream_tpu.api.model import ErrorsSpec, ResourcesSpec
    from langstream_tpu.api.planner import AgentNode, Connection

    def conn(section):
        if not section:
            return None
        return Connection.to_topic(section["topic"])

    def build(agent: dict[str, Any]) -> AgentNode:
        return AgentNode(
            id=agent["agentId"],
            agent_type=agent["agentType"],
            component_type=agent.get("componentType", "processor"),
            module_id=agent.get("module", "default"),
            pipeline_id=agent.get("pipeline", "default"),
            configuration=dict(agent.get("configuration", {})),
            resources=ResourcesSpec.from_dict(agent.get("resources")) or ResourcesSpec(),
            errors=ErrorsSpec.from_dict(agent.get("errors")) or ErrorsSpec(),
            input=conn(agent.get("input")),
            output=conn(agent.get("output")),
            disk=bool(agent.get("disk", False)),
            composite=[build(child) for child in agent.get("composite", [])],
        )

    return build(pod["agent"])


async def run_agent_runtime(pod: dict[str, Any]) -> None:
    from pathlib import Path

    from langstream_tpu.api.metrics import MetricsReporter
    from langstream_tpu.api.model import Application, Resource
    from langstream_tpu.messaging.registry import get_topic_connections_runtime
    from langstream_tpu.runtime.http_server import RuntimeHttpServer
    from langstream_tpu.runtime.runner import AgentRunner, SimpleAgentContext

    from langstream_tpu.parallel.multihost import DistributedConfig, bootstrap

    # multi-host replica? join the jax.distributed process group FIRST (must
    # precede any jax backend touch; parallel/multihost.py for the contract)
    dist = DistributedConfig.from_env()
    bootstrap(dist)

    node = build_agent_node(pod)

    if dist.is_multihost:
        serving_count = sum(
            1
            for r in (pod.get("resources") or {}).values()
            if r.get("type") == "tpu-serving"
        )
        if serving_count > 1:
            # each engine would announce on the one broadcast transport with
            # no shared total order — reject rather than hang the replica
            raise RuntimeError(
                "a multi-host (tpu.hosts > 1) agent supports exactly one "
                f"tpu-serving resource, found {serving_count}"
            )

    if dist.is_multihost and not dist.is_leader:
        # follower host: a mesh worker of its replica's process group — it
        # must NOT open a broker consumer or any agent machinery ("one
        # logical consumer, N pods"). When the agent serves a tpu-serving
        # model, the follower builds an IDENTICAL (unstarted) engine and
        # replays the leader's device dispatches over the SPMD channel
        # (parallel/spmd_serving.py); otherwise it parks serving /metrics.
        metrics = MetricsReporter()
        serving_resource = next(
            (
                r
                for r in (pod.get("resources") or {}).values()
                if r.get("type") == "tpu-serving"
            ),
            None,
        )
        http = RuntimeHttpServer(
            metrics_text=metrics.prometheus_text,
            agents_info=lambda: [
                {"agent-id": node.id, "replica": dist.replica_index,
                 "role": "mesh-worker", "process-index": dist.process_index,
                 "spmd-serving": serving_resource is not None}
            ],
            host=os.environ.get("HTTP_HOST", "0.0.0.0"),
            port=int(pod.get("httpPort", os.environ.get("HTTP_PORT", "8080"))),
        )
        await http.start()
        log.info(
            "mesh worker up: %s process %d/%d",
            node.id, dist.process_index, dist.num_processes,
        )
        try:
            if serving_resource is not None:
                from langstream_tpu.ai.tpu_serving import _EngineHolder
                from langstream_tpu.parallel.spmd_serving import follower_loop

                holder = _EngineHolder(
                    dict(serving_resource.get("configuration", {}))
                )
                engine = holder.build_engine(start=False)
                assert engine._spmd is not None
                # replay until the leader announces STOP (leader restart
                # restarts this pod via the crash-only StatefulSet policy)
                await asyncio.to_thread(follower_loop, engine, engine._spmd)
            else:
                await asyncio.Event().wait()  # crash-only: leader restarts us
        finally:
            await http.stop()
        return

    streaming = pod.get("streamingCluster", {"type": "memory", "configuration": {}})
    topic_runtime = get_topic_connections_runtime(streaming.get("type", "memory"))
    await topic_runtime.init(streaming.get("configuration", {}))

    # resources (AI providers, datasources) declared by the application
    app = Application()
    for rid, resource in (pod.get("resources") or {}).items():
        app.resources[rid] = Resource(
            id=rid,
            name=resource.get("name", rid),
            type=resource["type"],
            configuration=dict(resource.get("configuration", {})),
        )
    from langstream_tpu.ai.provider import ServiceProviderRegistry

    registry = ServiceProviderRegistry(app)

    metrics = MetricsReporter()
    if dist.is_multihost:
        # the pod's ordinal covers hosts × replicas; the broker-facing
        # replica id is the process GROUP index
        replica = dist.replica_index
    else:
        # StatefulSet pods end in "-<ordinal>"; anything else (docker hex
        # ids, bare hostnames) falls back to replica 0
        try:
            replica = int(
                os.environ.get("REPLICA")
                or os.environ.get("HOSTNAME", "0").rsplit("-", 1)[-1]
            )
        except ValueError:
            replica = 0
    state_dir = os.environ.get("PERSISTENT_STATE_DIR", "/persistent-state")
    context = SimpleAgentContext(
        global_agent_id=f"{pod.get('applicationId', 'app')}-{node.id}-{replica}",
        tenant=pod.get("tenant", "default"),
        topic_runtime=topic_runtime,
        metrics=metrics,
        state_dir=Path(state_dir) if node.disk else None,
        service_registry=registry,
        on_critical_failure=lambda e: os._exit(1),  # crash-only (reference)
        code_directory=os.environ.get("APP_CODE_DIR"),
    )
    runner = AgentRunner(node, topic_runtime, context, replica)
    await runner.setup()
    await runner.start()

    http = RuntimeHttpServer(
        metrics_text=metrics.prometheus_text,
        agents_info=lambda: [runner.info()],
        host=os.environ.get("HTTP_HOST", "0.0.0.0"),
        port=int(pod.get("httpPort", os.environ.get("HTTP_PORT", "8080"))),
    )
    await http.start()
    log.info("agent runtime up: %s", node.id)
    try:
        await runner.run()
    finally:
        await http.stop()
        try:
            await runner.close()
        except Exception:  # noqa: BLE001 — shutdown best-effort
            log.exception("agent close failed")


async def run_control_plane() -> None:
    from langstream_tpu.webservice.server import ControlPlaneServer
    from langstream_tpu.webservice.service import make_local_service

    root = os.environ.get("STORAGE_ROOT", "/var/lib/langstream-tpu")
    code_storage = None
    if os.environ.get("CODE_STORAGE"):
        # JSON codeStorage block, e.g. {"type":"s3","configuration":{...}}
        from langstream_tpu.webservice.stores import make_code_storage

        code_storage = make_code_storage(json.loads(os.environ["CODE_STORAGE"]))
    applications, tenants, runtime = make_local_service(root, code_storage)
    server = ControlPlaneServer(
        applications,
        tenants,
        host="0.0.0.0",
        port=int(os.environ.get("CONTROL_PLANE_PORT", "8090")),
        auth_token=os.environ.get("ADMIN_TOKEN") or None,
        archetypes_path=os.environ.get("ARCHETYPES_PATH") or None,
    )
    await server.start()
    log.info("control plane up on %s", server.url)
    try:
        await asyncio.Event().wait()
    finally:
        await runtime.close()
        await server.stop()


async def run_gateway() -> None:
    """Standalone gateway over the control plane's disk store (shared PVC)."""
    from langstream_tpu.gateway.server import GatewayServer, StoreApplicationProvider
    from langstream_tpu.webservice.stores import LocalDiskApplicationStore

    root = os.environ.get("STORAGE_ROOT", "/var/lib/langstream-tpu")
    store = LocalDiskApplicationStore(f"{root}/apps")
    server = GatewayServer(
        StoreApplicationProvider(store),
        host="0.0.0.0",
        port=int(os.environ.get("GATEWAY_PORT", "8091")),
    )
    await server.start()
    log.info("gateway up on %s", server.url)
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()


def run_operator(stop=None) -> None:
    """WATCH-driven, level-based reconcile loop against a live API server
    (or the HTTP fake). Watcher threads stream Application/Agent CR events
    and wake the reconcile pass immediately; every pass is still a full
    list+reconcile (the JOSDK operator's event loop collapsed to
    level-triggered form, which converges identically because the
    reconcilers are idempotent — AppController.java:92-245 two-phase
    flow). A fallback pass still runs every OPERATOR_POLL_SECONDS even
    with no events: unwatched state (StatefulSet readiness, Secrets)
    only surfaces through the periodic list, so the watch ACCELERATES
    convergence for CR edits without ever slowing anything else.

    OPERATOR_ONCE=true runs a single pass and exits 0 (tests / cron);
    ``stop`` (an optional threading.Event) ends the loop and its watcher
    threads — the in-process test harness's shutdown path."""
    import threading as _threading
    import time as _time

    from langstream_tpu.k8s.client import KubeApiClient, KubeWatchExpired
    from langstream_tpu.k8s.controllers import (
        AgentController,
        AppController,
        InProcessJobExecutor,
    )
    from langstream_tpu.k8s.crds import AgentCustomResource, ApplicationCustomResource

    kube = KubeApiClient.from_env()
    namespace = os.environ.get("OPERATOR_NAMESPACE")  # None = cluster-wide
    poll = float(os.environ.get("OPERATOR_POLL_SECONDS", "2"))
    once = os.environ.get("OPERATOR_ONCE") == "true"
    app_controller = AppController(kube, InProcessJobExecutor(kube))
    agent_controller = AgentController(kube)
    log.info("operator up against %s (namespace=%s)", kube.server, namespace or "*")

    dirty = _threading.Event()
    dirty.set()  # first pass runs immediately
    stop = stop or _threading.Event()

    def _watcher(kind: str) -> None:
        rv = None
        delay = poll
        while not stop.is_set():
            try:
                for _type, _obj in kube.watch(
                    kind, namespace, resource_version=rv, timeout_seconds=30
                ):
                    rv = _obj.get("metadata", {}).get("resourceVersion", rv)
                    dirty.set()
                    if stop.is_set():
                        return
                delay = poll  # clean stream end: reset backoff
            except KubeWatchExpired:
                rv = None  # horizon passed: next watch starts fresh; the
                dirty.set()  # full-list pass re-levels everything missed
            except Exception:  # noqa: BLE001 — reconnect with backoff
                log.warning(
                    "%s watch dropped; reconnecting in %.1fs",
                    kind, delay, exc_info=True,
                )
                if stop.wait(delay):
                    return
                delay = min(delay * 2, 60.0)

    if not once:
        for kind in (ApplicationCustomResource.KIND, AgentCustomResource.KIND):
            _threading.Thread(
                target=_watcher, args=(kind,), daemon=True,
                name=f"watch-{kind.lower()}",
            ).start()

    backoff = poll
    while True:
        dirty.clear()  # events landing during the pass re-set it
        try:
            # apps first — their deployer phase writes the Agent CRs the
            # second list picks up, so one pass converges a fresh app
            for manifest in kube.list(ApplicationCustomResource.KIND, namespace):
                try:
                    app_controller.reconcile(manifest)
                except Exception:  # noqa: BLE001 — keep reconciling others
                    log.exception(
                        "application reconcile failed: %s",
                        manifest.get("metadata", {}).get("name"),
                    )
            for manifest in kube.list(AgentCustomResource.KIND, namespace):
                try:
                    agent_controller.reconcile(manifest)
                except Exception:  # noqa: BLE001
                    log.exception(
                        "agent reconcile failed: %s",
                        manifest.get("metadata", {}).get("name"),
                    )
            backoff = poll  # healthy pass: reset
        except Exception:  # noqa: BLE001 — API server blip: back off and retry
            log.exception(
                "list from API server failed; retrying in %.1fs", backoff
            )
            if once:
                raise
            _time.sleep(backoff)
            backoff = min(backoff * 2, 60.0)  # exponential, capped
            continue
        if once:
            return
        if stop.is_set():
            return
        # watch events wake us instantly; unwatched state (StatefulSet
        # readiness) still converges at the plain poll cadence
        dirty.wait(timeout=poll)


def _load_application_cr():
    """(kube, ApplicationCustomResource) for the job roles, from
    APPLICATION_NAME + NAMESPACE env (the operator stamps these into the
    Job pod spec; reference RuntimeDeployerConfiguration)."""
    from langstream_tpu.k8s.client import KubeApiClient
    from langstream_tpu.k8s.crds import ApplicationCustomResource

    kube = KubeApiClient.from_env()
    name = os.environ["APPLICATION_NAME"]
    namespace = os.environ.get("NAMESPACE", "default")
    manifest = kube.get(ApplicationCustomResource.KIND, namespace, name)
    if manifest is None:
        raise RuntimeError(f"Application CR {namespace}/{name} not found")
    return kube, ApplicationCustomResource.from_manifest(manifest)


def run_deployer_job() -> None:
    """The deployer Job's work: plan the app, write one Agent CR (+ pod
    config Secret) per physical agent (KubernetesClusterRuntime.deploy:93)."""
    from langstream_tpu.k8s.controllers import InProcessJobExecutor

    kube, app = _load_application_cr()
    InProcessJobExecutor(kube).run_deployer(app)
    log.info("deployer job done for %s", app.name)


def run_setup_job() -> None:
    """The setup Job's work: validate the plan / provision declared assets
    before the deployer runs (AppController phase 1)."""
    from langstream_tpu.k8s.controllers import InProcessJobExecutor

    kube, app = _load_application_cr()
    InProcessJobExecutor(kube).run_setup(app)
    log.info("setup job done for %s", app.name)


def run_code_download() -> None:
    """Init-container role: fetch the application's code archive from the
    control plane and unpack it into the shared volume the agent runtime
    mounts (reference agent-code-download + CodeStorage download path).

    Env: CONTROL_PLANE_URL, TENANT, APPLICATION_ID, TARGET_DIR
    (+ ADMIN_TOKEN when the control plane requires auth)."""
    import io
    import urllib.request
    import zipfile
    from pathlib import Path

    base = os.environ["CONTROL_PLANE_URL"].rstrip("/")
    tenant = os.environ.get("TENANT", "default")
    app_id = os.environ["APPLICATION_ID"]
    target = Path(os.environ.get("TARGET_DIR", "/app-code-download"))
    req = urllib.request.Request(
        f"{base}/api/applications/{tenant}/{app_id}/code"
    )
    token = os.environ.get("ADMIN_TOKEN")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=60) as resp:
        archive = resp.read()
    target.mkdir(parents=True, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(archive)) as zf:
        root = target.resolve()
        for info in zf.infolist():
            # refuse path traversal from a hostile archive (proper ancestor
            # check — a raw str prefix passes sibling dirs like /target-evil)
            dest = (target / info.filename).resolve()
            if not dest.is_relative_to(root):
                raise RuntimeError(f"archive path escapes target: {info.filename}")
        zf.extractall(target)
    log.info("code archive for %s/%s unpacked to %s", tenant, app_id, target)


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(level=logging.INFO)
    argv = argv if argv is not None else sys.argv[1:]
    role = argv[0] if argv else "agent-runtime"
    if role == "agent-runtime":
        config_path = os.environ.get("POD_CONFIGURATION", "/app-config/pod-configuration")
        with open(config_path) as f:
            pod = json.load(f)
        asyncio.run(run_agent_runtime(pod))
        return 0
    if role == "control-plane":
        asyncio.run(run_control_plane())
        return 0
    if role == "gateway":
        asyncio.run(run_gateway())
        return 0
    if role == "run-local":
        from langstream_tpu.cli.main import cli

        cli(["run", "local", *argv[1:]], standalone_mode=True, obj={})
        return 0
    if role == "operator":
        run_operator()
        return 0
    if role == "deployer-runtime":
        run_deployer_job()
        return 0
    if role == "application-setup":
        run_setup_job()
        return 0
    if role == "agent-code-download":
        run_code_download()
        return 0
    print(f"unknown role {role!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
