"""Pod entry point — role dispatch for the runtime image.

Parity: reference ``runtime/Main.java:42-45`` (``agent-runtime |
agent-code-download | deployer-runtime | application-setup``) plus the
control-plane/gateway roles the reference runs as separate Spring apps.

Roles that run standalone here:
- ``agent-runtime``: one physical agent replica driven by the
  RuntimePodConfiguration JSON the deployer wrote into the pod Secret
  (mounted at ``$POD_CONFIGURATION``); serves /metrics + /info on :8080.
- ``control-plane``: REST control plane over a disk-backed store
  (``$STORAGE_ROOT``), with the gateway embedded.
- ``run-local``: whole platform in one process (delegates to the CLI).

``deployer-runtime`` / ``application-setup`` / ``agent-code-download`` need
a Kubernetes API client, which this image does not ship — they fail with an
explicit message (same gating pattern as the kafka/pulsar broker runtimes).

Usage: ``python -m langstream_tpu.entrypoint <role> [args...]``
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import sys
from typing import Any

log = logging.getLogger(__name__)


def build_agent_node(pod: dict[str, Any]):
    """RuntimePodConfiguration ``agent`` section → AgentNode."""
    from langstream_tpu.api.model import ErrorsSpec, ResourcesSpec
    from langstream_tpu.api.planner import AgentNode, Connection

    def conn(section):
        if not section:
            return None
        return Connection.to_topic(section["topic"])

    def build(agent: dict[str, Any]) -> AgentNode:
        return AgentNode(
            id=agent["agentId"],
            agent_type=agent["agentType"],
            component_type=agent.get("componentType", "processor"),
            module_id=agent.get("module", "default"),
            pipeline_id=agent.get("pipeline", "default"),
            configuration=dict(agent.get("configuration", {})),
            resources=ResourcesSpec.from_dict(agent.get("resources")) or ResourcesSpec(),
            errors=ErrorsSpec.from_dict(agent.get("errors")) or ErrorsSpec(),
            input=conn(agent.get("input")),
            output=conn(agent.get("output")),
            disk=bool(agent.get("disk", False)),
            composite=[build(child) for child in agent.get("composite", [])],
        )

    return build(pod["agent"])


async def run_agent_runtime(pod: dict[str, Any]) -> None:
    from pathlib import Path

    from langstream_tpu.api.metrics import MetricsReporter
    from langstream_tpu.api.model import Application, Resource
    from langstream_tpu.messaging.registry import get_topic_connections_runtime
    from langstream_tpu.runtime.http_server import RuntimeHttpServer
    from langstream_tpu.runtime.runner import AgentRunner, SimpleAgentContext

    from langstream_tpu.parallel.multihost import DistributedConfig, bootstrap

    # multi-host replica? join the jax.distributed process group FIRST (must
    # precede any jax backend touch; parallel/multihost.py for the contract)
    dist = DistributedConfig.from_env()
    bootstrap(dist)

    node = build_agent_node(pod)

    if dist.is_multihost and not dist.is_leader:
        # follower host: a mesh worker of its replica's process group — it
        # must NOT open a broker consumer or any agent machinery ("one
        # logical consumer, N pods"). It serves /metrics + /info and stays
        # joined to the group; the leader-broadcast SPMD dispatch for the
        # serving engine is the documented hardware-untested step
        # (parallel/multihost.py caveat).
        metrics = MetricsReporter()
        http = RuntimeHttpServer(
            metrics_text=metrics.prometheus_text,
            agents_info=lambda: [
                {"agent-id": node.id, "replica": dist.replica_index,
                 "role": "mesh-worker", "process-index": dist.process_index}
            ],
            host=os.environ.get("HTTP_HOST", "0.0.0.0"),
            port=int(pod.get("httpPort", os.environ.get("HTTP_PORT", "8080"))),
        )
        await http.start()
        log.info(
            "mesh worker up: %s process %d/%d",
            node.id, dist.process_index, dist.num_processes,
        )
        try:
            await asyncio.Event().wait()  # crash-only: leader death restarts us
        finally:
            await http.stop()
        return

    streaming = pod.get("streamingCluster", {"type": "memory", "configuration": {}})
    topic_runtime = get_topic_connections_runtime(streaming.get("type", "memory"))
    await topic_runtime.init(streaming.get("configuration", {}))

    # resources (AI providers, datasources) declared by the application
    app = Application()
    for rid, resource in (pod.get("resources") or {}).items():
        app.resources[rid] = Resource(
            id=rid,
            name=resource.get("name", rid),
            type=resource["type"],
            configuration=dict(resource.get("configuration", {})),
        )
    from langstream_tpu.ai.provider import ServiceProviderRegistry

    registry = ServiceProviderRegistry(app)

    metrics = MetricsReporter()
    if dist.is_multihost:
        # the pod's ordinal covers hosts × replicas; the broker-facing
        # replica id is the process GROUP index
        replica = dist.replica_index
    else:
        # StatefulSet pods end in "-<ordinal>"; anything else (docker hex
        # ids, bare hostnames) falls back to replica 0
        try:
            replica = int(
                os.environ.get("REPLICA")
                or os.environ.get("HOSTNAME", "0").rsplit("-", 1)[-1]
            )
        except ValueError:
            replica = 0
    state_dir = os.environ.get("PERSISTENT_STATE_DIR", "/persistent-state")
    context = SimpleAgentContext(
        global_agent_id=f"{pod.get('applicationId', 'app')}-{node.id}-{replica}",
        tenant=pod.get("tenant", "default"),
        topic_runtime=topic_runtime,
        metrics=metrics,
        state_dir=Path(state_dir) if node.disk else None,
        service_registry=registry,
        on_critical_failure=lambda e: os._exit(1),  # crash-only (reference)
        code_directory=os.environ.get("APP_CODE_DIR"),
    )
    runner = AgentRunner(node, topic_runtime, context, replica)
    await runner.setup()
    await runner.start()

    http = RuntimeHttpServer(
        metrics_text=metrics.prometheus_text,
        agents_info=lambda: [runner.info()],
        host=os.environ.get("HTTP_HOST", "0.0.0.0"),
        port=int(pod.get("httpPort", os.environ.get("HTTP_PORT", "8080"))),
    )
    await http.start()
    log.info("agent runtime up: %s", node.id)
    try:
        await runner.run()
    finally:
        await http.stop()
        try:
            await runner.close()
        except Exception:  # noqa: BLE001 — shutdown best-effort
            log.exception("agent close failed")


async def run_control_plane() -> None:
    from langstream_tpu.webservice.server import ControlPlaneServer
    from langstream_tpu.webservice.service import make_local_service

    root = os.environ.get("STORAGE_ROOT", "/var/lib/langstream-tpu")
    applications, tenants, runtime = make_local_service(root)
    server = ControlPlaneServer(
        applications,
        tenants,
        host="0.0.0.0",
        port=int(os.environ.get("CONTROL_PLANE_PORT", "8090")),
        auth_token=os.environ.get("ADMIN_TOKEN") or None,
        archetypes_path=os.environ.get("ARCHETYPES_PATH") or None,
    )
    await server.start()
    log.info("control plane up on %s", server.url)
    try:
        await asyncio.Event().wait()
    finally:
        await runtime.close()
        await server.stop()


async def run_gateway() -> None:
    """Standalone gateway over the control plane's disk store (shared PVC)."""
    from langstream_tpu.gateway.server import GatewayServer, StoreApplicationProvider
    from langstream_tpu.webservice.stores import LocalDiskApplicationStore

    root = os.environ.get("STORAGE_ROOT", "/var/lib/langstream-tpu")
    store = LocalDiskApplicationStore(f"{root}/apps")
    server = GatewayServer(
        StoreApplicationProvider(store),
        host="0.0.0.0",
        port=int(os.environ.get("GATEWAY_PORT", "8091")),
    )
    await server.start()
    log.info("gateway up on %s", server.url)
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(level=logging.INFO)
    argv = argv if argv is not None else sys.argv[1:]
    role = argv[0] if argv else "agent-runtime"
    if role == "agent-runtime":
        config_path = os.environ.get("POD_CONFIGURATION", "/app-config/pod-configuration")
        with open(config_path) as f:
            pod = json.load(f)
        asyncio.run(run_agent_runtime(pod))
        return 0
    if role == "control-plane":
        asyncio.run(run_control_plane())
        return 0
    if role == "gateway":
        asyncio.run(run_gateway())
        return 0
    if role == "run-local":
        from langstream_tpu.cli.main import cli

        cli(["run", "local", *argv[1:]], standalone_mode=True, obj={})
        return 0
    if role in ("operator", "deployer-runtime", "application-setup", "agent-code-download"):
        print(
            f"role {role!r} drives the Kubernetes API and requires a k8s client "
            "library, which this image does not ship; in local mode the "
            "in-process executor performs this work (langstream_tpu.k8s)",
            file=sys.stderr,
        )
        return 2
    print(f"unknown role {role!r}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
