"""LSA5xx — thread-shutdown hygiene.

The repo's worker threads (engine loop, token fetcher, spill/durable
workers, beacon refresher, SPMD receiver…) all follow one contract:
``daemon`` is set EXPLICITLY at construction (an implicit non-daemon
thread turns process exit into a hang; an implicit daemon thread hides
the decision), and a thread the owner keeps a handle to has a reachable
``join`` on the owner's close path (the spill-worker wedged-join arena
hazard in CHANGES.md is what happens when teardown hopes instead of
joining).

- LSA501  ``threading.Thread(...)`` constructed without an explicit
          ``daemon=`` keyword
- LSA502  a thread stored on ``self`` whose class never joins it, or a
          fire-and-forget local thread that is neither ``daemon=True``
          nor joined in the same function
"""

from __future__ import annotations

import ast
from typing import Optional

from langstream_tpu.analysis.core import (
    Finding,
    Repo,
    is_self_attr,
    parents,
)


def _is_thread_ctor(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return isinstance(f.value, ast.Name) and f.value.id.endswith(
            "threading"
        )
    return False


def _daemon_kwarg(node: ast.Call) -> Optional[ast.AST]:
    for kw in node.keywords:
        if kw.arg == "daemon":
            return kw.value
    return None


def _enclosing(node: ast.AST, kind) -> Optional[ast.AST]:
    for p in parents(node):
        if isinstance(p, kind):
            return p
    return None


def _class_joins(cls: ast.ClassDef, attr: str) -> bool:
    """True if any method in ``cls`` joins ``self.<attr>`` — directly, or
    through a local alias (``t = self._thread; …; t.join(timeout=…)``,
    the shape every stop() in engine.py uses so the join target cannot
    be swapped out from under it mid-teardown)."""
    for fn in ast.walk(cls):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        aliases: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and is_self_attr(
                node.value, attr
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                continue
            v = node.func.value
            if is_self_attr(v, attr):
                return True
            if isinstance(v, ast.Name) and v.id in aliases:
                return True
    return False


def _fn_joins_name(fn: ast.AST, name: str) -> bool:
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == name
        ):
            return True
    return False


def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for pf in repo.files:
        if pf.rel.startswith("langstream_tpu/analysis/"):
            continue
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            daemon = _daemon_kwarg(node)
            if daemon is None:
                findings.append(
                    Finding(
                        code="LSA501",
                        path=pf.rel,
                        line=node.lineno,
                        message=(
                            "threading.Thread without an explicit "
                            "daemon= — say whether process exit may "
                            "abandon this thread"
                        ),
                    )
                )
            parent = getattr(node, "_lstpu_parent", None)
            # ownership: self._x = Thread(...)
            self_attr: Optional[str] = None
            local_name: Optional[str] = None
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if is_self_attr(t):
                        self_attr = t.attr  # type: ignore[union-attr]
                    elif isinstance(t, ast.Name):
                        local_name = t.id
            if self_attr is not None:
                cls = _enclosing(node, ast.ClassDef)
                if cls is not None and not _class_joins(cls, self_attr):
                    findings.append(
                        Finding(
                            code="LSA502",
                            path=pf.rel,
                            line=node.lineno,
                            message=(
                                f"{cls.name}.{self_attr} is a thread "
                                "handle this class never joins — the "
                                "close path must join (or document why "
                                "leaking is safe with an inline "
                                "suppression)"
                            ),
                        )
                    )
            elif local_name is not None or isinstance(parent, ast.Attribute):
                fn = _enclosing(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                )
                is_daemon_true = (
                    isinstance(daemon, ast.Constant) and daemon.value is True
                )
                joined = (
                    local_name is not None
                    and fn is not None
                    and _fn_joins_name(fn, local_name)
                )
                if not is_daemon_true and not joined:
                    findings.append(
                        Finding(
                            code="LSA502",
                            path=pf.rel,
                            line=node.lineno,
                            message=(
                                "non-daemon thread with no reachable "
                                "join in this scope — process exit will "
                                "hang on it"
                            ),
                        )
                    )
    return findings
