"""LSA2xx — redaction taint: the static twin of the runtime redaction
stance (``validate_flight_dump`` / ``validate_beacon`` / the wire frame
schemas).

Dumps, spans, beacons and wire frames travel to incident channels,
Prometheus and peer replicas — token CONTENT must never ride them. The
runtime validators enforce this on the artifacts tests happen to
produce; this pass enforces it on every construction site in the tree:

- LSA201  a dict literal (or a key-assignment to it) flowing into a
          flight-recorder ``dump(extra=…, counters=…)`` call carries a
          token-content key (``tokens``/``prompt``/``text``/… — the
          ``_FORBIDDEN_KEYS`` set is parsed from
          ``serving/observability.py``, so the runtime denylist and the
          static one cannot drift apart)
- LSA202  same, flowing into ``emit_request_spans`` attributes
- LSA203  the ``beacon_from_engine`` literal carries a forbidden key,
          or omits a field ``validate_beacon`` requires
- LSA204  a wire-frame literal (``"kind": "tokens"/"begin"/…``) carries
          a key outside that kind's schema allowlist — the static twin
          of the ``lstpu-frames-v2``/``lstpu-kvmig-v2`` codecs, which
          silently DROP unknown keys on the binary path (a key the
          codec drops is a protocol change that never happened)

The flow analysis is intra-function: literals at the call site, plus
``name = {...}`` and ``name["key"] = …`` assignments to the same local
in the enclosing function. That is exactly the depth at which the
historical bug shape ("one more debug key in a dump extra") appears.
"""

from __future__ import annotations

import ast
from typing import Optional

from langstream_tpu.analysis.core import (
    Finding,
    ParsedFile,
    Repo,
    call_name,
    dict_literal_str_keys,
    enclosing_function,
    literal_str,
)

# fallback only: the live set is parsed out of serving/observability.py
FORBIDDEN_KEYS_FALLBACK = frozenset(
    {"tokens", "token", "prompt", "prompt_tokens", "generated", "text",
     "drafts", "value"}
)

# validate_beacon's required fields (serving/fleet.py) — kept in sync by
# the registry-drift pass reading both sides
BEACON_REQUIRED = (
    "schema", "id", "at", "load_score", "queue_wait_ema_s", "draining",
    "quarantined", "prefixes",
)
# beacons carry digests and counters, never token ids — the runtime
# validator's denylist, applied statically to the construction literal
BEACON_FORBIDDEN = frozenset({"tokens", "prompt", "text", "prompt_tokens"})

# per-kind frame schema allowlists (docs/SERVING.md §17/§18/§21 + the
# v2 codec in serving/wire.py). "prompt_tokens" in a begin/end frame is
# a token LIST by §18 design (migration re-prefill source) / a COUNT in
# an end frame — frames are the data plane; dumps and beacons are where
# token content is forbidden outright.
FRAME_KEYS: dict[str, frozenset] = {
    "tokens": frozenset(
        {"v", "seq", "kind", "tokens", "dfa_state", "replica"}
    ),
    "heartbeat": frozenset({"v", "seq", "kind", "replica"}),
    "end": frozenset(
        {"v", "seq", "kind", "finish_reason", "prompt_tokens",
         "completion_tokens", "ttft_s", "total_s", "engine_ttft_s",
         "usage", "replica", "tokens_per_sec", "failovers"}
    ),
    "error": frozenset(
        {"v", "seq", "kind", "error", "shed", "retry_after_s", "replica"}
    ),
    "route": frozenset(
        {"v", "seq", "kind", "replica", "url", "local", "resumed",
         "disagg", "decision"}
    ),
    "begin": frozenset(
        {"v", "seq", "kind", "length", "digest", "pages", "page_size",
         "bytes_per_page", "tier", "prompt_tokens"}
    ),
    "page": frozenset({"v", "seq", "kind", "i", "data", "raw", "checksum"}),
    "commit": frozenset({"v", "seq", "kind", "pages_sent", "state"}),
}

OBSERVABILITY_REL = "langstream_tpu/serving/observability.py"
FLEET_REL = "langstream_tpu/serving/fleet.py"


def forbidden_keys(repo: Repo) -> frozenset:
    """Parse ``_FORBIDDEN_KEYS`` out of observability.py so the static
    denylist IS the runtime one."""
    pf = repo.get(OBSERVABILITY_REL)
    if pf is None:
        return FORBIDDEN_KEYS_FALLBACK
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "_FORBIDDEN_KEYS"
            for t in node.targets
        ):
            call = node.value
            if (
                isinstance(call, ast.Call)
                and call.args
                and isinstance(call.args[0], (ast.Set, ast.Tuple, ast.List))
            ):
                keys = {
                    literal_str(el)
                    for el in call.args[0].elts
                    if literal_str(el) is not None
                }
                if keys:
                    return frozenset(keys)
    return FORBIDDEN_KEYS_FALLBACK


# ---------------------------------------------------------------------------
# Intra-function dataflow: dict literals + key-stores per local name
# ---------------------------------------------------------------------------


class _FnIndex:
    """Per-function map of local name -> (dict literals assigned to it,
    string keys stored into it)."""

    def __init__(self, fn: ast.AST) -> None:
        self.literals: dict[str, list[ast.Dict]] = {}
        self.stores: dict[str, list[tuple[str, int]]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Dict
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.literals.setdefault(t.id, []).append(node.value)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and literal_str(t.slice) is not None
                    ):
                        self.stores.setdefault(t.value.id, []).append(
                            (literal_str(t.slice), t.lineno)  # type: ignore[arg-type]
                        )


def _arg_keys(
    arg: ast.AST, index: Optional[_FnIndex]
) -> list[tuple[str, int]]:
    """Every statically-visible string key the argument may carry:
    literal keys, one level of ``**spread`` resolution, and key-stores
    on the same local."""
    out: list[tuple[str, int]] = []
    if isinstance(arg, ast.Dict):
        out.extend(dict_literal_str_keys(arg))
        for k, v in zip(arg.keys, arg.values):
            if k is None and isinstance(v, ast.Name) and index is not None:
                for lit in index.literals.get(v.id, ()):
                    out.extend(dict_literal_str_keys(lit))
                out.extend(index.stores.get(v.id, ()))
    elif isinstance(arg, ast.Name) and index is not None:
        for lit in index.literals.get(arg.id, ()):
            out.extend(dict_literal_str_keys(lit))
        out.extend(index.stores.get(arg.id, ()))
    elif isinstance(arg, ast.Call) and call_name(arg) == "dict":
        for kw in arg.keywords:
            if kw.arg is not None:
                out.append((kw.arg, kw.value.lineno))
            else:
                out.extend(_arg_keys(kw.value, index))
    return out


def _fn_index(call: ast.Call) -> Optional[_FnIndex]:
    fn = enclosing_function(call)
    return _FnIndex(fn) if fn is not None else None


# ---------------------------------------------------------------------------
# The pass
# ---------------------------------------------------------------------------


def _check_dump_call(
    pf: ParsedFile, call: ast.Call, forbidden: frozenset,
    findings: list[Finding],
) -> None:
    if call_name(call) != "dump":
        return
    checked = [kw.value for kw in call.keywords if kw.arg in ("extra", "counters")]
    if not checked:
        return
    index = _fn_index(call)
    for arg in checked:
        for key, line in _arg_keys(arg, index):
            if key in forbidden:
                findings.append(
                    Finding(
                        code="LSA201",
                        path=pf.rel,
                        line=line,
                        message=(
                            f"flight-dump payload carries token-content "
                            f"key {key!r} (validate_flight_dump would "
                            "reject this at incident time)"
                        ),
                    )
                )


def _check_span_call(
    pf: ParsedFile, call: ast.Call, forbidden: frozenset,
    findings: list[Finding],
) -> None:
    if call_name(call) != "emit_request_spans":
        return
    args = []
    if len(call.args) >= 3:
        args.append(call.args[2])
    args.extend(kw.value for kw in call.keywords if kw.arg == "attributes")
    index = _fn_index(call)
    for arg in args:
        for key, line in _arg_keys(arg, index):
            if key in forbidden:
                findings.append(
                    Finding(
                        code="LSA202",
                        path=pf.rel,
                        line=line,
                        message=(
                            f"request-span attributes carry token-content "
                            f"key {key!r} (spans ride /traces to external "
                            "consumers)"
                        ),
                    )
                )


def _check_beacon(pf: ParsedFile, findings: list[Finding]) -> None:
    if pf.rel != FLEET_REL:
        return
    for node in ast.walk(pf.tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == "beacon_from_engine"
        ):
            for ret in ast.walk(node):
                if not (
                    isinstance(ret, ast.Return)
                    and isinstance(ret.value, ast.Dict)
                ):
                    continue
                keys = dict_literal_str_keys(ret.value)
                names = {k for k, _ in keys}
                for key, line in keys:
                    if key in BEACON_FORBIDDEN:
                        findings.append(
                            Finding(
                                code="LSA203",
                                path=pf.rel,
                                line=line,
                                message=(
                                    f"beacon carries token-content key "
                                    f"{key!r} (validate_beacon rejects it)"
                                ),
                            )
                        )
                for req in BEACON_REQUIRED:
                    if req not in names:
                        findings.append(
                            Finding(
                                code="LSA203",
                                path=pf.rel,
                                line=ret.value.lineno,
                                message=(
                                    f"beacon literal omits required "
                                    f"field {req!r} (validate_beacon "
                                    "rejects every beacon this builds)"
                                ),
                            )
                        )


def _frame_kind(d: ast.Dict) -> Optional[str]:
    for k, v in zip(d.keys, d.values):
        if k is not None and literal_str(k) == "kind":
            kind = literal_str(v)
            if kind in FRAME_KEYS:
                return kind
    return None


def _check_frames(pf: ParsedFile, findings: list[Finding]) -> None:
    if not pf.rel.startswith("langstream_tpu/serving/"):
        return
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Dict):
            continue
        kind = _frame_kind(node)
        if kind is None:
            continue
        allowed = FRAME_KEYS[kind]
        for key, line in dict_literal_str_keys(node):
            if key not in allowed:
                findings.append(
                    Finding(
                        code="LSA204",
                        path=pf.rel,
                        line=line,
                        message=(
                            f"{kind!r} frame carries key {key!r} outside "
                            "the wire schema allowlist (the v2 binary "
                            "codec drops it silently; add it to the "
                            "schema in analysis/redaction.py + "
                            "serving/wire.py or remove it)"
                        ),
                    )
                )
        # key-stores on the variable the literal was assigned to
        fn = enclosing_function(node)
        if fn is None:
            continue
        var: Optional[str] = None
        parent = getattr(node, "_lstpu_parent", None)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Name):
                    var = t.id
        if var is None:
            continue
        for sub in ast.walk(fn):
            if (
                isinstance(sub, ast.Assign)
                and any(
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == var
                    and literal_str(t.slice) is not None
                    and literal_str(t.slice) not in allowed
                    for t in sub.targets
                )
            ):
                bad = next(
                    literal_str(t.slice)
                    for t in sub.targets
                    if isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == var
                    and literal_str(t.slice) is not None
                    and literal_str(t.slice) not in allowed
                )
                findings.append(
                    Finding(
                        code="LSA204",
                        path=pf.rel,
                        line=sub.lineno,
                        message=(
                            f"{kind!r} frame gains key {bad!r} outside "
                            "the wire schema allowlist"
                        ),
                    )
                )


def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    forbidden = forbidden_keys(repo)
    for pf in repo.files:
        if pf.rel.startswith("langstream_tpu/analysis/"):
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Call):
                _check_dump_call(pf, node, forbidden, findings)
                _check_span_call(pf, node, forbidden, findings)
        _check_beacon(pf, findings)
        _check_frames(pf, findings)
    return findings
