"""LSA1xx — lock discipline via the ``_GUARDED`` class registry.

The convention (docs/ANALYSIS.md): a class whose counters/state are
mutated from more than one thread declares, at class level,

    _GUARDED = {
        "_stats_lock": ("shed_total", "cancelled_total", ...),
    }

mapping each lock attribute to the attributes it guards. A MODULE whose
globals cross threads (serving/lifecycle.py) declares the same registry
at module level, mapping a module-global lock to the globals it guards.
This checker then flags every write to a registered attribute that is
not lexically inside a ``with self.<lock>:`` (or module-level
``with <lock>:``) block for the matching lock:

- LSA101  guarded attribute written outside its lock's ``with`` scope
          (direct assignment, ``+=``, item-store/delete on the guarded
          container). Writes in ``__init__``/``__new__`` are exempt
          (no second thread exists yet), as are methods whose name ends
          with ``_locked`` (the documented called-with-lock-held
          convention, e.g. ``Engine._stats_locked``).
- LSA102  malformed registry: a ``_GUARDED`` lock never created in the
          class, a non-literal registry, or an attribute guarded twice.

A write inside a nested function defined in a method is checked with an
EMPTY held-set even when the enclosing statement holds the lock: the
closure may run after the ``with`` exits (this is exactly the
finish-waker teardown-race shape CHANGES.md records). Suppress with
``# lstpu: ignore[LSA101]`` where the closure provably runs inline.
"""

from __future__ import annotations

import ast
from typing import Optional

from langstream_tpu.analysis.core import (
    Finding,
    ParsedFile,
    Repo,
    is_self_attr,
    literal_str,
)

EXEMPT_METHODS = ("__init__", "__new__")
LOCKED_SUFFIX = "_locked"


def _parse_guarded(scope) -> Optional[tuple[ast.AST, dict]]:
    """The ``_GUARDED`` assignment in ``scope.body`` (a ClassDef or a
    Module), if any: returns the assignment node and
    {lock_name: [attr, ...]} — or an empty dict when the literal is
    malformed."""
    for stmt in scope.body:
        target = None
        value = None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign):
            target, value = stmt.target, stmt.value
        if not (isinstance(target, ast.Name) and target.id == "_GUARDED"):
            continue
        if not isinstance(value, ast.Dict):
            return stmt, {}
        out: dict = {}
        for k, v in zip(value.keys, value.values):
            lock = literal_str(k) if k is not None else None
            if lock is None:
                return stmt, {}
            attrs = []
            if isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    s = literal_str(el)
                    if s is None:
                        return stmt, {}
                    attrs.append(s)
            else:
                return stmt, {}
            out[lock] = attrs
        return stmt, out
    return None


def _class_assigns_attr(cls: ast.ClassDef, attr: str) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if is_self_attr(t, attr):
                    return True
    return False


def _with_locks(stmt: ast.With, module_mode: bool = False) -> set[str]:
    held = set()
    for item in stmt.items:
        expr = item.context_expr
        if is_self_attr(expr):
            held.add(expr.attr)  # type: ignore[union-attr]
        elif module_mode and isinstance(expr, ast.Name):
            held.add(expr.id)
    return held


class _MethodChecker:
    def __init__(
        self,
        pf: ParsedFile,
        cls_name: str,
        guard_of: dict[str, str],
        module_mode: bool = False,
    ) -> None:
        self.pf = pf
        self.cls_name = cls_name
        self.guard_of = guard_of  # attr -> lock
        self.module_mode = module_mode
        self.findings: list[Finding] = []

    def _flag(self, node: ast.AST, attr: str, nested: bool) -> None:
        lock = self.guard_of[attr]
        ref = lock if self.module_mode else f"self.{lock}"
        why = (
            "from a nested function (the closure may outlive the lock)"
            if nested
            else f"outside `with {ref}:`"
        )
        self.findings.append(
            Finding(
                code="LSA101",
                path=self.pf.rel,
                line=node.lineno,
                message=(
                    f"{self.cls_name}.{attr} is guarded by "
                    f"{ref} but is written {why}"
                ),
            )
        )

    def _match(self, target: ast.AST) -> Optional[str]:
        """The guarded attribute a bare write target refers to, if any."""
        if self.module_mode:
            if isinstance(target, ast.Name):
                return target.id
            return None
        if is_self_attr(target):
            return target.attr  # type: ignore[union-attr]
        return None

    def _check_write_target(
        self, target: ast.AST, held: set[str], nested: bool, node: ast.AST
    ) -> None:
        # self.attr = / +=   (module mode: NAME = / +=)
        attr = self._match(target)
        if attr is None and isinstance(target, ast.Subscript):
            # self.attr[k] = / del self.attr[k]
            attr = self._match(target.value)
        if attr is not None:
            if attr in self.guard_of and self.guard_of[attr] not in held:
                self._flag(node, attr, nested)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._check_write_target(el, held, nested, node)

    def walk(
        self, stmts: list[ast.stmt], held: set[str], nested: bool
    ) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    self._check_write_target(t, held, nested, stmt)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                self._check_write_target(stmt.target, held, nested, stmt)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    self._check_write_target(t, held, nested, stmt)

            if isinstance(stmt, ast.With):
                self.walk(
                    stmt.body,
                    held | _with_locks(stmt, self.module_mode),
                    nested,
                )
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closures start from an empty held-set: they may run
                # after the enclosing `with` released the lock
                self.walk(stmt.body, set(), True)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self.walk(stmt.body, held, nested)
                self.walk(stmt.orelse, held, nested)
            elif isinstance(stmt, ast.If):
                self.walk(stmt.body, held, nested)
                self.walk(stmt.orelse, held, nested)
            elif isinstance(stmt, ast.Try):
                self.walk(stmt.body, held, nested)
                for h in stmt.handlers:
                    self.walk(h.body, held, nested)
                self.walk(stmt.orelse, held, nested)
                self.walk(stmt.finalbody, held, nested)
            elif isinstance(stmt, ast.ClassDef):
                self.walk(stmt.body, set(), nested)


def _module_assigns_name(tree: ast.Module, name: str) -> bool:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in stmt.targets
        ):
            return True
        if isinstance(stmt, ast.AnnAssign) and (
            isinstance(stmt.target, ast.Name) and stmt.target.id == name
        ):
            return True
    return False


def _check_module_registry(pf: ParsedFile, findings: list[Finding]) -> None:
    parsed = _parse_guarded(pf.tree)
    if parsed is None:
        return
    reg_node, registry = parsed
    if not registry:
        findings.append(
            Finding(
                code="LSA102",
                path=pf.rel,
                line=reg_node.lineno,
                message=(
                    "module-level _GUARDED must be a literal dict of "
                    "lock name -> tuple of global names"
                ),
            )
        )
        return
    guard_of: dict[str, str] = {}
    for lock, attrs in registry.items():
        if not _module_assigns_name(pf.tree, lock):
            findings.append(
                Finding(
                    code="LSA102",
                    path=pf.rel,
                    line=reg_node.lineno,
                    message=(
                        f"module _GUARDED names lock {lock!r} but the "
                        "module never creates it"
                    ),
                )
            )
            return
        for attr in attrs:
            if attr in guard_of:
                findings.append(
                    Finding(
                        code="LSA102",
                        path=pf.rel,
                        line=reg_node.lineno,
                        message=(
                            f"module _GUARDED lists {attr!r} under two "
                            "locks"
                        ),
                    )
                )
                return
            guard_of[attr] = lock
    mod_name = pf.rel.rsplit("/", 1)[-1].rsplit(".", 1)[0]
    for stmt in pf.tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if stmt.name in EXEMPT_METHODS or stmt.name.endswith(LOCKED_SUFFIX):
            continue
        mc = _MethodChecker(pf, mod_name, guard_of, module_mode=True)
        mc.walk(stmt.body, set(), False)
        findings.extend(mc.findings)


def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for pf in repo.files:
        _check_module_registry(pf, findings)
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            parsed = _parse_guarded(node)
            if parsed is None:
                continue
            reg_node, registry = parsed
            if not registry:
                findings.append(
                    Finding(
                        code="LSA102",
                        path=pf.rel,
                        line=reg_node.lineno,
                        message=(
                            f"{node.name}._GUARDED must be a literal "
                            "dict of lock name -> tuple of attribute "
                            "names"
                        ),
                    )
                )
                continue
            guard_of: dict[str, str] = {}
            ok = True
            for lock, attrs in registry.items():
                if not _class_assigns_attr(node, lock):
                    findings.append(
                        Finding(
                            code="LSA102",
                            path=pf.rel,
                            line=reg_node.lineno,
                            message=(
                                f"{node.name}._GUARDED names lock "
                                f"self.{lock!s} but the class never "
                                "creates it"
                            ),
                        )
                    )
                    ok = False
                for attr in attrs:
                    if attr in guard_of:
                        findings.append(
                            Finding(
                                code="LSA102",
                                path=pf.rel,
                                line=reg_node.lineno,
                                message=(
                                    f"{node.name}._GUARDED lists "
                                    f"{attr!r} under two locks"
                                ),
                            )
                        )
                        ok = False
                    guard_of[attr] = lock
            if not ok:
                continue
            for stmt in node.body:
                if not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if stmt.name in EXEMPT_METHODS or stmt.name.endswith(
                    LOCKED_SUFFIX
                ):
                    continue
                mc = _MethodChecker(pf, node.name, guard_of)
                mc.walk(stmt.body, set(), False)
                findings.extend(mc.findings)
    return findings
