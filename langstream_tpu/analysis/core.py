"""Shared frame for the lstpu-check passes: file discovery, parsed
files with parent-annotated ASTs, suppression comments, the committed
baseline, and the runner the CLI and the tier-1 test drive.

Suppression syntax (docs/ANALYSIS.md):

    x = 1  # lstpu: ignore[LSA101]
    # lstpu: ignore[LSA101, LSA502] — applies to the NEXT line too

A suppression names the exact code(s) it silences; a bare ``lstpu:
ignore`` without codes silences nothing (an unscoped waiver is how
invariants rot). The committed baseline (``.lstpu-baseline.json`` at the
repo root) grandfathers findings by ``path::code`` count — the tree
ships with an EMPTY baseline (every true positive found by the initial
run was fixed, not baselined), but the mechanism exists so a future
emergency revert does not have to fight the linter.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

#: repo-relative directories the passes scan (tests are scanned only by
#: the registry-drift pass, as evidence — never linted themselves)
SOURCE_ROOT = "langstream_tpu"
BASELINE_FILE = ".lstpu-baseline.json"

_SUPPRESS_RE = re.compile(r"#\s*lstpu:\s*ignore\[([A-Z0-9,\s]+)\]")


@dataclass(frozen=True)
class Finding:
    """One checker hit: a stable code, a repo-relative path, a 1-based
    line, and the human sentence. Sorting groups by file then line so
    the CLI output reads like a compiler's."""

    code: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    @property
    def key(self) -> str:
        return f"{self.path}::{self.code}"


class _ParentVisitor(ast.NodeVisitor):
    def generic_visit(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            child._lstpu_parent = node  # type: ignore[attr-defined]
        super().generic_visit(node)


def parents(node: ast.AST) -> Iterable[ast.AST]:
    """Walk ancestors root-ward (requires a ParsedFile tree)."""
    cur = getattr(node, "_lstpu_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_lstpu_parent", None)


@dataclass
class ParsedFile:
    """One source file: text, lines, a parent-annotated AST, and the
    per-line suppression map."""

    path: str  # absolute
    rel: str  # repo-relative, '/' separators
    source: str
    tree: ast.AST
    lines: list[str] = field(default_factory=list)
    suppressed: dict[int, set[str]] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, rel: str) -> "ParsedFile":
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=rel)
        _ParentVisitor().visit(tree)
        lines = source.splitlines()
        suppressed: dict[int, set[str]] = {}
        for i, text in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
            # a suppression covers its own line and, when the line is
            # the comment alone, the line below it
            suppressed.setdefault(i, set()).update(codes)
            if text.lstrip().startswith("#"):
                suppressed.setdefault(i + 1, set()).update(codes)
        return cls(
            path=path, rel=rel, source=source, tree=tree,
            lines=lines, suppressed=suppressed,
        )

    def is_suppressed(self, code: str, line: int) -> bool:
        return code in self.suppressed.get(line, ())


@dataclass
class Repo:
    """The parsed tree every checker receives. ``files`` carries the
    scanned source; ``root`` lets cross-artifact passes (registry drift)
    read tests, docs and dashboards as evidence."""

    root: str
    files: list[ParsedFile]

    _by_rel: Optional[dict[str, ParsedFile]] = None

    @classmethod
    def load(
        cls, root: str, subdirs: tuple[str, ...] = (SOURCE_ROOT,),
        exclude: tuple[str, ...] = ("__pycache__",),
    ) -> "Repo":
        files: list[ParsedFile] = []
        errors: list[str] = []
        for sub in subdirs:
            base = os.path.join(root, sub)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in exclude
                )
                for name in sorted(filenames):
                    if not name.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, name)
                    rel = os.path.relpath(path, root).replace(os.sep, "/")
                    try:
                        files.append(ParsedFile.parse(path, rel))
                    except SyntaxError as e:
                        errors.append(f"{rel}: unparseable ({e})")
        if errors:
            raise RuntimeError(
                "analysis cannot parse the tree:\n" + "\n".join(errors)
            )
        return cls(root=root, files=files)

    def get(self, rel: str) -> Optional[ParsedFile]:
        if self._by_rel is None:
            self._by_rel = {f.rel: f for f in self.files}
        return self._by_rel.get(rel)


# ---------------------------------------------------------------------------
# Small AST helpers shared by the passes
# ---------------------------------------------------------------------------


def is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    """``self.<attr>`` (any attr when ``attr`` is None)."""
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and (attr is None or node.attr == attr)
    )


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def dict_literal_str_keys(node: ast.Dict) -> list[tuple[str, int]]:
    """The string keys of a dict literal with their lines (``**spread``
    entries have no key and are skipped — the taint pass follows the
    spread's source separately when it can)."""
    out: list[tuple[str, int]] = []
    for key in node.keys:
        s = literal_str(key) if key is not None else None
        if s is not None:
            out.append((s, key.lineno))
    return out


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


def call_name(call: ast.Call) -> str:
    """Trailing name of the called expression: ``a.b.dump`` → ``dump``,
    ``emit_request_spans`` → itself."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

CheckerFn = Callable[[Repo], list[Finding]]


def all_checkers() -> dict[str, CheckerFn]:
    # imported here so `import langstream_tpu.analysis.core` stays cheap
    # and cycle-free for the passes themselves
    from langstream_tpu.analysis import (
        compile_surface,
        locks,
        redaction,
        registry_drift,
        threads,
    )

    return {
        "locks": locks.check,
        "redaction": redaction.check,
        "compile-surface": compile_surface.check,
        "registry-drift": registry_drift.check,
        "threads": threads.check,
    }


def load_baseline(root: str) -> dict[str, int]:
    path = os.path.join(root, BASELINE_FILE)
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise RuntimeError(f"{BASELINE_FILE} must be a JSON object")
    return {str(k): int(v) for k, v in doc.items()}


def apply_suppressions(
    repo: Repo, findings: list[Finding]
) -> list[Finding]:
    out = []
    for f in findings:
        pf = repo.get(f.path)
        if pf is not None and pf.is_suppressed(f.code, f.line):
            continue
        out.append(f)
    return out


def apply_baseline(
    findings: list[Finding], baseline: dict[str, int]
) -> tuple[list[Finding], dict[str, int]]:
    """Drop up to ``baseline[path::code]`` findings per key; return the
    survivors plus the STALE baseline entries (keys whose budget the
    tree no longer uses — strict mode fails on them so the baseline only
    ever shrinks)."""
    used: dict[str, int] = {}
    survivors: list[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.code)):
        budget = baseline.get(f.key, 0)
        if used.get(f.key, 0) < budget:
            used[f.key] = used.get(f.key, 0) + 1
            continue
        survivors.append(f)
    stale = {
        k: v - used.get(k, 0)
        for k, v in baseline.items()
        if used.get(k, 0) < v
    }
    return survivors, stale


def run_checks(
    root: str,
    only: Optional[Iterable[str]] = None,
    repo: Optional[Repo] = None,
) -> tuple[Repo, list[Finding]]:
    """Parse the tree and run the selected passes. Returns suppression-
    filtered findings, sorted; baseline handling is the caller's (the
    CLI applies it, the whole-repo-clean test wants raw findings)."""
    repo = repo or Repo.load(root)
    checkers = all_checkers()
    names = list(only) if only else list(checkers)
    unknown = [n for n in names if n not in checkers]
    if unknown:
        raise RuntimeError(
            f"unknown checker(s) {', '.join(unknown)}; "
            f"known: {', '.join(checkers)}"
        )
    findings: list[Finding] = []
    for name in names:
        findings.extend(checkers[name](repo))
    findings = apply_suppressions(repo, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return repo, findings


def repo_root_from_here() -> str:
    """The repo root, derived from this file's location (three levels up
    from ``langstream_tpu/analysis/core.py``)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def summarize(findings: list[Finding]) -> dict[str, Any]:
    by_code: dict[str, int] = {}
    for f in findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
    return {"total": len(findings), "by_code": by_code}
