"""LSA3xx — compile-surface lint: the "one program per family"
invariant that keeps ``stats()["compiled_programs"]`` flat.

Every ``jax.jit`` site is a distinct XLA program family; a jit that
sneaks into a per-request path (or whose operand shapes derive from a
per-request Python value) is a 15-23s mid-traffic compile stall. The
warmed ladder is therefore a REGISTRY: the modules below declare how
many jit sites they own, and adding/removing one anywhere in the tree
is a finding until the registry (and the warmup that covers it) is
updated deliberately.

- LSA301  a ``jax.jit`` site in a module absent from the warmed-program
          registry, or a module whose site count drifted from its
          registered value (new unwarmed program family / stale
          registry)
- LSA302  a ``jax.jit`` site lexically inside a ``for``/``while`` loop
          — a program family per iteration, the exact anti-pattern the
          fixed prefill-bucket ladder exists to prevent
- LSA303  a call to a jitted entry point whose operand slice is bounded
          by ``len(...)`` — a traced shape deriving from a per-request
          Python value (one compile per distinct length)
"""

from __future__ import annotations

import ast
from typing import Optional

from langstream_tpu.analysis.core import Finding, ParsedFile, Repo

#: the warmed compile surface: module -> number of jit sites it owns.
#: Every entry is covered by a warmup path (engine precompile ladder,
#: module-import-time definition, or a build-once factory). Adding a
#: jit site ANYWHERE means updating this registry — that diff line is
#: the reviewer's cue to ask "what warms it, and what are its static
#: shapes?" (docs/ANALYSIS.md).
WARMED_MODULES: dict[str, int] = {
    "langstream_tpu/agents/vector/__init__.py": 1,   # in-memory top-k probe
    "langstream_tpu/ai/tpu_serving.py": 1,           # embedding encode
    "langstream_tpu/models/streamload.py": 2,        # build-once loaders
    "langstream_tpu/models/transformer.py": 4,       # prefill/decode core
    "langstream_tpu/ops/kvcopy.py": 2,               # prefix publish/gather
    "langstream_tpu/parallel/sp.py": 1,              # long-context ring
    "langstream_tpu/serving/adapters.py": 1,         # LoRA row swap
    "langstream_tpu/serving/constrain.py": 1,        # grammar mask load
    "langstream_tpu/serving/engine.py": 16,          # the warmed ladder
    "langstream_tpu/serving/sampling.py": 2,         # sample/verify kernels
}


def _is_jit_ref(node: ast.AST) -> bool:
    """An occurrence of the ``jax.jit`` callable itself: ``jax.jit``
    attribute access, or a bare ``jit`` name imported from jax."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        v = node.value
        return isinstance(v, ast.Name) and v.id == "jax"
    return False


def _jit_sites(pf: ParsedFile) -> list[ast.AST]:
    sites = []
    jit_names = {"jit"} if _imports_jit_name(pf) else set()
    for node in ast.walk(pf.tree):
        if _is_jit_ref(node):
            sites.append(node)
        elif isinstance(node, ast.Name) and node.id in jit_names:
            # only count LOAD uses (a decorator/call), not stores
            if isinstance(node.ctx, ast.Load):
                sites.append(node)
    return sites


def _imports_jit_name(pf: ParsedFile) -> bool:
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            if any(a.name == "jit" for a in node.names):
                return True
    return False


def _in_loop(node: ast.AST) -> Optional[ast.AST]:
    from langstream_tpu.analysis.core import parents

    prev: ast.AST = node
    for p in parents(node):
        if isinstance(p, (ast.For, ast.AsyncFor, ast.While)):
            return p
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a jit applied as THIS function's decorator still belongs
            # to the enclosing scope (a loop around the def re-jits per
            # iteration); a jit in the function BODY is warmed when the
            # factory runs once at build time
            if prev not in p.decorator_list:
                return None
        prev = p
    return None


def _jitted_local_names(pf: ParsedFile) -> set[str]:
    """Names bound to jitted callables in this module: decorated defs
    and ``name = jax.jit(...)`` / ``name = functools.partial(jax.jit,…)``
    assignments."""
    names: set[str] = set()
    for node in ast.walk(pf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _is_jit_ref(target) or (
                    isinstance(dec, ast.Call)
                    and any(_is_jit_ref(a) for a in dec.args)
                ):
                    names.add(node.name)
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            call = node.value
            if _is_jit_ref(call.func) or any(
                _is_jit_ref(a) for a in call.args
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _len_bounded_slice(node: ast.AST) -> Optional[ast.AST]:
    """A subscript argument sliced to ``len(...)`` anywhere inside the
    expression: the per-request-shape heuristic."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Subscript) and isinstance(
            sub.slice, ast.Slice
        ):
            for bound in (sub.slice.lower, sub.slice.upper):
                if (
                    isinstance(bound, ast.Call)
                    and isinstance(bound.func, ast.Name)
                    and bound.func.id == "len"
                ):
                    return sub
    return None


def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    seen_modules: set[str] = set()
    for pf in repo.files:
        if pf.rel.startswith("langstream_tpu/analysis/"):
            continue
        sites = _jit_sites(pf)
        if sites:
            seen_modules.add(pf.rel)
        expected = WARMED_MODULES.get(pf.rel)
        if sites and expected is None:
            for site in sites:
                findings.append(
                    Finding(
                        code="LSA301",
                        path=pf.rel,
                        line=site.lineno,
                        message=(
                            "jax.jit site in a module outside the "
                            "warmed-program registry "
                            "(analysis/compile_surface.WARMED_MODULES) — "
                            "register it and say what warms it"
                        ),
                    )
                )
        elif expected is not None and len(sites) != expected:
            line = sites[0].lineno if sites else 1
            findings.append(
                Finding(
                    code="LSA301",
                    path=pf.rel,
                    line=line,
                    message=(
                        f"module owns {len(sites)} jax.jit site(s) but "
                        f"the warmed-program registry says {expected} — "
                        "update analysis/compile_surface.WARMED_MODULES "
                        "with the warmup story for the change"
                    ),
                )
            )
        for site in sites:
            loop = _in_loop(site)
            if loop is not None:
                findings.append(
                    Finding(
                        code="LSA302",
                        path=pf.rel,
                        line=site.lineno,
                        message=(
                            "jax.jit inside a loop compiles one program "
                            "family per iteration — hoist it to module "
                            "scope or a build-once factory"
                        ),
                    )
                )
        jitted = _jitted_local_names(pf)
        if jitted:
            for node in ast.walk(pf.tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in jitted
                ):
                    for arg in node.args:
                        bad = _len_bounded_slice(arg)
                        if bad is not None:
                            findings.append(
                                Finding(
                                    code="LSA303",
                                    path=pf.rel,
                                    line=node.lineno,
                                    message=(
                                        f"operand of jitted "
                                        f"{node.func.id!r} is sliced to "
                                        "len(...) — a traced shape from "
                                        "a per-request value compiles "
                                        "one program per distinct "
                                        "length; pad to a bucket "
                                        "instead"
                                    ),
                                )
                            )
    # stale registry rows: module registered but no longer owns a site
    for rel, expected in WARMED_MODULES.items():
        if rel not in seen_modules and repo.get(rel) is not None:
            findings.append(
                Finding(
                    code="LSA301",
                    path=rel,
                    line=1,
                    message=(
                        f"warmed-program registry expects {expected} "
                        "jax.jit site(s) here but the module owns none — "
                        "drop the stale registry row"
                    ),
                )
            )
    return findings
