"""CLI: ``python -m langstream_tpu.analysis [--strict] [--only PASS]``.

Exit codes: 0 clean (after suppressions + baseline), 1 findings (or, in
--strict mode, stale baseline entries), 2 usage/internal error. The
tier-1 CI analysis job runs ``--strict``; the whole-repo-clean test in
tests/test_analysis.py runs the same entry programmatically, so drift
fails tier-1 even where CI config is not in play.
"""

from __future__ import annotations

import argparse
import json
import sys

from langstream_tpu.analysis.core import (
    all_checkers,
    apply_baseline,
    load_baseline,
    repo_root_from_here,
    run_checks,
    summarize,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m langstream_tpu.analysis",
        description="lstpu-check: repo-native static analysis "
        "(docs/ANALYSIS.md)",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="also fail on stale baseline entries (the CI mode)",
    )
    parser.add_argument(
        "--only",
        action="append",
        metavar="PASS",
        help="run a single pass (repeatable); default: all",
    )
    parser.add_argument(
        "--root",
        default=None,
        help="repo root (default: derived from the package location)",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="machine-readable findings on stdout",
    )
    parser.add_argument(
        "--list",
        action="store_true",
        help="list the registered passes and exit",
    )
    args = parser.parse_args(argv)

    if args.list:
        for name in all_checkers():
            print(name)
        return 0

    root = args.root or repo_root_from_here()
    try:
        repo, findings = run_checks(root, only=args.only)
        baseline = load_baseline(root)
        findings, stale = apply_baseline(findings, baseline)
    except RuntimeError as e:
        print(f"lstpu-check: {e}", file=sys.stderr)
        return 2

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.__dict__ for f in findings],
                    "stale_baseline": stale,
                    "summary": summarize(findings),
                },
                indent=1,
            )
        )
    else:
        for f in findings:
            print(f.render())
        if stale:
            for key, n in sorted(stale.items()):
                print(
                    f"stale baseline entry {key} ({n} unused)",
                    file=sys.stderr,
                )
    if findings:
        s = summarize(findings)
        print(
            f"lstpu-check: {s['total']} finding(s) "
            + " ".join(
                f"{c}={n}" for c, n in sorted(s["by_code"].items())
            ),
            file=sys.stderr,
        )
        return 1
    if args.strict and stale:
        print(
            "lstpu-check: clean tree but stale baseline — shrink "
            ".lstpu-baseline.json",
            file=sys.stderr,
        )
        return 1
    print(
        f"lstpu-check: clean ({len(repo.files)} files, "
        f"{len(all_checkers() if not args.only else args.only)} passes)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
