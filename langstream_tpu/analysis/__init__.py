"""lstpu-check: the repo-native static analysis suite.

The serving core is a multi-threaded ~11k-line engine whose correctness
rests on hand-enforced invariants: counters mutate only under their
annotated lock, flight dumps / beacons / wire frames never carry token
content, the jit compile surface stays a fixed warmed ladder, and every
fault site / dump reason / knob / gauge stays in sync with its chaos
test, Grafana panel and docs section. Those invariants used to live in
reviewers' heads and a handful of runtime tests; CHANGES.md records at
least three shipped races a static pass would have flagged at PR time
(the submit-side shed counts lost outside the lock, the finish-waker
teardown race, the spill-worker wedged-join arena hazard).

This package is the static twin of the runtime checks
(docs/ANALYSIS.md):

- ``core``            shared visitor/reporting frame: file discovery,
                      parent-annotated ASTs, ``# lstpu: ignore[CODE]``
                      suppressions, the committed baseline file
- ``locks``           LSA1xx lock discipline (the ``_GUARDED`` class
                      registry convention)
- ``redaction``       LSA2xx redaction taint (dump extras, span
                      attributes, beacons, wire frames)
- ``compile_surface`` LSA3xx compile-surface lint (the warmed-program
                      registry that keeps ``compiled_programs`` flat)
- ``registry_drift``  LSA4xx registry drift (fault sites, dump reasons,
                      knobs, gauges vs tests / docs / dashboards)
- ``threads``         LSA5xx thread-shutdown hygiene (explicit
                      ``daemon=``, reachable join on the close path)
- ``lockorder``       the RUNTIME companion: a test-only lock-order
                      recorder that wraps the annotated locks during
                      the chaos suite and fails on acquisition cycles

Run ``python -m langstream_tpu.analysis --strict`` (the tier-1 CI
analysis job). No jax imports anywhere in the package: the suite parses
source, it never executes it.
"""

from langstream_tpu.analysis.core import (  # noqa: F401
    Finding,
    Repo,
    all_checkers,
    run_checks,
)
