"""LSA4xx — registry drift: fault sites, dump reasons, knobs and
metric names must stay in sync with their chaos tests, docs sections
and Grafana panels.

Every subsystem since round 6 keeps a registry whose entries fan out
into other artifacts: ``faultinject.SITES`` entries get chaos drills
and a §9 docs row, ``DUMP_REASONS`` entries get schema tests,
``tpu-serving`` knobs get a docs knob-table row, and every metric a
dashboard panel queries must actually be registered somewhere. Those
cross-checks used to run piecemeal at test time
(``test_metrics_artifacts.py``); this pass is their single static
home:

- LSA401  a fault-site string consulted via ``fires("…")`` that
          ``faultinject.SITES`` does not register (the injector would
          raise at runtime — but only on the code path that consults
          it, which is exactly the path chaos never exercised)
- LSA402  a dump reason passed to ``FlightRecorder.dump("…")`` that
          ``DUMP_REASONS`` does not register (validate_flight_dump
          rejects the artifact at incident time)
- LSA403  a registered fault site or dump reason with no test
          coverage (string absent from tests/) or no docs mention
          (absent from docs/SERVING.md) — a failure story that has
          never executed is a comment, not a feature
- LSA404  a ``tpu-serving`` config knob read in ai/tpu_serving.py that
          docs/SERVING.md never mentions (an undocumented knob is an
          unsupported knob)
- LSA405  a Grafana dashboard ``__name__`` matcher whose metric suffix
          nothing in the source registers (a panel that can never show
          data)
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Optional

from langstream_tpu.analysis.core import (
    Finding,
    Repo,
    call_name,
    literal_str,
)

FAULTINJECT_REL = "langstream_tpu/serving/faultinject.py"
OBSERVABILITY_REL = "langstream_tpu/serving/observability.py"
TPU_SERVING_REL = "langstream_tpu/ai/tpu_serving.py"
DASHBOARD_REL = "docker/metrics/dashboards/serving.json"
DOCS_REL = "docs/SERVING.md"

_METRIC_REG_RE = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*\"([a-z0-9_]+)\""
)


def _tuple_entries(
    repo: Repo, rel: str, name: str
) -> Optional[list[tuple[str, int]]]:
    """Entries (value, line) of a module-level tuple-of-strings
    assignment like ``SITES = (…)``."""
    pf = repo.get(rel)
    if pf is None:
        return None
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            if isinstance(node.value, (ast.Tuple, ast.List)):
                out = []
                for el in node.value.elts:
                    s = literal_str(el)
                    if s is not None:
                        out.append((s, el.lineno))
                return out
    return None


def _read_corpus(root: str, sub: str, suffix: str = ".py") -> str:
    chunks = []
    base = os.path.join(root, sub)
    if not os.path.isdir(base):
        return ""
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(suffix):
                try:
                    with open(
                        os.path.join(dirpath, fn), encoding="utf-8"
                    ) as f:
                        chunks.append(f.read())
                except OSError:
                    pass
    return "\n".join(chunks)


def _read_file(root: str, rel: str) -> str:
    try:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            return f.read()
    except OSError:
        return ""


def _check_fires_literals(
    repo: Repo, sites: set[str], findings: list[Finding]
) -> None:
    for pf in repo.files:
        if pf.rel == FAULTINJECT_REL or pf.rel.startswith(
            "langstream_tpu/analysis/"
        ):
            continue
        for node in ast.walk(pf.tree):
            if not (
                isinstance(node, ast.Call) and call_name(node) == "fires"
            ):
                continue
            for arg in node.args[:1]:
                site = literal_str(arg)
                if site is not None and site not in sites:
                    findings.append(
                        Finding(
                            code="LSA401",
                            path=pf.rel,
                            line=node.lineno,
                            message=(
                                f"fault site {site!r} is consulted here "
                                "but faultinject.SITES does not register "
                                "it — the injector raises on the exact "
                                "path chaos never exercised"
                            ),
                        )
                    )


def _check_dump_reasons(
    repo: Repo, reasons: set[str], findings: list[Finding]
) -> None:
    for pf in repo.files:
        if pf.rel == OBSERVABILITY_REL or pf.rel.startswith(
            "langstream_tpu/analysis/"
        ):
            continue
        for node in ast.walk(pf.tree):
            if not (
                isinstance(node, ast.Call) and call_name(node) == "dump"
            ):
                continue
            reason = None
            if node.args:
                reason = literal_str(node.args[0])
            for kw in node.keywords:
                if kw.arg == "reason":
                    reason = literal_str(kw.value)
            # only flag calls that look like FlightRecorder.dump —
            # they carry reason/extra/counters kwargs or a known-style
            # reason string; json.dump(obj, fh) passes a non-literal
            if reason is not None and reason not in reasons:
                findings.append(
                    Finding(
                        code="LSA402",
                        path=pf.rel,
                        line=node.lineno,
                        message=(
                            f"dump reason {reason!r} is not in "
                            "observability.DUMP_REASONS — "
                            "validate_flight_dump rejects the artifact "
                            "at incident time"
                        ),
                    )
                )


def _check_coverage(
    entries: list[tuple[str, int]],
    rel: str,
    what: str,
    tests_corpus: str,
    docs_text: str,
    findings: list[Finding],
) -> None:
    for value, line in entries:
        # substring, not exact-quoted: chaos specs reference sites as
        # "migrate@1" / "weights:0.5" compounds, so the bare value is
        # the only stable token
        if value not in tests_corpus:
            findings.append(
                Finding(
                    code="LSA403",
                    path=rel,
                    line=line,
                    message=(
                        f"{what} {value!r} has no test coverage (the "
                        "string appears nowhere under tests/) — drills "
                        "before registries"
                    ),
                )
            )
        if value not in docs_text:
            findings.append(
                Finding(
                    code="LSA403",
                    path=rel,
                    line=line,
                    message=(
                        f"{what} {value!r} is undocumented "
                        f"({DOCS_REL} never mentions it)"
                    ),
                )
            )


def _knob_reads(repo: Repo) -> list[tuple[str, int]]:
    pf = repo.get(TPU_SERVING_REL)
    if pf is None:
        return []
    out = []
    seen = set()
    for node in ast.walk(pf.tree):
        if not (isinstance(node, ast.Call) and call_name(node) == "get"):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Attribute)
            and func.value.attr == "config"
        ):
            continue
        if node.args:
            knob = literal_str(node.args[0])
            if knob is not None and knob not in seen:
                seen.add(knob)
                out.append((knob, node.lineno))
    return out


def _dashboard_suffixes(root: str) -> list[str]:
    text = _read_file(root, DASHBOARD_REL)
    if not text:
        return []
    try:
        doc = json.loads(text)
    except ValueError:
        return []
    exprs = [
        t["expr"]
        for panel in doc.get("panels", [])
        for t in panel.get("targets", [])
        if "expr" in t
    ]
    joined = "\n".join(exprs)
    return re.findall(r'__name__=~\\?"([^"\\]+)', joined)


def _registered_metric_names(repo: Repo) -> set[str]:
    names: set[str] = set()
    for pf in repo.files:
        names.update(_METRIC_REG_RE.findall(pf.source))
    for hist_name in ("ENGINE_HISTOGRAMS", "FLEET_HISTOGRAMS"):
        pf = repo.get(OBSERVABILITY_REL)
        if pf is None:
            continue
        for node in ast.walk(pf.tree):
            if (
                isinstance(node, (ast.Assign, ast.AnnAssign))
                and isinstance(node.value, ast.Dict)
            ):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                if any(
                    isinstance(t, ast.Name) and t.id == hist_name
                    for t in targets
                ):
                    for k in node.value.keys:
                        h = literal_str(k) if k is not None else None
                        if h is not None:
                            names.add(h)
                            names.update(
                                {f"{h}_bucket", f"{h}_sum", f"{h}_count"}
                            )
    return names


def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    root = repo.root

    sites = _tuple_entries(repo, FAULTINJECT_REL, "SITES") or []
    reasons = _tuple_entries(repo, OBSERVABILITY_REL, "DUMP_REASONS") or []
    tests_corpus = _read_corpus(root, "tests")
    docs_text = _read_file(root, DOCS_REL)

    if sites:
        _check_fires_literals(repo, {s for s, _ in sites}, findings)
        _check_coverage(
            sites, FAULTINJECT_REL, "fault site", tests_corpus, docs_text,
            findings,
        )
    if reasons:
        _check_dump_reasons(repo, {r for r, _ in reasons}, findings)
        _check_coverage(
            reasons, OBSERVABILITY_REL, "dump reason", tests_corpus,
            docs_text, findings,
        )

    for knob, line in _knob_reads(repo):
        if knob not in docs_text:
            findings.append(
                Finding(
                    code="LSA404",
                    path=TPU_SERVING_REL,
                    line=line,
                    message=(
                        f"tpu-serving knob {knob!r} is read here but "
                        f"{DOCS_REL} never documents it — an "
                        "undocumented knob is an unsupported knob"
                    ),
                )
            )

    registered = _registered_metric_names(repo)
    if registered:
        for regex in _dashboard_suffixes(root):
            suffix = regex.rsplit("_completions_", 1)[-1].rsplit(".+_", 1)[-1]
            if suffix not in registered:
                findings.append(
                    Finding(
                        code="LSA405",
                        path=DASHBOARD_REL,
                        line=1,
                        message=(
                            f"dashboard matcher {regex!r} references "
                            f"metric suffix {suffix!r} that nothing in "
                            "the source registers — the panel can never "
                            "show data"
                        ),
                    )
                )
    return findings
