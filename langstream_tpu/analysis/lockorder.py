"""Runtime lock-order recorder — the dynamic companion to the static
``locks`` pass (docs/ANALYSIS.md).

The static pass proves every guarded write holds ITS lock; it cannot
prove two locks are always taken in one order. This recorder can:
``install()`` swaps ``threading.Lock`` for a factory that wraps locks
created from langstream_tpu frames, tags each with its CREATION site
(file:line — the stable identity across engine instances), and records
a directed edge ``held-site -> acquiring-site`` every time a thread
acquires one lock while holding another. A cycle in that graph is a
lock-order inversion: two threads interleaving those paths can deadlock
even though every individual acquisition is lock-correct.

Test-only by design: the wrapper costs a dict lookup per acquire, so it
is armed via ``LSTPU_LOCKORDER=1`` (the chaos CI step) through the
conftest session fixture, never in production. Same-site edges are
skipped — two INSTANCES of one class locking in sequence (router A then
router B) share a creation site, and ordering between instances is a
different discipline (address-ordered locking) the recorder cannot
judge from sites alone.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

_REAL_LOCK = threading.Lock


def _caller_site(depth: int = 2) -> Optional[str]:
    """``file:line`` of the frame creating the lock, repo-relative, or
    None when the creation site is outside langstream_tpu (stdlib queue/
    logging locks stay untracked and untaxed)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    fn = frame.f_code.co_filename
    marker = "langstream_tpu" + os.sep
    idx = fn.rfind(marker)
    if idx < 0:
        return None
    rel = fn[idx:].replace(os.sep, "/")
    if rel.startswith("langstream_tpu/analysis/"):
        return None  # never instrument ourselves
    return f"{rel}:{frame.f_lineno}"


class _TrackedLock:
    """A real lock plus edge recording. Proxy, not subclass —
    ``threading.Lock`` is a factory function, not a type."""

    __slots__ = ("_lock", "_site", "_rec")

    def __init__(self, rec: "LockOrderRecorder", site: str) -> None:
        self._lock = _REAL_LOCK()
        self._site = site
        self._rec = rec

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._rec._note_acquire(self._site)
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._rec._note_held(self._site)
        return got

    def release(self) -> None:
        self._rec._note_release(self._site)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class LockOrderRecorder:
    """Process-wide edge collector; one instance per test session."""

    def __init__(self) -> None:
        self._edges: dict[tuple[str, str], int] = {}
        self._elock = _REAL_LOCK()
        self._tls = threading.local()
        self._installed = False

    # -- instrumentation hooks (called from _TrackedLock) ------------------

    def _held(self) -> list[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, site: str) -> None:
        held = self._held()
        new_edges = [
            (h, site) for h in held if h != site
        ]
        if new_edges:
            with self._elock:
                for e in new_edges:
                    self._edges[e] = self._edges.get(e, 0) + 1

    def _note_held(self, site: str) -> None:
        self._held().append(site)

    def _note_release(self, site: str) -> None:
        held = self._held()
        # release order may not mirror acquire order; drop the LAST match
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                break

    # -- install / report --------------------------------------------------

    def install(self) -> None:
        if self._installed:
            return

        rec = self

        def _factory() -> object:
            site = _caller_site()
            if site is None:
                return _REAL_LOCK()
            return _TrackedLock(rec, site)

        threading.Lock = _factory  # type: ignore[assignment]
        self._installed = True

    def uninstall(self) -> None:
        if self._installed:
            threading.Lock = _REAL_LOCK  # type: ignore[assignment]
            self._installed = False

    def edges(self) -> dict[tuple[str, str], int]:
        with self._elock:
            return dict(self._edges)

    def cycles(self) -> list[list[str]]:
        """Every elementary inversion witness found by DFS over the
        aggregated edge graph (usually length 2: A->B and B->A)."""
        graph: dict[str, set[str]] = {}
        for (a, b) in self.edges():
            graph.setdefault(a, set()).add(b)
        out: list[list[str]] = []
        seen_cycles: set[tuple[str, ...]] = set()
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in sorted(graph.get(node, ())):
                    if nxt == start:
                        cyc = path + [start]
                        # canonicalize by rotation so each cycle reports once
                        body = tuple(sorted(cyc[:-1]))
                        if body not in seen_cycles:
                            seen_cycles.add(body)
                            out.append(cyc)
                    elif nxt not in path and len(path) < 8:
                        stack.append((nxt, path + [nxt]))
        return out

    def report(self) -> str:
        lines = []
        for cyc in self.cycles():
            lines.append(
                "lock-order inversion: " + " -> ".join(cyc)
            )
        return "\n".join(lines)


_ACTIVE: Optional[LockOrderRecorder] = None


def enabled() -> bool:
    return os.environ.get("LSTPU_LOCKORDER", "") == "1"


def activate() -> LockOrderRecorder:
    """Install the process-wide recorder (idempotent); the conftest
    session fixture calls this when LSTPU_LOCKORDER=1."""
    global _ACTIVE
    if _ACTIVE is None:
        _ACTIVE = LockOrderRecorder()
        _ACTIVE.install()
    return _ACTIVE


def deactivate() -> Optional[LockOrderRecorder]:
    """Uninstall and return the recorder (for the end-of-session cycle
    assertion)."""
    global _ACTIVE
    rec = _ACTIVE
    if rec is not None:
        rec.uninstall()
        _ACTIVE = None
    return rec
