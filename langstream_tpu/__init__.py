"""langstream_tpu — a TPU-native, event-driven framework for streaming Gen-AI apps.

Same capabilities as the reference (LangStream: declarative YAML pipelines of
agents wired through broker topics, planner, per-agent runners with ordered
at-least-once commit, websocket/HTTP gateway, control plane/operator) but with
inference served locally on TPUs through a JAX/XLA engine (continuous batching,
jit prefill/decode, tensor/expert parallelism over an ICI mesh).

Layer map (mirrors SURVEY.md §1):
  api/            L0 model + SPIs (pure dataclasses/ABCs)
  core/           L1 parser / placeholder resolver / validator / planner
  messaging/      L2 broker runtimes: memory, kafka, pulsar, pravega (all dependency-free wire clients)
  runtime/        L3 agent runner main loop, ordered commit, local runner
  agents/         L4 built-in agent library
  ai/             provider SPI (completions/embeddings) + TPU provider
  models/         JAX model family (decoder LMs + encoder embedders)
  serving/        continuous-batching TPU serving engine
  ops/            Pallas kernels + XLA fallbacks (attention, paged attention)
  parallel/       mesh / sharding / collectives helpers
  gateway/        L6 websocket/HTTP API gateway
  control_plane/  L7/L8 REST control plane + operator resource factory
  cli/            L9 command line client
"""

__version__ = "0.1.0"
