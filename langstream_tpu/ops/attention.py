"""Flash-attention Pallas kernels.

Two hot paths, both GQA-aware (queries grouped per kv head so K/V blocks are
read once per group, not once per query head). K/V come in HEAD-MAJOR layout
[B, Hkv, T, D] — the kv-head axis stays out of the trailing two dims, so the
Mosaic TPU lowering's (8, 128) block-tiling constraint falls on (T, D) where
blocks are naturally aligned, and a per-head kv block is a contiguous
(block_k, D) slice (no relayout per grid step).

- ``flash_prefill_attention``: causal blocked attention with fp32
  online-softmax scratch accumulators — O(block_q x block_k) VMEM instead of
  the O(S^2) masked score tensor the jnp path materializes.
- ``ragged_decode_attention``: one query per sequence against a KV cache,
  skipping cache blocks past each row's true length (the continuous batcher
  packs rows of very different lengths into one step, so the dense masked
  read wastes bandwidth proportional to max_len - mean_len).

No reference counterpart (the reference's compute is remote HTTP calls);
kernel structure follows the public flash/paged-attention pattern from the
Pallas TPU guide.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from langstream_tpu.models.configs import ModelConfig

_NEG = -1e30


def _fit_block(block: int, n: int) -> int:
    """Largest block ≤ ``block`` that divides ``n``. pallas_ok blesses any
    128-multiple length, so a 512 default block must step down (512 → 256 →
    128) for lengths like 640/768 rather than tripping the divisibility
    assert."""
    block = min(block, n)
    while block > 1 and n % block != 0:
        block //= 2
    return block


def _vmem_block_q(block_q: int, group: int, d: int, itemsize: int) -> int:
    """Shrink block_q until the kernel's VMEM footprint fits the ~16MB
    scoped budget. The prefill/segment kernels hold double-buffered q/out
    blocks [G, block_q, D] plus f32 m/l/acc scratch [G, block_q, 128|D]:
    at the 512 default that is ~17MB for fat-head models (gemma G=8
    D=256 — Mosaic refused to compile exactly this in the r5 bench) but
    ~5MB for llama (G=4 D=128), so the cap must be shape-aware rather
    than a smaller global default that would slow llama down."""
    while block_q > 128:
        io = 2 * 2 * group * block_q * d * itemsize  # q + out, ×2 buffers
        scratch = group * block_q * (128 + 128 + d) * 4
        if io + scratch <= 11 * 1024 * 1024:
            break
        block_q //= 2
    return block_q


# ---------------------------------------------------------------------------
# Prefill: causal blocked flash attention
# ---------------------------------------------------------------------------


def _prefill_kernel(
    q_ref,  # [1, 1, G, block_q, D]
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    o_ref,  # [1, 1, G, block_q, D]
    m_scr,  # [G, block_q, 128] f32
    l_scr,  # [G, block_q, 128] f32
    acc_scr,  # [G, block_q, D] f32
    *,
    block_q: int,
    block_k: int,
    scale: float,
    softcap,
):
    i = pl.program_id(2)  # query block
    j = pl.program_id(3)  # key block
    nk = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_start = i * block_q
    k_start = j * block_k

    # causal: skip key blocks strictly above the diagonal
    @pl.when(k_start <= q_start + block_q - 1)
    def _body():
        # dots stay in the MODEL dtype (bf16 in production) with fp32
        # accumulation — casting operands to f32 forced multi-pass f32 MXU
        # matmuls and capped the kernel at ~14 TFLOPS effective (measured
        # r5; the entire 19s 32k-prefill TTFT was this)
        q = q_ref[0, 0, :, :, :]  # [G, block_q, D]
        k = k_ref[0, 0, :, :]  # [block_k, D]
        v = v_ref[0, 0, :, :]
        s = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [G, block_q, block_k] f32
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_q, block_k), 1)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_q, block_k), 2)
        s = jnp.where(k_pos <= q_pos, s, _NEG)

        m_prev = m_scr[:, :, 0]  # [G, block_q]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, :, None])
        p = jnp.where(s <= _NEG, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :, 0] = l_scr[:, :, 0] * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, block_q, D]
        acc_scr[...] = acc_scr[...] * corr[:, :, None] + pv
        m_scr[:, :, 0] = m_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :, 0], 1e-30)[:, :, None]  # [G, block_q, 1]
        o_ref[0, 0, :, :, :] = (acc_scr[...] / l).astype(o_ref.dtype)


def flash_prefill_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, Hkv, S, D] head-major
    v: jax.Array,  # [B, Hkv, S, D]
    config: ModelConfig,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Causal GQA attention → [B, S, H*D]."""
    b, s, h, d = q.shape
    hkv = k.shape[1]
    group = h // hkv
    block_q = _fit_block(
        _vmem_block_q(block_q, group, d, jnp.dtype(q.dtype).itemsize), s
    )
    block_k = _fit_block(block_k, s)
    assert s % block_q == 0 and s % block_k == 0, "caller gates divisibility"
    # head-major queries: [B, Hkv, G, S, D] so the blocked dims are (S, D)
    qg = q.reshape(b, s, hkv, group, d).transpose(0, 2, 3, 1, 4)

    kernel = functools.partial(
        _prefill_kernel,
        block_q=block_q,
        block_k=block_k,
        scale=1.0 / (d**0.5),
        softcap=config.attn_logit_softcap,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b, hkv, s // block_q, s // block_k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group, block_q, d), lambda b, h, i, j: (b, h, 0, i, 0)
            ),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, block_q, d), lambda b, h, i, j: (b, h, 0, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((group, block_q, 128), jnp.float32),
            pltpu.VMEM((group, block_q, 128), jnp.float32),
            pltpu.VMEM((group, block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qg, k, v)
    # [B, Hkv, G, S, D] → [B, S, H*D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s, h * d)


# ---------------------------------------------------------------------------
# Chunked prefill: a prompt SEGMENT at a global offset attending to the
# already-written cache prefix (long-context serving; the engine loops this
# over 2k-token segments so any prompt <= max_seq_len serves with bounded
# activation memory — the O(S^2) single-shot prefill never materializes)
# ---------------------------------------------------------------------------


def _segment_body(
    off_ref,  # [B] int32 scalar-prefetch: global position of segment start
    q_ref,  # [1, 1, G, block_q, D]
    load_kv,  # (q_dtype) -> ([block_k, D], [block_k, D]) in model dtype
    o_ref,  # [1, 1, G, block_q, D]
    m_scr,  # [G, block_q, 128] f32
    l_scr,  # [G, block_q, 128] f32
    acc_scr,  # [G, block_q, D] f32
    *,
    block_q: int,
    block_k: int,
    scale: float,
    softcap,
):
    """Shared online-softmax body of the two segment kernels (bf16 cache
    and int8 cache differ only in how the K/V block materializes)."""
    b = pl.program_id(0)
    i = pl.program_id(2)  # query block (within the segment)
    j = pl.program_id(3)  # key block (over the full cache width)
    nk = pl.num_programs(3)
    off = off_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_start = off + i * block_q  # GLOBAL position of this q block's first row
    k_start = j * block_k

    # causal against global positions: the whole prefix (k < off) is visible,
    # plus the lower triangle within the segment
    @pl.when(k_start <= q_start + block_q - 1)
    def _body():
        # model-dtype dots, fp32 accumulation (see _prefill_kernel note:
        # f32-cast operands ran the MXU at ~14 TFLOPS — the 32k TTFT)
        q = q_ref[0, 0, :, :, :]  # [G, block_q, D]
        k, v = load_kv(q.dtype)
        s = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_q, block_k), 1)
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_q, block_k), 2)
        s = jnp.where(k_pos <= q_pos, s, _NEG)

        m_prev = m_scr[:, :, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, :, None])
        p = jnp.where(s <= _NEG, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :, 0] = l_scr[:, :, 0] * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v.dtype),
            v,
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * corr[:, :, None] + pv
        m_scr[:, :, 0] = m_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :, 0], 1e-30)[:, :, None]
        o_ref[0, 0, :, :, :] = (acc_scr[...] / l).astype(o_ref.dtype)


def _segment_kernel(
    off_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, **opts
):
    _segment_body(
        off_ref, q_ref,
        lambda _dt: (k_ref[0, 0, :, :], v_ref[0, 0, :, :]),
        o_ref, m_scr, l_scr, acc_scr, **opts,
    )


def flash_segment_attention(
    q: jax.Array,  # [B, S, H, D] — segment queries
    k: jax.Array,  # [B, Hkv, T, D] cache (head-major), T >= offset + S
    v: jax.Array,  # [B, Hkv, T, D]
    offset: jax.Array,  # [B] int32 global position of the segment start
    config: ModelConfig,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Causal GQA attention of a segment against cache prefix + itself
    → [B, S, H*D]. The segment's own K/V must already be scattered into the
    cache at [offset, offset+S)."""
    b, s, h, d = q.shape
    hkv = k.shape[1]
    t = k.shape[2]
    group = h // hkv
    block_q = _fit_block(
        _vmem_block_q(block_q, group, d, jnp.dtype(q.dtype).itemsize), s
    )
    block_k = _fit_block(block_k, t)
    assert s % block_q == 0 and t % block_k == 0, "caller gates divisibility"
    qg = q.reshape(b, s, hkv, group, d).transpose(0, 2, 3, 1, 4)

    kernel = functools.partial(
        _segment_kernel,
        block_q=block_q,
        block_k=block_k,
        scale=1.0 / (d**0.5),
        softcap=config.attn_logit_softcap,
    )

    def kv_index(b, h, i, j, off):
        # clamp past-diagonal blocks to the last block this q block needs:
        # Pallas re-references the SAME block and elides the HBM→VMEM DMA,
        # so early segments don't stream the whole (mostly-unwritten) cache
        last = jnp.maximum(pl.cdiv(off[b] + (i + 1) * block_q, block_k) - 1, 0)
        return (b, h, jnp.minimum(j, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, s // block_q, t // block_k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group, block_q, d), lambda b, h, i, j, off: (b, h, 0, i, 0)
            ),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, block_q, d), lambda b, h, i, j, off: (b, h, 0, i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, block_q, 128), jnp.float32),
            pltpu.VMEM((group, block_q, 128), jnp.float32),
            pltpu.VMEM((group, block_q, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, s, d), q.dtype),
        interpret=interpret,
    )(offset.astype(jnp.int32), qg, k, v)
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s, h * d)


def _segment_int8_kernel(
    off_ref,  # [B] int32 scalar-prefetch: global position of segment start
    q_ref,  # [1, 1, G, block_q, D]
    kq_ref,  # [1, 1, block_k, D] int8
    ks_ref,  # [1, 1, block_k, 1] f32 per-token scales
    vq_ref,  # [1, 1, block_k, D] int8
    vs_ref,  # [1, 1, block_k, 1] f32
    o_ref,  # [1, 1, G, block_q, D]
    m_scr,  # [G, block_q, 128] f32
    l_scr,  # [G, block_q, 128] f32
    acc_scr,  # [G, block_q, D] f32
    **opts,
):
    """_segment_body over an int8 KV cache: the HBM read stays int8
    (the r5 32k-TTFT residual was the materialized bf16 cache copy the
    non-quantized kernel forced — ~8.6GB of traffic per late segment);
    K/V dequantize in VMEM to the model dtype so the dots still ride the
    MXU at bf16 rate (f32 operands measured 14 vs 34.8 TFLOPS)."""

    def load_kv(dtype):
        k = (kq_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0]).astype(dtype)
        v = (vq_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0]).astype(dtype)
        return k, v

    _segment_body(off_ref, q_ref, load_kv, o_ref, m_scr, l_scr, acc_scr, **opts)


def flash_segment_attention_int8(
    q: jax.Array,  # [B, S, H, D] — segment queries
    k: dict,  # int8 cache entry {"q": [B,Hkv,T,D] i8, "s": [B,Hkv,T] f32}
    v: dict,
    offset: jax.Array,  # [B] int32 global position of the segment start
    config: ModelConfig,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """flash_segment_attention directly over the int8 KV cache → no
    cache-sized bf16 temp, int8 on the HBM wire. Same causal/GQA math."""
    b, s, h, d = q.shape
    hkv = k["q"].shape[1]
    t = k["q"].shape[2]
    group = h // hkv
    block_q = _fit_block(
        _vmem_block_q(block_q, group, d, jnp.dtype(q.dtype).itemsize), s
    )
    block_k = _fit_block(block_k, t)
    assert s % block_q == 0 and t % block_k == 0, "caller gates divisibility"
    qg = q.reshape(b, s, hkv, group, d).transpose(0, 2, 3, 1, 4)

    kernel = functools.partial(
        _segment_int8_kernel,
        block_q=block_q,
        block_k=block_k,
        scale=1.0 / (d**0.5),
        softcap=config.attn_logit_softcap,
    )

    def kv_index(b, h, i, j, off):
        # clamp past-diagonal blocks to the last block this q block needs
        # (same DMA-eliding trick as the bf16 segment kernel)
        last = jnp.maximum(pl.cdiv(off[b] + (i + 1) * block_q, block_k) - 1, 0)
        return (b, h, jnp.minimum(j, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, s // block_q, t // block_k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group, block_q, d), lambda b, h, i, j, off: (b, h, 0, i, 0)
            ),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            # trailing singleton: Mosaic needs the block's last two dims
            # (8,128)-divisible or equal to the array's — [.., block_k, 1]
            pl.BlockSpec((1, 1, block_k, 1), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, 1), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, block_q, d), lambda b, h, i, j, off: (b, h, 0, i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, block_q, 128), jnp.float32),
            pltpu.VMEM((group, block_q, 128), jnp.float32),
            pltpu.VMEM((group, block_q, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, s, d), q.dtype),
        interpret=interpret,
    )(
        offset.astype(jnp.int32),
        qg,
        k["q"],
        k["s"][..., None],
        v["q"],
        v["s"][..., None],
    )
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s, h * d)


# ---------------------------------------------------------------------------
# Decode: one query per row against a ragged KV cache
# ---------------------------------------------------------------------------


def _decode_kernel(
    lengths_ref,  # scalar-prefetch [B]
    q_ref,  # [1, 1, G, D]
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    o_ref,  # [1, 1, G, D]
    m_scr,  # [G, 128] f32
    l_scr,  # [G, 128] f32
    acc_scr,  # [G, D] f32
    *,
    block_k: int,
    scale: float,
    softcap,
):
    b = pl.program_id(0)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    length = lengths_ref[b]
    k_start = j * block_k

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # skip cache blocks entirely past this row's written length
    @pl.when(k_start < length)
    def _body():
        q = q_ref[0, 0, :, :].astype(jnp.float32)  # [G, D]
        k = k_ref[0, 0, :, :].astype(jnp.float32)  # [block_k, D]
        v = v_ref[0, 0, :, :].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [G, block_k]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        s = jnp.where(k_pos < length, s, _NEG)

        m_prev = m_scr[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s <= _NEG, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * corr + p.sum(axis=-1)
        pv = jnp.dot(p, v, preferred_element_type=jnp.float32)  # [G, D]
        acc_scr[...] = acc_scr[...] * corr[:, None] + pv
        m_scr[:, 0] = m_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, 0], 1e-30)[:, None]
        o_ref[0, 0, :, :] = (acc_scr[...] / l).astype(o_ref.dtype)


def ragged_decode_attention(
    q: jax.Array,  # [B, H, D] single query per row
    k: jax.Array,  # [B, Hkv, T, D] cache (head-major)
    v: jax.Array,  # [B, Hkv, T, D]
    lengths: jax.Array,  # [B] int32 — valid cache prefix per row
    config: ModelConfig,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """GQA decode attention → [B, H*D]."""
    b, h, d = q.shape
    hkv = k.shape[1]
    t = k.shape[2]
    group = h // hkv
    block_k = _fit_block(block_k, t)
    assert t % block_k == 0, "caller gates divisibility"
    qg = q.reshape(b, hkv, group, d)

    kernel = functools.partial(
        _decode_kernel,
        block_k=block_k,
        scale=1.0 / (d**0.5),
        softcap=config.attn_logit_softcap,
    )
    def kv_index(b, h, j, lens):
        # paged-attention trick: clamp the block index at this row's last
        # valid block, so grid steps past the length re-reference the SAME
        # block and Pallas elides the HBM→VMEM copy — the DMA skip is where
        # the ragged bandwidth saving actually comes from (the pl.when only
        # skips the FLOPs)
        last = jnp.maximum(pl.cdiv(lens[b], block_k) - 1, 0)
        return (b, h, jnp.minimum(j, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, t // block_k),
        in_specs=[
            # index maps receive the scalar-prefetch ref as a trailing arg
            pl.BlockSpec((1, 1, group, d), lambda b, h, j, lens: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, d), lambda b, h, j, lens: (b, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qg, k, v)
    return out.reshape(b, h * d)


# ---------------------------------------------------------------------------
# Decode over an INT8 cache: same ragged structure, but k/v blocks are read
# raw int8 (+ per-token f32 scales) straight from HBM — cache bandwidth is
# the decode bottleneck (measured r5: llama-3-8b B=96 step time 27.9ms at
# T=256 vs 61.8ms at T=1024 — the dense masked read scales with cache WIDTH,
# not content), and the block-skip makes it scale with the longest row
# instead.
# ---------------------------------------------------------------------------


def _decode_int8_kernel(
    lengths_ref,  # scalar-prefetch [B]
    q_ref,  # [1, Hkv, G, D]
    kq_ref,  # [1, Hkv, block_k, D] int8
    ks_ref,  # [1, Hkv, block_k, 1] f32 per-token scales
    vq_ref,  # [1, Hkv, block_k, D] int8
    vs_ref,  # [1, Hkv, block_k, 1] f32
    o_ref,  # [1, Hkv, G, D]
    m_scr,  # [Hkv, G, 128] f32
    l_scr,  # [Hkv, G, 128] f32
    acc_scr,  # [Hkv, G, D] f32
    *,
    block_k: int,
    scale: float,
    softcap,
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nk = pl.num_programs(1)
    length = lengths_ref[b]
    k_start = j * block_k

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(k_start < length)
    def _body():
        # ALL kv heads ride one grid step (batched dots): an [B,Hkv,·]
        # grid needed 8x the steps, and per-step grid overhead made the
        # kernel LOSE to the dense masked path (592 vs 1322 tok/s, r5)
        q = q_ref[0].astype(jnp.float32)  # [Hkv, G, D]
        # dequantize IN VMEM: the HBM read stays int8 (the bandwidth win)
        k = kq_ref[0].astype(jnp.float32) * ks_ref[0]  # [Hkv, block_k, D]
        v = vq_ref[0].astype(jnp.float32) * vs_ref[0]
        s = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Hkv, G, block_k]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_k), 2
        )
        s = jnp.where(k_pos < length, s, _NEG)

        m_prev = m_scr[:, :, 0]  # [Hkv, G]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, :, None])
        p = jnp.where(s <= _NEG, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :, 0] = l_scr[:, :, 0] * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [Hkv, G, D]
        acc_scr[...] = acc_scr[...] * corr[:, :, None] + pv
        m_scr[:, :, 0] = m_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :, 0], 1e-30)[:, :, None]
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


def ragged_decode_attention_int8(
    q: jax.Array,  # [B, H, D] single query per row
    k: dict,  # int8 cache entry {"q": [B,Hkv,T,D] i8, "s": [B,Hkv,T] f32}
    v: dict,
    lengths: jax.Array,  # [B]
    config: ModelConfig,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """GQA decode attention over an int8 KV cache → [B, H*D].

    Grid is (B, T/block_k) with every kv head inside the block — fewer,
    fatter grid steps and ~1MB DMAs. Blocks past a row's length clamp to
    its last valid block (DMA elided), so HBM traffic scales with CONTENT
    (sum of lengths), not cache width, and stays int8 on the wire.

    Differs from the jnp int8 path in q handling (q stays full precision
    here; the jnp path re-quantizes q to ride the int8 MXU) — slightly MORE
    accurate, same K/V math."""
    b, h, d = q.shape
    hkv = k["q"].shape[1]
    t = k["q"].shape[2]
    group = h // hkv
    block_k = _fit_block(block_k, t)
    assert t % block_k == 0, "caller gates divisibility"
    qg = q.reshape(b, hkv, group, d)

    kernel = functools.partial(
        _decode_int8_kernel,
        block_k=block_k,
        scale=1.0 / (d**0.5),
        softcap=config.attn_logit_softcap,
    )

    def kv_index(b, j, lens):
        # clamp past-length blocks to the row's last valid block: Pallas
        # re-references the same block and elides the HBM→VMEM DMA
        last = jnp.maximum(pl.cdiv(lens[b], block_k) - 1, 0)
        return (b, 0, jnp.minimum(j, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, t // block_k),
        in_specs=[
            pl.BlockSpec((1, hkv, group, d), lambda b, j, lens: (b, 0, 0, 0)),
            pl.BlockSpec((1, hkv, block_k, d), kv_index),
            # trailing singleton: Mosaic needs the block's last two dims
            # (8,128)-divisible or equal to the array's — [.., block_k, 1]
            pl.BlockSpec((1, hkv, block_k, 1), kv_index),
            pl.BlockSpec((1, hkv, block_k, d), kv_index),
            pl.BlockSpec((1, hkv, block_k, 1), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, group, d), lambda b, j, lens: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv, group, 128), jnp.float32),
            pltpu.VMEM((hkv, group, 128), jnp.float32),
            pltpu.VMEM((hkv, group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        qg,
        k["q"],
        k["s"][..., None],
        v["q"],
        v["s"][..., None],
    )
    return out.reshape(b, h * d)


# ---------------------------------------------------------------------------
# Ragged PAGED decode: one query per row against a page-table-indexed KV
# pool [P, Hkv, page_size, D] (arxiv 2502.10490 "Ragged Paged Attention" —
# the paper's block layout: per-slot sequence lengths index pages through a
# table, the last page clamps, (8,128) tiling on the (page_size, D) trailing
# dims, model-dtype/int8 MXU dots with f32 accumulation). The grid is
# (B, table_len) with every kv head inside the block — the same fat-block
# shape that made the dense int8 ragged kernel competitive (r5: a per-head
# grid had 8× the steps and lost) — and the index map DMAs exactly the
# slot's mapped pages: block j loads physical page table[b, j], clamped to
# the last valid page past the row's length so Pallas elides the HBM→VMEM
# copy. HBM traffic therefore scales with CONTENT (sum of lengths), and no
# kv_bound ladder is needed: the table IS the bound, one compiled program
# for every sequence-length mix. The masked-jnp fallback (gather through
# the table, then the stock attention math) lives in
# models/transformer._paged_gather_entry and carries tier-1 exactness.
# ---------------------------------------------------------------------------


def _paged_decode_kernel(
    lengths_ref,  # scalar-prefetch [B]
    table_ref,  # scalar-prefetch [B * Tp] flattened page table
    q_ref,  # [1, Hkv, G, D]
    k_ref,  # [1, Hkv, ps, D] — ONE physical page, all kv heads
    v_ref,  # [1, Hkv, ps, D]
    o_ref,  # [1, Hkv, G, D]
    m_scr,  # [Hkv, G, 128] f32
    l_scr,  # [Hkv, G, 128] f32
    acc_scr,  # [Hkv, G, D] f32
    *,
    page_size: int,
    scale: float,
    softcap,
):
    b = pl.program_id(0)
    j = pl.program_id(1)  # logical page index
    nk = pl.num_programs(1)
    length = lengths_ref[b]
    k_start = j * page_size

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(k_start < length)
    def _body():
        q = q_ref[0].astype(jnp.float32)  # [Hkv, G, D]
        k = k_ref[0].astype(jnp.float32)  # [Hkv, ps, D]
        v = v_ref[0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Hkv, G, ps]
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, 1, page_size), 2)
        s = jnp.where(k_pos < length, s, _NEG)

        m_prev = m_scr[:, :, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, :, None])
        p = jnp.where(s <= _NEG, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :, 0] = l_scr[:, :, 0] * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [Hkv, G, D]
        acc_scr[...] = acc_scr[...] * corr[:, :, None] + pv
        m_scr[:, :, 0] = m_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :, 0], 1e-30)[:, :, None]
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


def _paged_kv_index(num_pages: int, page_size: int, table_len: int):
    """Index map factory for page-pool blocks: grid step (b, j) loads the
    physical page ``table[b, j]``, with j clamped to the row's last valid
    logical page (re-referencing the same block elides the DMA — the ragged
    bandwidth saving) and the physical index clamped in-range so an
    unmapped sentinel entry (possible only on masked-out pages) reads SOME
    page instead of faulting."""

    def kv_index(b, j, lens, table):
        last = jnp.maximum(pl.cdiv(lens[b], page_size) - 1, 0)
        page = table[b * table_len + jnp.minimum(j, last)]
        return (jnp.clip(page, 0, num_pages - 1), 0, 0, 0)

    return kv_index


def ragged_paged_decode_attention(
    q: jax.Array,  # [B, H, D] single query per row
    k: jax.Array,  # page pool entry [P, Hkv, ps, D]
    v: jax.Array,
    lengths: jax.Array,  # [B] valid logical columns per row
    table: jax.Array,  # [B, Tp] physical page per logical page
    config: ModelConfig,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    """GQA paged decode attention → [B, H*D]."""
    b, h, d = q.shape
    num_pages, hkv = k.shape[0], k.shape[1]
    tp = table.shape[1]
    group = h // hkv
    qg = q.reshape(b, hkv, group, d)

    kernel = functools.partial(
        _paged_decode_kernel,
        page_size=page_size,
        scale=1.0 / (d**0.5),
        softcap=config.attn_logit_softcap,
    )
    kv_index = _paged_kv_index(num_pages, page_size, tp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, tp),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, group, d), lambda b, j, lens, table: (b, 0, 0, 0)
            ),
            pl.BlockSpec((1, hkv, page_size, d), kv_index),
            pl.BlockSpec((1, hkv, page_size, d), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, group, d), lambda b, j, lens, table: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv, group, 128), jnp.float32),
            pltpu.VMEM((hkv, group, 128), jnp.float32),
            pltpu.VMEM((hkv, group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        table.astype(jnp.int32).reshape(-1),
        qg,
        k,
        v,
    )
    return out.reshape(b, h * d)


def _paged_decode_int8_kernel(
    lengths_ref,  # scalar-prefetch [B]
    table_ref,  # scalar-prefetch [B * Tp]
    q_ref,  # [1, Hkv, G, D]
    kq_ref,  # [1, Hkv, ps, D] int8 — one physical page
    ks_ref,  # [1, Hkv, ps, 1] f32 per-token scales
    vq_ref,  # [1, Hkv, ps, D] int8
    vs_ref,  # [1, Hkv, ps, 1] f32
    o_ref,  # [1, Hkv, G, D]
    m_scr,
    l_scr,
    acc_scr,
    *,
    page_size: int,
    scale: float,
    softcap,
):
    """_paged_decode_kernel over the int8 pool: pages read raw int8 from
    HBM (+f32 scales), dequantized in VMEM — the same wire format as the
    dense int8 ragged kernel, per page instead of per cache block."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    nk = pl.num_programs(1)
    length = lengths_ref[b]
    k_start = j * page_size

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(k_start < length)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = kq_ref[0].astype(jnp.float32) * ks_ref[0]  # [Hkv, ps, D]
        v = vq_ref[0].astype(jnp.float32) * vs_ref[0]
        s = (
            jax.lax.dot_general(
                q,
                k,
                dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if softcap is not None:
            s = jnp.tanh(s / softcap) * softcap
        k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (1, 1, page_size), 2)
        s = jnp.where(k_pos < length, s, _NEG)

        m_prev = m_scr[:, :, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, :, None])
        p = jnp.where(s <= _NEG, 0.0, p)
        corr = jnp.exp(m_prev - m_new)
        l_scr[:, :, 0] = l_scr[:, :, 0] * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p,
            v,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_scr[...] = acc_scr[...] * corr[:, :, None] + pv
        m_scr[:, :, 0] = m_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:, :, 0], 1e-30)[:, :, None]
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


def ragged_paged_decode_attention_int8(
    q: jax.Array,  # [B, H, D]
    k: dict,  # int8 pool entry {"q": [P,Hkv,ps,D] i8, "s": [P,Hkv,ps] f32}
    v: dict,
    lengths: jax.Array,  # [B]
    table: jax.Array,  # [B, Tp]
    config: ModelConfig,
    page_size: int,
    interpret: bool = False,
) -> jax.Array:
    """GQA paged decode attention over the int8 page pool → [B, H*D]."""
    b, h, d = q.shape
    num_pages, hkv = k["q"].shape[0], k["q"].shape[1]
    tp = table.shape[1]
    group = h // hkv
    qg = q.reshape(b, hkv, group, d)

    kernel = functools.partial(
        _paged_decode_int8_kernel,
        page_size=page_size,
        scale=1.0 / (d**0.5),
        softcap=config.attn_logit_softcap,
    )
    kv_index = _paged_kv_index(num_pages, page_size, tp)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, tp),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, group, d), lambda b, j, lens, table: (b, 0, 0, 0)
            ),
            pl.BlockSpec((1, hkv, page_size, d), kv_index),
            # trailing singleton: Mosaic wants the block's last two dims
            # (8,128)-divisible or equal to the array's — [.., ps, 1]
            pl.BlockSpec((1, hkv, page_size, 1), kv_index),
            pl.BlockSpec((1, hkv, page_size, d), kv_index),
            pl.BlockSpec((1, hkv, page_size, 1), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, group, d), lambda b, j, lens, table: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv, group, 128), jnp.float32),
            pltpu.VMEM((hkv, group, 128), jnp.float32),
            pltpu.VMEM((hkv, group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        interpret=interpret,
    )(
        lengths.astype(jnp.int32),
        table.astype(jnp.int32).reshape(-1),
        qg,
        k["q"],
        k["s"][..., None],
        v["q"],
        v["s"][..., None],
    )
    return out.reshape(b, h * d)


def paged_pallas_ok(config: ModelConfig, page_size: int) -> bool:
    """True when the ragged-paged decode kernel should carry the paged
    decode read. ``attention_impl="pallas"`` forces it (interpret mode
    off-TPU, for exactness tests); ``"auto"`` requires a real TPU plus the
    Mosaic tiling constraints on the (page_size, D) block dims — off-TPU
    the gathered masked-jnp view is both exact and faster. ``"jnp"``
    disables it outright (the tier-1 reference path)."""
    if config.attention_impl == "jnp":
        return False
    if config.ring_axis is not None:
        return False
    if config.attention_impl == "pallas":
        return page_size % 8 == 0
    return (
        jax.default_backend() == "tpu"
        and config.resolved_head_dim % 128 == 0
        and page_size % 16 == 0
    )


# ---------------------------------------------------------------------------
# Fused prefill+decode batch: one attention call whose rows mix S-token
# prompt SEGMENTS (chunked prefill at a global offset) with single-token
# decode queries against the same big KV cache (arxiv 2604.15464's ragged
# mixed batch, expressed as a dispatch over the two existing paths rather
# than a third kernel: prefill rows ride the segment kernel, decode rows
# the kv_bound-sliced dense read that beat both ragged decode kernels in
# r5). This is the attention-layer BUILDING BLOCK for a true single-program
# fused iteration; the shipped engine runs two back-to-back dispatches
# instead (PERF.md round 6 records the decision), so nothing calls this in
# production yet — it is exactness-tested and kept for the revisit.
# ---------------------------------------------------------------------------


def fused_segment_decode_attention(
    q_seg: jax.Array,  # [P, S, H, D] segment queries (prefill rows)
    seg_offsets: jax.Array,  # [P] int32 global position of each segment start
    q_dec: jax.Array,  # [Bd, H, D] one query per decode row
    k,  # [B, Hkv, T, D] shared head-major cache (array or int8 {"q","s"})
    v,
    seg_rows: jax.Array,  # [P] int32 cache row of each prefill row
    dec_rows: jax.Array,  # [Bd] int32 cache row of each decode row
    dec_lengths: jax.Array,  # [Bd] int32 valid cache prefix per decode row
    config: ModelConfig,
    kv_bound: int | None = None,  # static cap on decode rows' readable columns
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Mixed prefill-segment + decode attention over ONE cache
    → ([P, S, H*D] segment out, [Bd, H*D] decode out).

    The segment rows' own K/V must already be scattered into the cache at
    [offset, offset+S) (same contract as flash_segment_attention); decode
    rows attend to their first ``dec_lengths`` columns. Exactness: each half
    is bit-identical to its standalone path — this function only routes, it
    never re-derives math — so a fused iteration built on it matches the
    serialized prefill-then-decode reference token for token."""
    from langstream_tpu.models.transformer import attention as jnp_attention

    quantized = isinstance(k, dict)
    t = (k["q"] if quantized else k).shape[2]

    # prefill rows → the segment path (Pallas kernel when shapes fit)
    k_seg = jax.tree.map(lambda x: x[seg_rows], k)
    v_seg = jax.tree.map(lambda x: x[seg_rows], v)
    p, s = q_seg.shape[0], q_seg.shape[1]
    if pallas_ok(config, s, t):
        if quantized:
            seg_out = flash_segment_attention_int8(
                q_seg, k_seg, v_seg, seg_offsets, config, interpret=interpret
            )
        else:
            seg_out = flash_segment_attention(
                q_seg, k_seg, v_seg, seg_offsets, config, interpret=interpret
            )
    else:
        positions = seg_offsets[:, None] + jnp.arange(s)[None, :]  # [P, S]
        kv_pos = jnp.arange(t)[None, None, :]
        seg_mask = kv_pos <= positions[:, :, None]
        seg_out = jnp_attention(q_seg, k_seg, v_seg, seg_mask, config)

    # decode rows → the dense masked read over the kv_bound-sliced cache
    # (r5 measured this beating both ragged kernels at decode shapes)
    k_dec = jax.tree.map(lambda x: x[dec_rows], k)
    v_dec = jax.tree.map(lambda x: x[dec_rows], v)
    t_dec = t
    if kv_bound is not None and kv_bound < t:
        k_dec = jax.tree.map(lambda x: x[:, :, :kv_bound], k_dec)
        v_dec = jax.tree.map(lambda x: x[:, :, :kv_bound], v_dec)
        t_dec = kv_bound
    dec_mask = (
        jnp.arange(t_dec)[None, None, :] < dec_lengths[:, None, None]
    )  # [Bd, 1, T]
    dec_out = jnp_attention(q_dec[:, None], k_dec, v_dec, dec_mask, config)
    return seg_out, dec_out[:, 0]


# ---------------------------------------------------------------------------
# Multi-token verify: K+1 speculative-draft queries per row against the big
# cache (self-speculative decoding, engine._verify_chunk). Decode-shaped
# work, not prefill-shaped: S is tiny (k+1 ≤ ~9) and never 128-aligned, so
# the segment kernels' tiling can't apply — and r5 measured the dense masked
# read over the kv_bound-sliced cache beating the ragged kernels at exactly
# these shapes. One routing function for both cache dtypes keeps the verify
# path on the SAME jnp attention math as single-token decode, which is what
# makes greedy speculation token-exact with non-speculative greedy.
# ---------------------------------------------------------------------------


def multitoken_verify_attention(
    q: jax.Array,  # [B, S, H, D] — current token + S-1 draft queries per row
    k,  # [B, Hkv, T, D] cache (head-major array, or int8 {"q","s"} entry)
    v,
    mask: jax.Array,  # [B, S, T] bool — per-slot causal, built by the caller
    config: ModelConfig,
) -> jax.Array:
    """Per-slot causal attention of a draft chunk against the cache
    → [B, S, H*D]. Query j of row b attends columns ≤ position[b] + j (the
    prefix written by earlier steps plus the drafts' own lower triangle —
    their K/V must already be scattered at the query positions, the
    prefill_segment contract). The mask comes from verify_step_inplace,
    which owns the ONLY definition of the verify causal frontier — columns
    past a row's frontier may hold stale rejected-draft K/V from a
    previous verify, and the mask is what makes that harmless.

    Deliberately a named entry point here rather than an inlined call in
    transformer._dispatch_attention: this is the seam a Pallas multi-token
    verify kernel would replace if a chip measurement ever justified one
    (r5's data says it won't at small S — the dense path won)."""
    from langstream_tpu.models.transformer import attention as jnp_attention

    return jnp_attention(q, k, v, mask, config)


# ---------------------------------------------------------------------------
# Dispatch gate
# ---------------------------------------------------------------------------


def pallas_ok(config: ModelConfig, seq_len: int, cache_len: int | None = None) -> bool:
    """True when the pallas kernels apply; no ring axis (ring attention owns
    the sequence-parallel path).

    ``attention_impl="pallas"`` forces the kernels (interpret mode off-TPU,
    for tests) gated only on block divisibility; ``"auto"`` additionally
    requires a real TPU backend and lane-aligned (128) head dim / lengths —
    the engine's prefill buckets and cache widths guarantee those in
    production."""
    if config.attention_impl == "jnp":
        return False
    if config.ring_axis is not None:
        return False
    force = config.attention_impl == "pallas"
    if force:
        ok_seq = seq_len == 1 or seq_len % min(128, seq_len) == 0
        ok_cache = cache_len is None or cache_len % min(128, cache_len) == 0
        return ok_seq and ok_cache
    if jax.default_backend() != "tpu":
        return False
    if config.resolved_head_dim % 128 != 0:
        return False
    if seq_len > 1 and seq_len % 128 != 0:
        return False
    if cache_len is not None and cache_len % 128 != 0:
        return False
    return True
