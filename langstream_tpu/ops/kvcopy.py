"""Device-side KV row copies for the prefix cache pool.

Both helpers move whole cache rows between the serving big cache
([L, B, Hkv, T, D] — bf16 or the int8 {"q","s"} dict, see
models.transformer.make_kv_cache) and the prefix pool, which uses the SAME
layout with B = pool entries and T = the largest prefill bucket. Row indices
are traced scalars, so each helper is ONE compiled program regardless of
which slot/entry moves (a per-index compile would multiply the program count
by max_batch × pool entries — the exact mid-traffic-compile hazard the
engine's compiled_programs guarantee exists to prevent).

Width handling: the pool is (usually) narrower than the decode cache and
(sometimes) narrower than a long-prefill local cache, so both directions
copy ``min(src_T, dst_T)`` columns — a STATIC slice. Columns past a cached
prefix's true length carry garbage by design: the serving mask invariant
("columns beyond the written frontier are masked until overwritten") makes
masking the copy pure waste.
"""

from __future__ import annotations

import functools

import jax
from jax import lax

from langstream_tpu.models.transformer import make_kv_cache


@functools.partial(jax.jit, donate_argnames=("pool",))
def publish_prefix_rows(pool, cache, slot, entry_row):
    """Copy big-cache row ``slot`` (its first pool-width columns) into pool
    row ``entry_row``. One gather + one scatter per leaf; ``entry_row``
    values out of bounds drop the write (warmup dispatches one such call so
    the first real publish is never a compile)."""

    def put(p, c):
        w = min(p.shape[3], c.shape[3])
        # axis 1 is the row axis, axis 3 is T for both the rank-5 value
        # arrays and the int8 cache's rank-4 scale arrays; after the row
        # gather T shifts to axis 2
        row = lax.dynamic_index_in_dim(c, slot, 1, keepdims=False)[:, :, :w]
        return p.at[:, entry_row, :, :w].set(row.astype(p.dtype), mode="drop")

    return jax.tree.map(put, pool, cache)


@functools.partial(jax.jit, static_argnames=("config", "width"))
def gather_prefix_local(pool, entry_row, config, width):
    """Materialize a batch-1 local cache of ``width`` columns whose first
    ``min(width, pool_T)`` columns are pool row ``entry_row`` — the seed a
    warm admission's suffix prefill segment then extends in place. The
    zeros base is traced (free); the gather is the only data movement."""
    local = make_kv_cache(config, 1, width)

    def put(loc, p):
        w = min(p.shape[3], loc.shape[3])
        row = lax.dynamic_index_in_dim(p, entry_row, 1, keepdims=False)[:, :, :w]
        return loc.at[:, 0, :, :w].set(row.astype(loc.dtype))

    return jax.tree.map(put, local, pool)
