"""Pallas TPU kernels for the hot ops (flash prefill attention, ragged
decode attention). The transformer dispatches here when shapes fit the TPU
tiling constraints; the jnp reference path remains the fallback everywhere
else (CPU tests run the kernels in interpret mode)."""
