"""Leader-broadcast SPMD dispatch for multi-host serving.

One logical serving replica spans N processes (``resources.tpu.hosts``);
every process must execute the SAME jitted programs in the same order for
the mesh collectives to line up, but only the leader (process 0) owns the
broker consumer and the request queue. The leader therefore broadcasts,
before every device dispatch, a fixed-shape CONTROL BLOCK describing the
call (op + host-side inputs); followers sit in a replay loop executing the
identical `_dev_*` engine methods with the received inputs
(`serving/engine.py` call sites). Design sketched in round 2
(`parallel/multihost.py` caveat), now implemented.

The transport is ``jax.experimental.multihost_utils.broadcast_one_to_all``
— a psum over the global device mesh, so every announcement is itself a
lockstep point: followers park inside the collective until the leader's
next dispatch arrives. All announcements are made from the leader's engine
thread, preserving a single total order.

Fixed shapes: collectives require every process to present identical
shapes, so the block is padded to (prefill_batch, max bucket width) and
sliced host-side after receipt.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

OP_IDLE = 0
OP_PREFILL = 1
OP_LONG_SEG = 2
OP_DECODE = 3
OP_STOP = 4
OP_RING = 5  # ring long-prefill: padded prompt streamed in token chunks

# head vector layout (int32[12])
_H_OP = 0
_H_WIDTH = 1
_H_STEPS = 2
_H_NROWS = 3
_H_S0 = 4
_H_SEG_LEN = 5
_H_KV_BOUND = 6
_H_LONG_START = 7
_H_LONG_FINAL = 8
_H_LONG_IDX = 9
_H_PROMPT_LEN = 10
_H_T_LONG = 11
_HEAD_LEN = 12


@dataclass
class ControlBlock:
    """One decoded announcement."""

    op: int
    width: int = 0
    steps: int = 0
    n_rows: int = 0
    s0: int = 0
    seg_len: int = 0
    kv_bound: int = 0
    long_start: bool = False
    long_final: bool = False
    long_idx: int = 0
    prompt_len: int = 0
    t_long: int = 0
    tokens: Optional[np.ndarray] = None  # [n_rows, width] int32
    lengths: Optional[np.ndarray] = None  # [n_rows]
    slots: Optional[np.ndarray] = None  # [n_rows] (or stale idxs for DECODE)
    temps: Optional[np.ndarray] = None
    top_ks: Optional[np.ndarray] = None
    top_ps: Optional[np.ndarray] = None


class SpmdChannel:
    """Fixed-shape broadcast channel between the replica's processes."""

    def __init__(self, prefill_batch: int, max_width: int, max_batch: int) -> None:
        self.prefill_batch = int(prefill_batch)
        self.max_width = int(max_width)
        self.max_batch = int(max_batch)
        # slots/stale padded to max(prefill rows, batch) so DECODE's stale
        # list and PREFILL's slot list share one field
        self.n_pad = max(self.prefill_batch, self.max_batch)

    # -- packing -------------------------------------------------------------

    def _zeros(self) -> tuple:
        return (
            np.zeros(_HEAD_LEN, np.int32),
            np.zeros((self.prefill_batch, self.max_width), np.int32),
            np.zeros(self.n_pad, np.int32),  # lengths
            np.zeros(self.n_pad, np.int32),  # slots / stale
            np.zeros(self.n_pad, np.float32),  # temps
            np.zeros(self.n_pad, np.int32),  # top_ks
            np.ones(self.n_pad, np.float32),  # top_ps
        )

    def _pack(self, block: ControlBlock) -> tuple:
        head, tokens, lengths, slots, temps, top_ks, top_ps = self._zeros()
        head[_H_OP] = block.op
        head[_H_WIDTH] = block.width
        head[_H_STEPS] = block.steps
        head[_H_NROWS] = block.n_rows
        head[_H_S0] = block.s0
        head[_H_SEG_LEN] = block.seg_len
        head[_H_KV_BOUND] = block.kv_bound
        head[_H_LONG_START] = int(block.long_start)
        head[_H_LONG_FINAL] = int(block.long_final)
        head[_H_LONG_IDX] = block.long_idx
        head[_H_PROMPT_LEN] = block.prompt_len
        head[_H_T_LONG] = block.t_long

        def fill(dst: np.ndarray, src: Optional[np.ndarray]) -> None:
            if src is not None and len(src):
                dst[: len(src)] = src

        if block.tokens is not None:
            n, w = block.tokens.shape
            tokens[:n, :w] = block.tokens
        fill(lengths, block.lengths)
        fill(slots, block.slots)
        fill(temps, block.temps)
        fill(top_ks, block.top_ks)
        fill(top_ps, block.top_ps)
        return head, tokens, lengths, slots, temps, top_ks, top_ps

    def _unpack(self, packed: tuple) -> ControlBlock:
        head, tokens, lengths, slots, temps, top_ks, top_ps = (
            np.asarray(x) for x in packed
        )
        n = int(head[_H_NROWS])
        w = int(head[_H_WIDTH])
        return ControlBlock(
            op=int(head[_H_OP]),
            width=w,
            steps=int(head[_H_STEPS]),
            n_rows=n,
            s0=int(head[_H_S0]),
            seg_len=int(head[_H_SEG_LEN]),
            kv_bound=int(head[_H_KV_BOUND]),
            long_start=bool(head[_H_LONG_START]),
            long_final=bool(head[_H_LONG_FINAL]),
            long_idx=int(head[_H_LONG_IDX]),
            prompt_len=int(head[_H_PROMPT_LEN]),
            t_long=int(head[_H_T_LONG]),
            tokens=tokens[:n, :w] if w else tokens[:n],
            lengths=lengths[:n],
            slots=slots[:n],
            temps=temps[:n],
            top_ks=top_ks[:n],
            top_ps=top_ps[:n],
        )

    # -- transport -----------------------------------------------------------

    def _broadcast(self, payload: tuple) -> tuple:
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(payload)

    @staticmethod
    def _needs_payload(op: int) -> bool:
        # DECODE/STOP/IDLE carry everything in the head + slots vector; only
        # prefill ops ship the (prefill_batch x max_width) token buffer —
        # two-phase keeps the per-decode-chunk hot path to two small arrays
        return op in (OP_PREFILL, OP_LONG_SEG, OP_RING)

    def announce(self, block: ControlBlock) -> None:
        """Leader: publish the next device dispatch (engine thread only —
        announcements must form one total order)."""
        head, tokens, lengths, slots, temps, top_ks, top_ps = self._pack(block)
        self._broadcast((head, slots))
        if self._needs_payload(block.op):
            self._broadcast((tokens, lengths, temps, top_ks, top_ps))

    def recv(self) -> ControlBlock:
        """Follower: block until the leader's next dispatch."""
        zeros = self._zeros()
        head, slots = self._broadcast((zeros[0], zeros[3]))
        tokens, lengths, temps, top_ks, top_ps = (
            zeros[1], zeros[2], zeros[4], zeros[5], zeros[6]
        )
        if self._needs_payload(int(np.asarray(head)[_H_OP])):
            tokens, lengths, temps, top_ks, top_ps = self._broadcast(
                (tokens, lengths, temps, top_ks, top_ps)
            )
        return self._unpack((head, tokens, lengths, slots, temps, top_ks, top_ps))


class LoopbackChannel(SpmdChannel):
    """In-process channel for tests and the multichip dryrun: announce
    enqueues the packed block, recv dequeues it. Exercises the exact
    pack/unpack/fixed-shape discipline of the real broadcast path, with a
    leader engine and a follower engine sharing one process (and one
    device mesh) — the state-lockstep property is identical."""

    def __init__(self, prefill_batch: int, max_width: int, max_batch: int) -> None:
        super().__init__(prefill_batch, max_width, max_batch)
        import queue as _queue

        self._q: Any = _queue.Queue()

    def announce(self, block: ControlBlock) -> None:
        self._q.put(self._pack(block))

    def recv(self) -> ControlBlock:
        return self._unpack(self._q.get())


def follower_loop(engine: Any, channel: SpmdChannel) -> None:
    """Replay the leader's dispatches on a follower process. ``engine`` is
    a ServingEngine constructed with the SAME config/params/mesh/seed but
    never start()ed — only its device-touching ``_dev_*`` methods run, so
    its sharded state evolves in lockstep with the leader's.

    A dispatch failure here is fatal by design: the leader and follower
    states may have diverged, so the exception propagates, the process
    exits, and the replica's pods restart together (crash-only)."""
    import logging

    log = logging.getLogger(__name__)
    while True:
        block = channel.recv()
        if block.op == OP_STOP:
            return
        if block.op == OP_IDLE:
            continue
        try:
            _replay(engine, block)
        except Exception:
            log.exception("SPMD replay failed (op=%d); crashing replica", block.op)
            raise


def _replay(engine: Any, block: ControlBlock) -> None:
    if block.op == OP_PREFILL:
        engine._dev_prefill(
            block.width,
            block.tokens,
            block.lengths,
            block.temps,
            block.top_ks,
            block.top_ps,
            block.slots,
        )
    elif block.op == OP_LONG_SEG:
        engine._dev_long_segment(
            block.tokens,
            block.s0,
            block.seg_len,
            block.kv_bound,
            block.t_long,
            float(block.temps[0]),
            int(block.top_ks[0]),
            float(block.top_ps[0]),
            start=block.long_start,
            final=block.long_final,
            idx=block.long_idx,
            prompt_len=block.prompt_len,
        )
    elif block.op == OP_RING:
        # the padded prompt streams in (prefill_batch*max_width)-token
        # chunks; the final chunk triggers the one-dispatch ring admit,
        # evolving the follower's sharded state in lockstep with the leader
        if block.long_start:
            engine._spmd_ring_buf = []
        engine._spmd_ring_buf.append(
            np.asarray(block.tokens, np.int32).reshape(-1)[: block.seg_len]
        )
        if block.long_final:
            prompt = np.concatenate(engine._spmd_ring_buf)
            engine._spmd_ring_buf = []
            # reconstruct the leader's pow2 padding locally (deterministic
            # from the shared mesh/max_seq_len config) — only the prompt
            # itself rides the channel
            s_pad = engine._ring_pad(block.prompt_len)
            tokens = np.zeros((1, s_pad), np.int32)
            tokens[0, : len(prompt)] = prompt
            engine._dev_ring(
                tokens,
                block.prompt_len,
                float(block.temps[0]),
                int(block.top_ks[0]),
                float(block.top_ps[0]),
                block.long_idx,
            )
    elif block.op == OP_DECODE:
        # kv_bound=0 replays pre-bound announcements as unbounded
        engine._dev_decode(block.steps, block.slots, block.kv_bound or None)
