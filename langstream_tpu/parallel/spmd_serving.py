"""Leader-broadcast SPMD dispatch for multi-host serving.

One logical serving replica spans N processes (``resources.tpu.hosts``);
every process must execute the SAME jitted programs in the same order for
the mesh collectives to line up, but only the leader (process 0) owns the
broker consumer and the request queue. The leader therefore broadcasts,
before every device dispatch, a fixed-shape CONTROL BLOCK describing the
call (op + host-side inputs); followers sit in a replay loop executing the
identical `_dev_*` engine methods with the received inputs
(`serving/engine.py` call sites). Design sketched in round 2
(`parallel/multihost.py` caveat), implemented in round 3.

Protocol v2 (round 13 — docs/SERVING.md §14): every host-side decision the
FAST paths make now rides the wire, so prefix reuse, self-speculative
decoding and ``kv_layout="paged"`` run under SPMD instead of being
construction-disabled:

- ``OP_VERIFY`` ships the leader's n-gram drafts (the index itself is
  deterministic given the replayed token stream, so only the drafts need
  the wire — acceptance is computed ON DEVICE identically on every host).
- ``OP_PREFIX_ADMIT`` / ``OP_PREFIX_PUBLISH`` replay the dense prefix
  cache's gather+suffix-segment admissions and copy-on-publish rows (the
  pool ROW index rides the wire; the radix trie stays leader-only).
- ``OP_PAGE_BIND`` / ``OP_PAGE_FREE`` / ``OP_PAGE_ZERO`` replay the paged
  allocator's observable RESULTS — the page lists bound to a slot
  (aliased prefix pages included, plus the one copy-on-write pair), table
  clears, and quarantine page-zero dispatches. Followers keep only the
  per-slot TABLES (what device dispatches read); the free list, refcounts
  and the prefix page index remain leader-only state.
- Every ``OP_DECODE``/``OP_VERIFY`` block carries an explicit ACTIVE-slot
  mask: the leader's slot liveness (a host-side property followers cannot
  observe — completions are discovered at fetch time) masks non-active
  page-table rows to the out-of-bounds sentinel on every host.
- ``OP_ROW_RESET`` replays the dense NaN-quarantine row zero, so an SPMD
  replica quarantines a poisoned slot victim-only (round-8 semantics)
  instead of crashing the whole replica.
- ``OP_WARMUP`` replays a whole precompile family (decode ladder, verify
  ladder, paged surface, prefill buckets, prefix programs) as ONE
  announcement — both sides run the identical deterministic dispatch
  sequence from shared config, so the warmups stay off the hot wire.

Every announcement carries a monotonically increasing ``seq``; followers
verify contiguity. With ``echo`` enabled on the channel the leader also
re-broadcasts each decode/verify chunk's FETCHED tokens (``OP_ECHO``)
and the follower compares them against its own device results — a
mismatch emits a flight-recorder dump tagged with the ControlBlock seq
(reason ``spmd-divergence``) and crashes the replica. Divergence is never
silently survived.

The transport is ``jax.experimental.multihost_utils.broadcast_one_to_all``
— a psum over the global device mesh, so every announcement is itself a
lockstep point: followers park inside the collective until the leader's
next dispatch arrives. All announcements are made from the leader's engine
thread, preserving a single total order.

Fixed shapes: collectives require every process to present identical
shapes, so the block is padded to (prefill_batch, max bucket width) and
sliced host-side after receipt. The page/draft/echo payloads get their own
fixed-shape buffers (sized from ``table_len`` / ``spec_tokens`` at
construction — identical on every process because the engine config is).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

OP_IDLE = 0
OP_PREFILL = 1
OP_LONG_SEG = 2
OP_DECODE = 3
OP_STOP = 4
OP_RING = 5  # ring long-prefill: padded prompt streamed in token chunks
OP_VERIFY = 6  # speculative verify dispatch (drafts payload)
OP_PREFIX_ADMIT = 7  # dense warm admission: gather + suffix segment
OP_PREFIX_PUBLISH = 8  # dense copy-on-publish into a pool row
OP_PAGE_BIND = 9  # paged reservation result: slot's page list (+ COW pair)
OP_PAGE_FREE = 10  # slot's table clears (completion / quarantine / abort)
OP_PAGE_ZERO = 11  # quarantine page-zero dispatch
OP_ROW_RESET = 12  # dense NaN-quarantine row zero dispatch
OP_ECHO = 13  # leader's fetched chunk result (divergence check, optional)
OP_WARMUP = 14  # replay a whole precompile family (count = WARMUP_* kind)

# OP_WARMUP kinds (ControlBlock.count)
WARMUP_DECODE_LADDER = 0
WARMUP_VERIFY_LADDER = 1
WARMUP_PAGED = 2
WARMUP_PREFILL_BUCKETS = 3
WARMUP_PREFIX_PROGRAMS = 4

# OP_ECHO kinds (ControlBlock.long_idx)
ECHO_DECODE = 0
ECHO_VERIFY = 1

# head vector layout (int32[_HEAD_LEN])
_H_OP = 0
_H_WIDTH = 1
_H_STEPS = 2
_H_NROWS = 3
_H_S0 = 4
_H_SEG_LEN = 5
_H_KV_BOUND = 6
_H_LONG_START = 7
_H_LONG_FINAL = 8
_H_LONG_IDX = 9
_H_PROMPT_LEN = 10
_H_T_LONG = 11
_H_ENTRY_ROW = 12  # prefix pool row (dense admit/publish, long warm start); -1 = none
_H_COW_SRC = 13  # copy-on-write source page (paged bind); -1 = none
_H_COW_DST = 14  # copy-on-write destination page; -1 = none
_H_SEQ = 15  # announcement sequence number (follower verifies contiguity)
_H_COUNT = 16  # page count / echo element count / warmup kind
_HEAD_LEN = 17


@dataclass
class ControlBlock:
    """One decoded announcement."""

    op: int
    width: int = 0
    steps: int = 0
    n_rows: int = 0
    s0: int = 0
    seg_len: int = 0
    kv_bound: int = 0
    long_start: bool = False
    long_final: bool = False
    long_idx: int = 0
    prompt_len: int = 0
    t_long: int = 0
    entry_row: int = -1
    cow_src: int = -1
    cow_dst: int = -1
    seq: int = 0
    count: int = 0
    tokens: Optional[np.ndarray] = None  # [n_rows, width] int32
    lengths: Optional[np.ndarray] = None  # [n_rows]
    slots: Optional[np.ndarray] = None  # [n_rows] (or stale idxs for DECODE)
    temps: Optional[np.ndarray] = None
    top_ks: Optional[np.ndarray] = None
    top_ps: Optional[np.ndarray] = None
    # active-slot mask [max_batch] (decode/verify: the leader's host-side
    # slot liveness — followers mask page-table rows with it)
    mask: Optional[np.ndarray] = None
    drafts: Optional[np.ndarray] = None  # [max_batch, k] int32 (OP_VERIFY)
    pages: Optional[np.ndarray] = None  # [count] int32 (bind/zero)
    echo: Optional[np.ndarray] = None  # flat int32[count] (OP_ECHO)


class SpmdChannel:
    """Fixed-shape broadcast channel between the replica's processes.

    ``table_len`` (paged layouts), ``spec_tokens`` (speculation) and
    ``decode_chunk`` size the page/draft/echo payload buffers; all derive
    from the engine config, so every process builds the identical channel.
    ``echo=True`` adds the leader→follower result echo after every
    processed decode/verify chunk (one extra broadcast per chunk — the
    divergence-detection mode the parity suite runs under; off by default
    in production)."""

    def __init__(
        self,
        prefill_batch: int,
        max_width: int,
        max_batch: int,
        table_len: int = 0,
        spec_tokens: int = 0,
        echo: bool = False,
        decode_chunk: int = 64,
    ) -> None:
        self.prefill_batch = int(prefill_batch)
        self.max_width = int(max_width)
        self.max_batch = int(max_batch)
        self.table_len = int(table_len)
        self.spec_tokens = int(spec_tokens)
        self.echo = bool(echo)
        self.decode_chunk = int(decode_chunk)
        # slots/stale padded to max(prefill rows, batch) so DECODE's stale
        # list and PREFILL's slot list share one field
        self.n_pad = max(self.prefill_batch, self.max_batch)
        self.page_pad = max(1, self.table_len)
        self.draft_pad = max(1, self.spec_tokens)
        # echo buffer: big enough for a full decode chunk ([steps ≤
        # decode_chunk, B] — a chunk never exceeds the engine's configured
        # chunk size; the ctor default covers every chunk the engine knob
        # allows by default) and a verify result ([B, k+2]); announce()
        # asserts the fit so a mis-sized config fails loudly on the
        # leader, never as a silent truncation
        self.echo_pad = max(
            self.prefill_batch * self.max_width,
            self.max_batch * (self.draft_pad + 2),
            self.max_batch * max(1, self.decode_chunk),
        )
        # wire accounting (PERF.md round 13): bytes broadcast per announce
        # — the measured ControlBlock overhead per engine iteration
        self.announces_total = 0
        self.bytes_announced_total = 0
        self._seq = 0
        # immutable zero templates: _pack copies ONLY the arrays an op
        # actually writes (head/slots/mask + its payload kind) and passes
        # the shared read-only blanks for the rest — a head-only OP_DECODE
        # on the hot path must not allocate the (large) echo/drafts/token
        # buffers it never ships. recv() reuses the blanks as pure shape
        # templates (broadcast returns new arrays; inputs are not mutated).
        self._blank = self._zeros()
        for a in self._blank:
            a.setflags(write=False)

    # -- packing -------------------------------------------------------------

    def _zeros(self) -> tuple:
        return (
            np.zeros(_HEAD_LEN, np.int32),
            np.zeros((self.prefill_batch, self.max_width), np.int32),
            np.zeros(self.n_pad, np.int32),  # lengths
            np.zeros(self.n_pad, np.int32),  # slots / stale
            np.zeros(self.n_pad, np.float32),  # temps
            np.zeros(self.n_pad, np.int32),  # top_ks
            np.ones(self.n_pad, np.float32),  # top_ps
            np.zeros(self.max_batch, np.int32),  # active mask
            np.zeros((self.max_batch, self.draft_pad), np.int32),  # drafts
            np.full(self.page_pad, -1, np.int32),  # pages
            np.zeros(self.echo_pad, np.int32),  # echo
        )

    def _pack(self, block: ControlBlock) -> tuple:
        blank = self._blank
        kind = self._payload_kind(block.op)
        head, slots, mask = blank[0].copy(), blank[3].copy(), blank[7].copy()
        if kind == "tokens":
            tokens, lengths = blank[1].copy(), blank[2].copy()
            temps, top_ks, top_ps = (
                blank[4].copy(), blank[5].copy(), blank[6].copy()
            )
        else:
            tokens, lengths, temps, top_ks, top_ps = (
                blank[1], blank[2], blank[4], blank[5], blank[6]
            )
        drafts = blank[8].copy() if kind == "drafts" else blank[8]
        pages = blank[9].copy() if kind == "pages" else blank[9]
        echo = blank[10].copy() if kind == "echo" else blank[10]
        head[_H_OP] = block.op
        head[_H_WIDTH] = block.width
        head[_H_STEPS] = block.steps
        head[_H_NROWS] = block.n_rows
        head[_H_S0] = block.s0
        head[_H_SEG_LEN] = block.seg_len
        head[_H_KV_BOUND] = block.kv_bound
        head[_H_LONG_START] = int(block.long_start)
        head[_H_LONG_FINAL] = int(block.long_final)
        head[_H_LONG_IDX] = block.long_idx
        head[_H_PROMPT_LEN] = block.prompt_len
        head[_H_T_LONG] = block.t_long
        head[_H_ENTRY_ROW] = block.entry_row
        head[_H_COW_SRC] = block.cow_src
        head[_H_COW_DST] = block.cow_dst
        head[_H_SEQ] = block.seq
        head[_H_COUNT] = block.count

        def fill(dst: np.ndarray, src: Optional[np.ndarray]) -> None:
            if src is not None and len(src):
                dst[: len(src)] = src

        if block.tokens is not None:
            n, w = block.tokens.shape
            tokens[:n, :w] = block.tokens
        fill(lengths, block.lengths)
        fill(slots, block.slots)
        fill(temps, block.temps)
        fill(top_ks, block.top_ks)
        fill(top_ps, block.top_ps)
        fill(mask, block.mask)
        if block.drafts is not None:
            n, k = block.drafts.shape
            assert k <= self.draft_pad, (
                f"drafts k={k} exceed the channel's spec_tokens={self.draft_pad}"
            )
            drafts[:n, :k] = block.drafts
        if block.pages is not None:
            assert len(block.pages) <= self.page_pad, (
                f"{len(block.pages)} pages exceed the channel's "
                f"table_len={self.page_pad}"
            )
            pages[: len(block.pages)] = block.pages
        if block.echo is not None:
            flat = np.asarray(block.echo, np.int32).reshape(-1)
            assert len(flat) <= self.echo_pad, (
                f"echo of {len(flat)} elements exceeds the channel's "
                f"{self.echo_pad}-element buffer"
            )
            echo[: len(flat)] = flat
        return (
            head, tokens, lengths, slots, temps, top_ks, top_ps,
            mask, drafts, pages, echo,
        )

    def _unpack(self, packed: tuple) -> ControlBlock:
        (
            head, tokens, lengths, slots, temps, top_ks, top_ps,
            mask, drafts, pages, echo,
        ) = (np.asarray(x) for x in packed)
        n = int(head[_H_NROWS])
        w = int(head[_H_WIDTH])
        count = int(head[_H_COUNT])
        return ControlBlock(
            op=int(head[_H_OP]),
            width=w,
            steps=int(head[_H_STEPS]),
            n_rows=n,
            s0=int(head[_H_S0]),
            seg_len=int(head[_H_SEG_LEN]),
            kv_bound=int(head[_H_KV_BOUND]),
            long_start=bool(head[_H_LONG_START]),
            long_final=bool(head[_H_LONG_FINAL]),
            long_idx=int(head[_H_LONG_IDX]),
            prompt_len=int(head[_H_PROMPT_LEN]),
            t_long=int(head[_H_T_LONG]),
            entry_row=int(head[_H_ENTRY_ROW]),
            cow_src=int(head[_H_COW_SRC]),
            cow_dst=int(head[_H_COW_DST]),
            seq=int(head[_H_SEQ]),
            count=count,
            tokens=tokens[:n, :w] if w else tokens[:n],
            lengths=lengths[:n],
            slots=slots[:n],
            temps=temps[:n],
            top_ks=top_ks[:n],
            top_ps=top_ps[:n],
            mask=mask,
            drafts=drafts,
            pages=pages[:count],
            echo=echo[:count],
        )

    # -- transport -----------------------------------------------------------

    def _broadcast(self, payload: tuple) -> tuple:
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(payload)

    @staticmethod
    def _payload_kind(op: int) -> Optional[str]:
        """Which second-phase payload an op ships. DECODE/STOP/IDLE and the
        page/row bookkeeping ops carry everything in the head + phase-1
        vectors — two-phase keeps the per-decode-chunk hot path small."""
        if op in (OP_PREFILL, OP_LONG_SEG, OP_RING, OP_PREFIX_ADMIT):
            return "tokens"
        if op == OP_VERIFY:
            return "drafts"
        if op in (OP_PAGE_BIND, OP_PAGE_ZERO):
            return "pages"
        if op == OP_ECHO:
            return "echo"
        return None

    @classmethod
    def _phases(cls, packed: tuple, op: int) -> tuple[tuple, Optional[tuple]]:
        """Split one packed block into its broadcast phases: the phase-1
        triple every announcement ships, plus the op's payload phase (or
        None). The ONE definition both transports (broadcast + loopback)
        and both directions (announce + recv) build from, so the protocol
        cannot drift between them — the wire-byte accounting PERF.md
        presents as exact is summed off these same tuples."""
        (
            head, tokens, lengths, slots, temps, top_ks, top_ps,
            mask, drafts, pages, echo,
        ) = packed
        phase1 = (head, slots, mask)
        kind = cls._payload_kind(op)
        if kind == "tokens":
            return phase1, (tokens, lengths, temps, top_ks, top_ps)
        if kind == "drafts":
            return phase1, (drafts,)
        if kind == "pages":
            return phase1, (pages,)
        if kind == "echo":
            return phase1, (echo,)
        return phase1, None

    # seq is carried in an int32 head slot: wrap BELOW 2^31 so a replica
    # that lives through billions of announcements keeps running instead
    # of dying on a numpy OverflowError (followers wrap identically)
    SEQ_MOD = 0x7FFFFFFF

    def _next_seq(self) -> int:
        self._seq = self._seq % self.SEQ_MOD + 1
        return self._seq

    def announce(self, block: ControlBlock) -> None:
        """Leader: publish the next device dispatch (engine thread only —
        announcements must form one total order)."""
        block.seq = self._next_seq()
        phase1, payload = self._phases(self._pack(block), block.op)
        self._broadcast(phase1)
        sent = sum(a.nbytes for a in phase1)
        if payload is not None:
            self._broadcast(payload)
            sent += sum(a.nbytes for a in payload)
        self.announces_total += 1
        self.bytes_announced_total += sent

    def recv(self) -> ControlBlock:
        """Follower: block until the leader's next dispatch."""
        zeros = self._blank  # shape templates only; broadcast never mutates
        head, slots, mask = self._broadcast((zeros[0], zeros[3], zeros[7]))
        tokens, lengths, temps, top_ks, top_ps = (
            zeros[1], zeros[2], zeros[4], zeros[5], zeros[6]
        )
        drafts, pages, echo = zeros[8], zeros[9], zeros[10]
        kind = self._payload_kind(int(np.asarray(head)[_H_OP]))
        if kind == "tokens":
            tokens, lengths, temps, top_ks, top_ps = self._broadcast(
                (tokens, lengths, temps, top_ks, top_ps)
            )
        elif kind == "drafts":
            (drafts,) = self._broadcast((drafts,))
        elif kind == "pages":
            (pages,) = self._broadcast((pages,))
        elif kind == "echo":
            (echo,) = self._broadcast((echo,))
        return self._unpack((
            head, tokens, lengths, slots, temps, top_ks, top_ps,
            mask, drafts, pages, echo,
        ))


class LoopbackChannel(SpmdChannel):
    """In-process channel for tests and the multichip dryrun: announce
    enqueues the packed block, recv dequeues it. Exercises the exact
    pack/unpack/fixed-shape discipline of the real broadcast path, with a
    leader engine and a follower engine sharing one process (and one
    device mesh) — the state-lockstep property is identical."""

    def __init__(
        self,
        prefill_batch: int,
        max_width: int,
        max_batch: int,
        table_len: int = 0,
        spec_tokens: int = 0,
        echo: bool = False,
        decode_chunk: int = 64,
    ) -> None:
        super().__init__(
            prefill_batch, max_width, max_batch,
            table_len=table_len, spec_tokens=spec_tokens, echo=echo,
            decode_chunk=decode_chunk,
        )
        import queue as _queue

        self._q: Any = _queue.Queue()

    def announce(self, block: ControlBlock) -> None:
        block.seq = self._next_seq()
        packed = self._pack(block)
        # phase-1 + the op's payload phase, from the SAME splitter the
        # broadcast transport uses — loopback benches measure the real
        # per-iteration wire overhead
        phase1, payload = self._phases(packed, block.op)
        self.announces_total += 1
        self.bytes_announced_total += sum(a.nbytes for a in phase1) + (
            sum(a.nbytes for a in payload) if payload is not None else 0
        )
        self._q.put(packed)

    def recv(self) -> ControlBlock:
        return self._unpack(self._q.get())


class SpmdDivergenceError(RuntimeError):
    """Leader and follower state provably disagree (echo mismatch, sequence
    gap, or an un-replayable block). The replica must crash and restart
    together — continuing would serve garbage from half the mesh."""


def follower_loop(engine: Any, channel: SpmdChannel) -> None:
    """Replay the leader's dispatches on a follower process. ``engine`` is
    a ServingEngine constructed with the SAME config/params/mesh/seed but
    never start()ed — only its device-touching ``_dev_*`` methods (and the
    page-table bookkeeping the wire replays) run, so its sharded state
    evolves in lockstep with the leader's.

    A dispatch failure here is fatal by design: the leader and follower
    states may have diverged, so a flight-recorder dump tagged with the
    ControlBlock seq is emitted (reason ``spmd-divergence`` — SPMD
    incidents leave evidence like single-host ones, docs/SERVING.md §14),
    the exception propagates, the process exits, and the replica's pods
    restart together (crash-only)."""
    import logging
    from collections import deque

    log = logging.getLogger(__name__)
    # a follower must never fire its own faults: the leader's announced ops
    # already reflect ITS injector, and an independent follower schedule
    # would diverge the replicas by construction
    engine._injector = None
    # device results of replayed decode/verify dispatches, kept only while
    # the channel runs in echo (divergence-check) mode; OP_ECHO pops the
    # oldest — leader processes fetches in dispatch order, so FIFO order
    # matches by construction
    pending_echo: deque = deque()
    last_seq = 0
    while True:
        block = channel.recv()
        expected = last_seq % SpmdChannel.SEQ_MOD + 1  # leader's wrap rule
        if block.seq and last_seq and block.seq != expected:
            _fail_divergence(
                engine, block,
                f"announcement sequence gap: got seq {block.seq} after "
                f"{last_seq} (a block was lost or reordered)",
            )
        if block.seq:
            last_seq = block.seq
        if block.op == OP_STOP:
            return
        if block.op == OP_IDLE:
            continue
        try:
            _replay(engine, block, channel, pending_echo)
        except SpmdDivergenceError:
            raise
        except Exception:
            log.exception("SPMD replay failed (op=%d); crashing replica", block.op)
            _dump_divergence(engine, block, "replay raised")
            raise


def _dump_divergence(engine: Any, block: ControlBlock, why: str) -> None:
    """Best-effort flight-recorder dump before the replica crashes — the
    SPMD incident artifact (satellite: follower-divergence flight dump)."""
    try:
        engine._flight_dump(
            "spmd-divergence",
            extra={"seq": block.seq, "op": block.op, "why": why},
            force=True,
        )
    except Exception:  # noqa: BLE001 — the crash must proceed regardless
        import logging

        logging.getLogger(__name__).exception("divergence dump failed")


def _fail_divergence(engine: Any, block: ControlBlock, why: str) -> None:
    _dump_divergence(engine, block, why)
    raise SpmdDivergenceError(
        f"SPMD divergence at seq {block.seq} (op {block.op}): {why}"
    )


def _replay(
    engine: Any,
    block: ControlBlock,
    channel: SpmdChannel,
    pending_echo,
) -> None:
    if block.op == OP_PREFILL:
        engine._dev_prefill(
            block.width,
            block.tokens,
            block.lengths,
            block.temps,
            block.top_ks,
            block.top_ps,
            block.slots,
        )
    elif block.op == OP_LONG_SEG:
        if engine._paged:
            # paged segments (long-prompt chunks AND warm suffix segments)
            # write straight into the slot's wire-bound pages
            engine._dev_paged_segment(
                block.tokens,
                block.s0,
                block.seg_len,
                block.long_idx,
                float(block.temps[0]),
                int(block.top_ks[0]),
                float(block.top_ps[0]),
                final=block.long_final,
                prompt_len=block.prompt_len,
            )
        else:
            engine._dev_long_segment(
                block.tokens,
                block.s0,
                block.seg_len,
                block.kv_bound,
                block.t_long,
                float(block.temps[0]),
                int(block.top_ks[0]),
                float(block.top_ps[0]),
                start=block.long_start,
                final=block.long_final,
                idx=block.long_idx,
                prompt_len=block.prompt_len,
                prefix_row=block.entry_row if block.entry_row >= 0 else None,
            )
    elif block.op == OP_RING:
        # the padded prompt streams in (prefill_batch*max_width)-token
        # chunks; the final chunk triggers the one-dispatch ring admit,
        # evolving the follower's sharded state in lockstep with the leader
        if block.long_start:
            engine._spmd_ring_buf = []
        engine._spmd_ring_buf.append(
            np.asarray(block.tokens, np.int32).reshape(-1)[: block.seg_len]
        )
        if block.long_final:
            prompt = np.concatenate(engine._spmd_ring_buf)
            engine._spmd_ring_buf = []
            # reconstruct the leader's pow2 padding locally (deterministic
            # from the shared mesh/max_seq_len config) — only the prompt
            # itself rides the channel
            s_pad = engine._ring_pad(block.prompt_len)
            tokens = np.zeros((1, s_pad), np.int32)
            tokens[0, : len(prompt)] = prompt
            engine._dev_ring(
                tokens,
                block.prompt_len,
                float(block.temps[0]),
                int(block.top_ks[0]),
                float(block.top_ps[0]),
                block.long_idx,
            )
    elif block.op == OP_DECODE:
        # kv_bound=0 replays pre-bound announcements as unbounded
        chunk = engine._dev_decode(
            block.steps, block.slots, block.kv_bound or None, mask=block.mask
        )
        if channel.echo:
            pending_echo.append((ECHO_DECODE, chunk))
    elif block.op == OP_VERIFY:
        k = block.steps  # drafts per slot (engine.spec_tokens on the leader)
        packed = engine._dev_verify(
            np.asarray(block.drafts[:, :k], np.int32),
            block.slots,
            block.kv_bound,
            mask=block.mask,
        )
        if channel.echo:
            pending_echo.append((ECHO_VERIFY, packed))
    elif block.op == OP_PREFIX_ADMIT:
        engine._dev_prefix_admit(
            block.tokens,
            block.s0,
            block.seg_len,
            block.kv_bound,
            block.entry_row,
            float(block.temps[0]),
            int(block.top_ks[0]),
            float(block.top_ps[0]),
            block.long_idx,
        )
    elif block.op == OP_PREFIX_PUBLISH:
        engine._dev_prefix_publish(block.long_idx, block.entry_row)
    elif block.op == OP_PAGE_BIND:
        engine._spmd_apply_bind(
            block.long_idx,
            list(block.pages),
            block.cow_src if block.cow_src >= 0 else None,
            block.cow_dst if block.cow_dst >= 0 else None,
        )
    elif block.op == OP_PAGE_FREE:
        # the follower tracks TABLES only (never the free list/refcounts —
        # future reservations arrive as explicit BIND results)
        engine._pagepool.free_slot(block.long_idx)
    elif block.op == OP_PAGE_ZERO:
        engine._dev_page_zero(list(block.pages))
    elif block.op == OP_ROW_RESET:
        engine._dev_row_reset(list(block.slots))
    elif block.op == OP_WARMUP:
        _replay_warmup(engine, block)
    elif block.op == OP_ECHO:
        _check_echo(engine, block, pending_echo)
    else:
        _fail_divergence(engine, block, f"unknown op {block.op}")


def _replay_warmup(engine: Any, block: ControlBlock) -> None:
    """Run the announced precompile family locally — both sides execute the
    identical deterministic dispatch sequence (same config ⇒ same shapes,
    same PRNG consumption), so the warmups cost ONE announcement each."""
    kind = block.count
    if kind == WARMUP_DECODE_LADDER:
        engine._warmup_decode_ladder()
    elif kind == WARMUP_VERIFY_LADDER:
        engine._warmup_verify_ladder()
    elif kind == WARMUP_PAGED:
        engine._warmup_paged()
    elif kind == WARMUP_PREFILL_BUCKETS:
        engine._warmup_prefill_buckets()
    elif kind == WARMUP_PREFIX_PROGRAMS:
        engine._warmup_prefix_programs()
    else:
        _fail_divergence(engine, block, f"unknown warmup kind {kind}")


def _check_echo(engine: Any, block: ControlBlock, pending_echo) -> None:
    """Compare the leader's fetched chunk tokens against the follower's own
    device result for the same dispatch — the strongest per-chunk
    divergence check the protocol offers (opt-in: one device→host sync per
    chunk on the follower)."""
    import jax

    if not pending_echo:
        _fail_divergence(
            engine, block, "echo arrived with no pending replayed dispatch"
        )
    kind, dev = pending_echo.popleft()
    if kind != block.long_idx:
        _fail_divergence(
            engine, block,
            f"echo kind mismatch: leader says {block.long_idx}, follower "
            f"replayed {kind}",
        )
    full = np.asarray(jax.device_get(dev), np.int32).reshape(-1)
    if len(full) != block.count:
        # a shape drift (e.g. mismatched spec_tokens/decode_chunk config)
        # must report as the divergence it is — checked against the FULL
        # follower result, in either direction, before any truncation
        _fail_divergence(
            engine, block,
            f"echo length mismatch: leader sent {block.count} elements, "
            f"follower's replayed result has {len(full)}",
        )
    mine = full[: block.count]
    theirs = np.asarray(block.echo[: block.count], np.int32)
    if not np.array_equal(mine, theirs):
        bad = int(np.argmax(mine != theirs))
        _fail_divergence(
            engine, block,
            f"token divergence at element {bad}: leader {int(theirs[bad])} "
            f"vs follower {int(mine[bad])}",
        )
