"""Leader-broadcast SPMD dispatch for multi-host serving.

One logical serving replica spans N processes (``resources.tpu.hosts``);
every process must execute the SAME jitted programs in the same order for
the mesh collectives to line up, but only the leader (process 0) owns the
broker consumer and the request queue. The leader therefore broadcasts,
before every device dispatch, a fixed-shape CONTROL BLOCK describing the
call (op + host-side inputs); followers sit in a replay loop executing the
identical `_dev_*` engine methods with the received inputs
(`serving/engine.py` call sites). Design sketched in round 2
(`parallel/multihost.py` caveat), implemented in round 3.

Protocol v2 (round 13 — docs/SERVING.md §14): every host-side decision the
FAST paths make now rides the wire, so prefix reuse, self-speculative
decoding and ``kv_layout="paged"`` run under SPMD instead of being
construction-disabled:

- ``OP_VERIFY`` ships the leader's n-gram drafts (the index itself is
  deterministic given the replayed token stream, so only the drafts need
  the wire — acceptance is computed ON DEVICE identically on every host).
- ``OP_PREFIX_ADMIT`` / ``OP_PREFIX_PUBLISH`` replay the dense prefix
  cache's gather+suffix-segment admissions and copy-on-publish rows (the
  pool ROW index rides the wire; the radix trie stays leader-only).
- ``OP_PAGE_BIND`` / ``OP_PAGE_FREE`` / ``OP_PAGE_ZERO`` replay the paged
  allocator's observable RESULTS — the page lists bound to a slot
  (aliased prefix pages included, plus the one copy-on-write pair), table
  clears, and quarantine page-zero dispatches. Followers keep only the
  per-slot TABLES (what device dispatches read); the free list, refcounts
  and the prefix page index remain leader-only state.
- Every ``OP_DECODE``/``OP_VERIFY`` block carries an explicit ACTIVE-slot
  mask: the leader's slot liveness (a host-side property followers cannot
  observe — completions are discovered at fetch time) masks non-active
  page-table rows to the out-of-bounds sentinel on every host.
- ``OP_ROW_RESET`` replays the dense NaN-quarantine row zero, so an SPMD
  replica quarantines a poisoned slot victim-only (round-8 semantics)
  instead of crashing the whole replica.
- ``OP_WARMUP`` replays a whole precompile family (decode ladder, verify
  ladder, paged surface, prefill buckets, prefix programs) as ONE
  announcement — both sides run the identical deterministic dispatch
  sequence from shared config, so the warmups stay off the hot wire.

Every announcement carries a monotonically increasing ``seq``; followers
verify contiguity. With ``echo`` enabled on the channel the leader also
re-broadcasts each decode/verify chunk's FETCHED tokens (``OP_ECHO``)
and the follower compares them against its own device results.

Slice resilience (round 19 — docs/SERVING.md §20). The crash-only
multi-host contract is gone; three mechanisms replace it:

- ``OP_RECOVER`` + recovery epochs: a leader engine-loop crash under
  SPMD announces OP_RECOVER carrying a new epoch number instead of STOP.
  Both sides quarantine their in-flight device state and run the SAME
  deterministic rebuild (``engine._rebuild_device_state`` — the OP_WARMUP
  rule: identical config ⇒ identical dispatch sequence), the seq counter
  resets to the epoch base (0, so the first post-recovery announcement is
  seq 1), and the replica resumes under the leader's existing
  ``engine-restart-backoff``/``engine-max-restarts`` supervisor with
  QUEUED admissions preserved leader-side. Zero process exits.
- Watchdog: ``recv()`` takes a deadline (``watchdog_s`` on the channel —
  the ``spmd-watchdog-s`` knob). The leader announces OP_IDLE heartbeats
  whenever the wire would otherwise go quiet (idle iterations AND the
  restart-backoff wait), so silence past the deadline is evidence of a
  dead or wedged leader: the follower dumps a ``spmd-wedge`` flight
  record and exits with ``SpmdWedgeError`` (bounded-time detection
  instead of parking in the collective forever). The leader symmetrically
  bounds its per-iteration fetch waits by the same knob and escalates a
  wedged iteration to OP_RECOVER (``EngineWedgedError`` → the supervisor)
  instead of hanging the slice.
- Divergence resync: an echo TOKEN mismatch or a seq gap first requests
  ONE coordinated resync (``report_divergence`` — follower→leader via a
  shared flag on the loopback channel, via the jax.distributed KV store
  when a real coordinator is up, unsupported ⇒ the old fatal path). The
  leader answers with ``OP_RESYNC``: its authoritative per-slot page
  tables and device positions at a new epoch (the active-slot mask is
  per-dispatch wire data and needs no resync). The follower
  VERIFIES its own tables/positions against them — a match means the
  divergence was transient wire loss (e.g. a dropped idle heartbeat) and
  the follower rejoins at the new epoch; a mismatch, a second divergence
  while a resync is pending, or any divergence within ``resync_window_s``
  of the previous resync stays fatal (``SpmdDivergenceError`` + the
  ``spmd-divergence`` dump). Structural divergences (unknown op, echo
  SHAPE mismatch, failed replay) never attempt resync — leader and
  follower configs provably disagree and re-verification cannot help.
  Wrong output is never served from half the mesh.

The transport is ``jax.experimental.multihost_utils.broadcast_one_to_all``
— a psum over the global device mesh, so every announcement is itself a
lockstep point: followers park inside the collective until the leader's
next dispatch arrives. All announcements are made from the leader's engine
thread, preserving a single total order.

Fixed shapes: collectives require every process to present identical
shapes, so the block is padded to (prefill_batch, max bucket width) and
sliced host-side after receipt. The page/draft/echo payloads get their own
fixed-shape buffers (sized from ``table_len`` / ``spec_tokens`` at
construction — identical on every process because the engine config is).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

_monotonic = time.monotonic

OP_IDLE = 0
OP_PREFILL = 1
OP_LONG_SEG = 2
OP_DECODE = 3
OP_STOP = 4
OP_RING = 5  # ring long-prefill: padded prompt streamed in token chunks
OP_VERIFY = 6  # speculative verify dispatch (drafts payload)
OP_PREFIX_ADMIT = 7  # dense warm admission: gather + suffix segment
OP_PREFIX_PUBLISH = 8  # dense copy-on-publish into a pool row
OP_PAGE_BIND = 9  # paged reservation result: slot's page list (+ COW pair)
OP_PAGE_FREE = 10  # slot's table clears (completion / quarantine / abort)
OP_PAGE_ZERO = 11  # quarantine page-zero dispatch
OP_ROW_RESET = 12  # dense NaN-quarantine row zero dispatch
OP_ECHO = 13  # leader's fetched chunk result (divergence check, optional)
OP_WARMUP = 14  # replay a whole precompile family (count = WARMUP_* kind)
OP_RECOVER = 15  # leader loop crashed: both sides rebuild (count = epoch)
OP_RESYNC = 16  # leader's authoritative tables/positions/mask (divergence
#                 resync; long_idx = epoch, count = payload elements)

# OP_WARMUP kinds (ControlBlock.count)
WARMUP_DECODE_LADDER = 0
WARMUP_VERIFY_LADDER = 1
WARMUP_PAGED = 2
WARMUP_PREFILL_BUCKETS = 3
WARMUP_PREFIX_PROGRAMS = 4

# OP_ECHO kinds (ControlBlock.long_idx)
ECHO_DECODE = 0
ECHO_VERIFY = 1

# head vector layout (int32[_HEAD_LEN])
_H_OP = 0
_H_WIDTH = 1
_H_STEPS = 2
_H_NROWS = 3
_H_S0 = 4
_H_SEG_LEN = 5
_H_KV_BOUND = 6
_H_LONG_START = 7
_H_LONG_FINAL = 8
_H_LONG_IDX = 9
_H_PROMPT_LEN = 10
_H_T_LONG = 11
_H_ENTRY_ROW = 12  # prefix pool row (dense admit/publish, long warm start); -1 = none
_H_COW_SRC = 13  # copy-on-write source page (paged bind); -1 = none
_H_COW_DST = 14  # copy-on-write destination page; -1 = none
_H_SEQ = 15  # announcement sequence number (follower verifies contiguity)
_H_COUNT = 16  # page count / echo element count / warmup kind
_HEAD_LEN = 17


@dataclass
class ControlBlock:
    """One decoded announcement."""

    op: int
    width: int = 0
    steps: int = 0
    n_rows: int = 0
    s0: int = 0
    seg_len: int = 0
    kv_bound: int = 0
    long_start: bool = False
    long_final: bool = False
    long_idx: int = 0
    prompt_len: int = 0
    t_long: int = 0
    entry_row: int = -1
    cow_src: int = -1
    cow_dst: int = -1
    seq: int = 0
    count: int = 0
    tokens: Optional[np.ndarray] = None  # [n_rows, width] int32
    lengths: Optional[np.ndarray] = None  # [n_rows]
    slots: Optional[np.ndarray] = None  # [n_rows] (or stale idxs for DECODE)
    temps: Optional[np.ndarray] = None
    top_ks: Optional[np.ndarray] = None
    top_ps: Optional[np.ndarray] = None
    # active-slot mask [max_batch] (decode/verify: the leader's host-side
    # slot liveness — followers mask page-table rows with it)
    mask: Optional[np.ndarray] = None
    drafts: Optional[np.ndarray] = None  # [max_batch, k] int32 (OP_VERIFY)
    pages: Optional[np.ndarray] = None  # [count] int32 (bind/zero)
    echo: Optional[np.ndarray] = None  # flat int32[count] (OP_ECHO)


class SpmdChannel:
    """Fixed-shape broadcast channel between the replica's processes.

    ``table_len`` (paged layouts), ``spec_tokens`` (speculation) and
    ``decode_chunk`` size the page/draft/echo payload buffers; all derive
    from the engine config, so every process builds the identical channel.
    ``echo=True`` adds the leader→follower result echo after every
    processed decode/verify chunk (one extra broadcast per chunk — the
    divergence-detection mode the parity suite runs under; off by default
    in production).

    ``watchdog_s`` (the ``spmd-watchdog-s`` knob, 0 = off) arms the slice
    resilience machinery on BOTH sides: followers bound ``recv()`` by 2×
    it (the leader's own per-dispatch wait is bounded by 1×, so only
    silence past the leader's bound PLUS its escalation budget reads as
    dead → ``SpmdTimeout``), the leader announces OP_IDLE heartbeats at
    ``watchdog_s / 4`` whenever the wire would otherwise go quiet, and
    bounds its own per-iteration fetch waits by it. ``resync_window_s``
    is the follower's repeat-divergence window: a second divergence
    within it of a granted resync stays fatal. ``fault_injector`` drives the ``spmd-wedge`` (leader
    goes silent — every later announcement dropped) and ``spmd-drop``
    (one idle heartbeat lost → seq gap) drill sites at the transport
    layer (serving/faultinject.py)."""

    def __init__(
        self,
        prefill_batch: int,
        max_width: int,
        max_batch: int,
        table_len: int = 0,
        spec_tokens: int = 0,
        echo: bool = False,
        decode_chunk: int = 64,
        watchdog_s: float = 0.0,
        resync_window_s: float = 60.0,
        fault_injector: Optional[Any] = None,
    ) -> None:
        self.prefill_batch = int(prefill_batch)
        self.max_width = int(max_width)
        self.max_batch = int(max_batch)
        self.table_len = int(table_len)
        self.spec_tokens = int(spec_tokens)
        self.echo = bool(echo)
        self.decode_chunk = int(decode_chunk)
        self.watchdog_s = max(0.0, float(watchdog_s))
        self.resync_window_s = max(0.0, float(resync_window_s))
        # transport-layer fault injector (spmd-wedge / spmd-drop sites);
        # the ENGINE's injector is follower-nulled by follower_loop, this
        # one belongs to the channel itself
        self.injector = fault_injector
        # monotonic time of the last announce() ATTEMPT (wedged/dropped
        # announcements count — the leader believes it announced; that gap
        # between belief and wire is exactly what the watchdog detects)
        self.last_announce_t = 0.0
        self._wedged = False
        # deadline-receive machinery (lazily started: collectives cannot be
        # interrupted portably, so a deadline recv runs the blocking
        # receive on a persistent helper thread and bounds the WAIT; a
        # tripped deadline poisons the channel — the follower exits)
        self._rx_thread: Optional[Any] = None
        self._rx_req: Any = None
        self._rx_resp: Any = None
        # divergence-resync bookkeeping (report_ on followers, poll_ on
        # the leader; the base transport carries requests through the
        # jax.distributed KV store when one is up — one polled-counter
        # lane per follower process)
        self._resync_reported = 0
        self._resync_polled: dict[int, int] = {}
        # slots/stale padded to max(prefill rows, batch) so DECODE's stale
        # list and PREFILL's slot list share one field
        self.n_pad = max(self.prefill_batch, self.max_batch)
        self.page_pad = max(1, self.table_len)
        self.draft_pad = max(1, self.spec_tokens)
        # echo buffer: big enough for a full decode chunk ([steps ≤
        # decode_chunk, B] — a chunk never exceeds the engine's configured
        # chunk size; the ctor default covers every chunk the engine knob
        # allows by default) and a verify result ([B, k+2]); announce()
        # asserts the fit so a mis-sized config fails loudly on the
        # leader, never as a silent truncation
        # ALSO sized for the OP_RESYNC payload (per-slot tables + device
        # positions, flattened int32 — docs/SERVING.md §20), which rides
        # the same buffer: resyncs are rare, a dedicated buffer would
        # bloat every recv's shape template for nothing
        self.echo_pad = max(
            self.prefill_batch * self.max_width,
            self.max_batch * (self.draft_pad + 2),
            self.max_batch * max(1, self.decode_chunk),
            self.max_batch * (self.table_len + 1),
        )
        # wire accounting (PERF.md round 13): bytes broadcast per announce
        # — the measured ControlBlock overhead per engine iteration
        self.announces_total = 0
        self.bytes_announced_total = 0
        self._seq = 0
        # immutable zero templates: _pack copies ONLY the arrays an op
        # actually writes (head/slots/mask + its payload kind) and passes
        # the shared read-only blanks for the rest — a head-only OP_DECODE
        # on the hot path must not allocate the (large) echo/drafts/token
        # buffers it never ships. recv() reuses the blanks as pure shape
        # templates (broadcast returns new arrays; inputs are not mutated).
        self._blank = self._zeros()
        for a in self._blank:
            a.setflags(write=False)

    # -- packing -------------------------------------------------------------

    def _zeros(self) -> tuple:
        return (
            np.zeros(_HEAD_LEN, np.int32),
            np.zeros((self.prefill_batch, self.max_width), np.int32),
            np.zeros(self.n_pad, np.int32),  # lengths
            np.zeros(self.n_pad, np.int32),  # slots / stale
            np.zeros(self.n_pad, np.float32),  # temps
            np.zeros(self.n_pad, np.int32),  # top_ks
            np.ones(self.n_pad, np.float32),  # top_ps
            np.zeros(self.max_batch, np.int32),  # active mask
            np.zeros((self.max_batch, self.draft_pad), np.int32),  # drafts
            np.full(self.page_pad, -1, np.int32),  # pages
            np.zeros(self.echo_pad, np.int32),  # echo
        )

    def _pack(self, block: ControlBlock) -> tuple:
        blank = self._blank
        kind = self._payload_kind(block.op)
        head, slots, mask = blank[0].copy(), blank[3].copy(), blank[7].copy()
        if kind == "tokens":
            tokens, lengths = blank[1].copy(), blank[2].copy()
            temps, top_ks, top_ps = (
                blank[4].copy(), blank[5].copy(), blank[6].copy()
            )
        else:
            tokens, lengths, temps, top_ks, top_ps = (
                blank[1], blank[2], blank[4], blank[5], blank[6]
            )
        drafts = blank[8].copy() if kind == "drafts" else blank[8]
        pages = blank[9].copy() if kind == "pages" else blank[9]
        echo = blank[10].copy() if kind == "echo" else blank[10]
        head[_H_OP] = block.op
        head[_H_WIDTH] = block.width
        head[_H_STEPS] = block.steps
        head[_H_NROWS] = block.n_rows
        head[_H_S0] = block.s0
        head[_H_SEG_LEN] = block.seg_len
        head[_H_KV_BOUND] = block.kv_bound
        head[_H_LONG_START] = int(block.long_start)
        head[_H_LONG_FINAL] = int(block.long_final)
        head[_H_LONG_IDX] = block.long_idx
        head[_H_PROMPT_LEN] = block.prompt_len
        head[_H_T_LONG] = block.t_long
        head[_H_ENTRY_ROW] = block.entry_row
        head[_H_COW_SRC] = block.cow_src
        head[_H_COW_DST] = block.cow_dst
        head[_H_SEQ] = block.seq
        head[_H_COUNT] = block.count

        def fill(dst: np.ndarray, src: Optional[np.ndarray]) -> None:
            if src is not None and len(src):
                dst[: len(src)] = src

        if block.tokens is not None:
            n, w = block.tokens.shape
            tokens[:n, :w] = block.tokens
        fill(lengths, block.lengths)
        fill(slots, block.slots)
        fill(temps, block.temps)
        fill(top_ks, block.top_ks)
        fill(top_ps, block.top_ps)
        fill(mask, block.mask)
        if block.drafts is not None:
            n, k = block.drafts.shape
            assert k <= self.draft_pad, (
                f"drafts k={k} exceed the channel's spec_tokens={self.draft_pad}"
            )
            drafts[:n, :k] = block.drafts
        if block.pages is not None:
            assert len(block.pages) <= self.page_pad, (
                f"{len(block.pages)} pages exceed the channel's "
                f"table_len={self.page_pad}"
            )
            pages[: len(block.pages)] = block.pages
        if block.echo is not None:
            flat = np.asarray(block.echo, np.int32).reshape(-1)
            assert len(flat) <= self.echo_pad, (
                f"echo of {len(flat)} elements exceeds the channel's "
                f"{self.echo_pad}-element buffer"
            )
            echo[: len(flat)] = flat
        return (
            head, tokens, lengths, slots, temps, top_ks, top_ps,
            mask, drafts, pages, echo,
        )

    def _unpack(self, packed: tuple) -> ControlBlock:
        (
            head, tokens, lengths, slots, temps, top_ks, top_ps,
            mask, drafts, pages, echo,
        ) = (np.asarray(x) for x in packed)
        n = int(head[_H_NROWS])
        w = int(head[_H_WIDTH])
        count = int(head[_H_COUNT])
        return ControlBlock(
            op=int(head[_H_OP]),
            width=w,
            steps=int(head[_H_STEPS]),
            n_rows=n,
            s0=int(head[_H_S0]),
            seg_len=int(head[_H_SEG_LEN]),
            kv_bound=int(head[_H_KV_BOUND]),
            long_start=bool(head[_H_LONG_START]),
            long_final=bool(head[_H_LONG_FINAL]),
            long_idx=int(head[_H_LONG_IDX]),
            prompt_len=int(head[_H_PROMPT_LEN]),
            t_long=int(head[_H_T_LONG]),
            entry_row=int(head[_H_ENTRY_ROW]),
            cow_src=int(head[_H_COW_SRC]),
            cow_dst=int(head[_H_COW_DST]),
            seq=int(head[_H_SEQ]),
            count=count,
            tokens=tokens[:n, :w] if w else tokens[:n],
            lengths=lengths[:n],
            slots=slots[:n],
            temps=temps[:n],
            top_ks=top_ks[:n],
            top_ps=top_ps[:n],
            mask=mask,
            drafts=drafts,
            pages=pages[:count],
            echo=echo[:count],
        )

    # -- transport -----------------------------------------------------------

    def _broadcast(self, payload: tuple) -> tuple:
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(payload)

    @staticmethod
    def _payload_kind(op: int) -> Optional[str]:
        """Which second-phase payload an op ships. DECODE/STOP/IDLE and the
        page/row bookkeeping ops carry everything in the head + phase-1
        vectors — two-phase keeps the per-decode-chunk hot path small."""
        if op in (OP_PREFILL, OP_LONG_SEG, OP_RING, OP_PREFIX_ADMIT):
            return "tokens"
        if op == OP_VERIFY:
            return "drafts"
        if op in (OP_PAGE_BIND, OP_PAGE_ZERO):
            return "pages"
        if op in (OP_ECHO, OP_RESYNC):
            # OP_RESYNC reuses the echo buffer (tables ++ positions ++
            # mask, flattened; sized into echo_pad at construction)
            return "echo"
        return None

    @classmethod
    def _phases(cls, packed: tuple, op: int) -> tuple[tuple, Optional[tuple]]:
        """Split one packed block into its broadcast phases: the phase-1
        triple every announcement ships, plus the op's payload phase (or
        None). The ONE definition both transports (broadcast + loopback)
        and both directions (announce + recv) build from, so the protocol
        cannot drift between them — the wire-byte accounting PERF.md
        presents as exact is summed off these same tuples."""
        (
            head, tokens, lengths, slots, temps, top_ks, top_ps,
            mask, drafts, pages, echo,
        ) = packed
        phase1 = (head, slots, mask)
        kind = cls._payload_kind(op)
        if kind == "tokens":
            return phase1, (tokens, lengths, temps, top_ks, top_ps)
        if kind == "drafts":
            return phase1, (drafts,)
        if kind == "pages":
            return phase1, (pages,)
        if kind == "echo":
            return phase1, (echo,)
        return phase1, None

    # seq is carried in an int32 head slot: wrap BELOW 2^31 so a replica
    # that lives through billions of announcements keeps running instead
    # of dying on a numpy OverflowError (followers wrap identically)
    SEQ_MOD = 0x7FFFFFFF

    def _next_seq(self) -> int:
        self._seq = self._seq % self.SEQ_MOD + 1
        return self._seq

    def reset_seq(self) -> None:
        """Leader: reset the announcement sequence to the epoch base after
        an OP_RECOVER/OP_RESYNC announcement — the first post-recovery
        announcement is seq 1, and the follower resets its contiguity
        tracker when it processes the recover/resync block, so both sides
        agree on the base without a handshake (docs/SERVING.md §20)."""
        self._seq = 0

    def _deliver(self, op: int) -> bool:
        """Transport-layer fault sites (drills — serving/faultinject.py):
        ``spmd-wedge`` silences the leader permanently (every later
        announcement dropped: the follower watchdog's detection target),
        ``spmd-drop`` loses ONE idle heartbeat (seq still consumed — the
        next delivered announcement carries the gap the resync drill
        detects). Both model wire loss: the leader believes it announced."""
        if self._wedged:
            return False
        inj = self.injector
        if inj is None:
            return True
        if inj.fires("spmd-wedge"):
            self._wedged = True
            return False
        if op == OP_IDLE and inj.fires("spmd-drop"):
            return False
        return True

    # -- divergence resync signalling ----------------------------------------
    #
    # The broadcast wire is one-way (leader → followers); the resync
    # REQUEST needs the opposite direction. The loopback channel carries
    # it as a shared flag (same process); the real transport uses the
    # jax.distributed coordinator's KV store when one is initialized —
    # followers set a monotonically numbered key, the leader polls the
    # next expected one (throttled by the engine, never on a dispatch's
    # critical path). Where neither exists report_divergence returns
    # False and the follower keeps the round-13 fatal contract.

    @staticmethod
    def _kv_client():
        try:
            from jax._src import distributed

            client = distributed.global_state.client
        except Exception:  # noqa: BLE001 — old jax layouts: no side channel
            return None
        if client is None or not hasattr(client, "key_value_try_get"):
            return None
        return client

    def report_divergence(self, seq: int, op: int, why: str) -> bool:
        """Follower: ask the leader for one coordinated OP_RESYNC. True
        when the request was delivered (the follower then keeps replaying
        while it waits); False when no side channel exists (fatal path).
        Keys are namespaced by THIS follower's process index — every
        follower counts its own requests, so two followers diverging
        never collide on a key and the leader polls each lane
        independently."""
        import json

        import jax

        client = self._kv_client()
        if client is None:
            return False
        try:
            self._resync_reported += 1
            client.key_value_set(
                f"lstpu-spmd-resync-p{jax.process_index()}"
                f"-{self._resync_reported}",
                json.dumps({"seq": int(seq), "op": int(op), "why": str(why)}),
            )
            return True
        except Exception:  # noqa: BLE001 — coordinator gone ⇒ fatal path
            return False

    def poll_divergence(self) -> Optional[dict]:
        """Leader: the next pending resync request from ANY follower, or
        None. Non-blocking; the engine throttles calls to a few per
        second. One per-process polled counter per follower lane."""
        import json

        import jax

        client = self._kv_client()
        if client is None:
            return None
        for proc in range(1, jax.process_count()):
            seen = self._resync_polled.get(proc, 0)
            try:
                raw = client.key_value_try_get(
                    f"lstpu-spmd-resync-p{proc}-{seen + 1}"
                )
            except Exception:  # noqa: BLE001 — missing key raises on some jaxlibs
                continue
            if not raw:
                continue
            self._resync_polled[proc] = seen + 1
            try:
                req = json.loads(raw)
            except Exception:  # noqa: BLE001 — still a request, degraded
                req = {"why": "unparseable resync request"}
            req["process"] = proc
            return req
        return None

    def announce(self, block: ControlBlock) -> None:
        """Leader: publish the next device dispatch (engine thread only —
        announcements must form one total order). ONE prologue for every
        transport — seq assignment, the wedge/drop fault sites and the
        wire accounting live here so the loopback drills can never drift
        from the real broadcast; subclasses override only ``_send``."""
        self.last_announce_t = _monotonic()
        block.seq = self._next_seq()
        if not self._deliver(block.op):
            return
        packed = self._pack(block)
        phase1, payload = self._phases(packed, block.op)
        self._send(packed, phase1, payload)
        self.announces_total += 1
        self.bytes_announced_total += sum(a.nbytes for a in phase1) + (
            sum(a.nbytes for a in payload) if payload is not None else 0
        )

    def _send(self, packed: tuple, phase1: tuple, payload) -> None:
        """Transport hook: put the announcement on the wire."""
        self._broadcast(phase1)
        if payload is not None:
            self._broadcast(payload)

    def recv(self, timeout_s: Optional[float] = None) -> ControlBlock:
        """Follower: block until the leader's next dispatch. With
        ``timeout_s`` the WAIT is bounded: the blocking receive runs on a
        persistent helper thread and ``SpmdTimeout`` is raised on expiry
        (the collective itself cannot be interrupted portably — the
        helper stays parked in it, which is fine because a tripped
        watchdog means this process is about to exit)."""
        if timeout_s is None or timeout_s <= 0:
            return self._recv_blocking()
        import queue as _queue
        import threading as _threading

        if self._rx_thread is None or not self._rx_thread.is_alive():
            self._rx_req = _queue.SimpleQueue()
            self._rx_resp = _queue.SimpleQueue()

            def _rx_run() -> None:
                while self._rx_req.get():
                    try:
                        self._rx_resp.put(self._recv_blocking())
                    except BaseException as e:  # noqa: BLE001 — surface to caller
                        self._rx_resp.put(e)

            self._rx_thread = _threading.Thread(
                target=_rx_run, name="spmd-recv", daemon=True
            )
            self._rx_thread.start()
        self._rx_req.put(True)
        try:
            out = self._rx_resp.get(timeout=timeout_s)
        except _queue.Empty:
            raise SpmdTimeout(
                f"no leader announcement within {timeout_s:.1f}s "
                "(spmd-watchdog-s)"
            ) from None
        if isinstance(out, BaseException):
            raise out
        return out

    def close(self, timeout_s: float = 1.0) -> None:
        """Retire the receive helper. The falsy sentinel is honoured the
        next time the helper is idle between requests; a helper parked
        INSIDE the collective cannot be interrupted portably (it is
        ``daemon=True`` for exactly that case), so the join is bounded —
        a clean OP_STOP shutdown reaps it, a wedged one abandons it to
        process exit."""
        t = self._rx_thread
        if t is None:
            return
        self._rx_req.put(False)
        t.join(timeout=timeout_s)
        self._rx_thread = None

    def _recv_blocking(self) -> ControlBlock:
        zeros = self._blank  # shape templates only; broadcast never mutates
        head, slots, mask = self._broadcast((zeros[0], zeros[3], zeros[7]))
        tokens, lengths, temps, top_ks, top_ps = (
            zeros[1], zeros[2], zeros[4], zeros[5], zeros[6]
        )
        drafts, pages, echo = zeros[8], zeros[9], zeros[10]
        kind = self._payload_kind(int(np.asarray(head)[_H_OP]))
        if kind == "tokens":
            tokens, lengths, temps, top_ks, top_ps = self._broadcast(
                (tokens, lengths, temps, top_ks, top_ps)
            )
        elif kind == "drafts":
            (drafts,) = self._broadcast((drafts,))
        elif kind == "pages":
            (pages,) = self._broadcast((pages,))
        elif kind == "echo":
            (echo,) = self._broadcast((echo,))
        return self._unpack((
            head, tokens, lengths, slots, temps, top_ks, top_ps,
            mask, drafts, pages, echo,
        ))


class LoopbackChannel(SpmdChannel):
    """In-process channel for tests and the multichip dryrun: announce
    enqueues the packed block, recv dequeues it. Exercises the exact
    pack/unpack/fixed-shape discipline of the real broadcast path, with a
    leader engine and a follower engine sharing one process (and one
    device mesh) — the state-lockstep property is identical."""

    def __init__(
        self,
        prefill_batch: int,
        max_width: int,
        max_batch: int,
        table_len: int = 0,
        spec_tokens: int = 0,
        echo: bool = False,
        decode_chunk: int = 64,
        watchdog_s: float = 0.0,
        resync_window_s: float = 60.0,
        fault_injector: Optional[Any] = None,
    ) -> None:
        super().__init__(
            prefill_batch, max_width, max_batch,
            table_len=table_len, spec_tokens=spec_tokens, echo=echo,
            decode_chunk=decode_chunk, watchdog_s=watchdog_s,
            resync_window_s=resync_window_s, fault_injector=fault_injector,
        )
        import queue as _queue
        import threading as _threading

        self._q: Any = _queue.Queue()
        # same-process resync side channel (report_/poll_divergence)
        self._div_lock = _threading.Lock()
        self._div_req: Optional[dict] = None

    def _send(self, packed: tuple, phase1: tuple, payload) -> None:
        # the shared announce() prologue already split phases / counted
        # bytes off the SAME splitter the broadcast transport uses —
        # loopback benches measure the real per-iteration wire overhead
        self._q.put(packed)

    def recv(self, timeout_s: Optional[float] = None) -> ControlBlock:
        import queue as _queue

        try:
            packed = (
                self._q.get(timeout=timeout_s)
                if timeout_s is not None and timeout_s > 0
                else self._q.get()
            )
        except _queue.Empty:
            raise SpmdTimeout(
                f"no leader announcement within {timeout_s:.1f}s "
                "(spmd-watchdog-s)"
            ) from None
        return self._unpack(packed)

    def report_divergence(self, seq: int, op: int, why: str) -> bool:
        with self._div_lock:
            self._div_req = {"seq": int(seq), "op": int(op), "why": str(why)}
        return True

    def poll_divergence(self) -> Optional[dict]:
        with self._div_lock:
            req, self._div_req = self._div_req, None
        return req


class SpmdDivergenceError(RuntimeError):
    """Leader and follower state provably disagree (echo mismatch, sequence
    gap, or an un-replayable block) and a resync was unavailable, already
    pending, inside the repeat window, or failed verification. The replica
    must crash and restart together — continuing would serve garbage from
    half the mesh. ``resyncable`` marks detections a coordinated OP_RESYNC
    may heal (token-level echo mismatch, seq gap); structural disagreements
    (unknown op, shape mismatch, failed replay) never are."""

    def __init__(self, message: str, resyncable: bool = False) -> None:
        super().__init__(message)
        self.resyncable = resyncable


class SpmdTimeout(RuntimeError):
    """``recv(timeout_s)`` expired with no leader announcement — the
    watchdog's raw signal (docs/SERVING.md §20)."""


class SpmdWedgeError(RuntimeError):
    """The follower watchdog detected a silenced leader: no announcement
    (idle heartbeats included) within ``watchdog_s``. The follower has
    dumped a ``spmd-wedge`` flight record and exits deliberately so the
    replica's pods restart together instead of parking in the collective
    forever."""


def follower_loop(
    engine: Any, channel: SpmdChannel, watchdog_s: Optional[float] = None,
) -> None:
    """Replay the leader's dispatches on a follower process. ``engine`` is
    a ServingEngine constructed with the SAME config/params/mesh/seed but
    never start()ed — only its device-touching ``_dev_*`` methods (and the
    page-table bookkeeping the wire replays) run, so its sharded state
    evolves in lockstep with the leader's.

    Slice resilience (docs/SERVING.md §20): OP_RECOVER runs the same
    deterministic device rebuild the leader's crash recovery runs and
    rejoins at the announced epoch (zero process exits); a seq gap or an
    echo TOKEN mismatch requests ONE coordinated OP_RESYNC and keeps
    replaying while it waits — the resync block's authoritative
    tables/positions must VERIFY against this side's or the divergence is
    fatal after all; ``watchdog_s`` (default: the channel's) bounds every
    recv, and silence past it dumps ``spmd-wedge`` and raises
    SpmdWedgeError. Structural failures (unknown op, shape drift, a replay
    that raises) stay fatal by design, with the ``spmd-divergence`` flight
    dump tagged with the ControlBlock seq as the incident artifact."""
    import logging
    from collections import deque

    log = logging.getLogger(__name__)
    # a follower must never fire its own faults: the leader's announced ops
    # already reflect ITS injector, and an independent follower schedule
    # would diverge the replicas by construction
    engine._injector = None
    # device results of replayed decode/verify dispatches, kept only while
    # the channel runs in echo (divergence-check) mode; OP_ECHO pops the
    # oldest — leader processes fetches in dispatch order, so FIFO order
    # matches by construction
    pending_echo: deque = deque()
    last_seq = 0
    # strict next-seq expectation. None ONLY before the very first block
    # (a follower may attach mid-stream); after an OP_RECOVER/OP_RESYNC
    # epoch reset the expectation is exactly 1 — losing the FIRST
    # post-epoch announcement must read as the gap it is, not slip
    # through a relaxed sentinel check
    expected_seq: Optional[int] = None
    # divergence-resync state: one request may be outstanding, and a
    # granted resync opens a repeat window inside which any further
    # divergence is fatal (transient wire loss does not repeat; real
    # state divergence does)
    resync_pending = False
    last_resync_t = 0.0

    def _divergence(block: ControlBlock, why: str, resyncable: bool) -> bool:
        """True = a resync was requested (keep replaying); raises when the
        divergence must stay fatal."""
        nonlocal resync_pending
        now = time.monotonic()
        if (
            not resyncable
            or resync_pending
            or (last_resync_t and now - last_resync_t < channel.resync_window_s)
            or not channel.report_divergence(block.seq, block.op, why)
        ):
            _fail_divergence(engine, block, why, resyncable=resyncable)
        log.warning(
            "SPMD divergence at seq %d (op %d): %s — resync requested",
            block.seq, block.op, why,
        )
        _dump_divergence(engine, block, why + " (resync requested)")
        resync_pending = True
        return True

    while True:
        # re-read per iteration: the channel's watchdog_s is the live
        # knob (drills arm it after warmup; cold-start compiles on the
        # leader's engine thread can exceed any sane bound, so the bound
        # only means something once the replica is warm)
        wd = channel.watchdog_s if watchdog_s is None else max(0.0, watchdog_s)
        try:
            # deadline = 2× the bound: the LEADER's own per-dispatch wait
            # is bounded by watchdog_s, so a leader mid-escalation (silent
            # while it waits out a wedged fetch, then announcing
            # OP_RECOVER) must never read as dead — only silence past the
            # leader's bound PLUS its escalation budget is. This is the
            # "detection within 2× spmd-watchdog-s" contract (§20).
            block = channel.recv(timeout_s=2 * wd if wd > 0 else None)
        except SpmdTimeout as e:
            # the leader is dead or wedged: leave the incident artifact
            # and exit deliberately (bounded-time detection — the whole
            # point of the watchdog) instead of blocking forever
            log.error("SPMD follower watchdog tripped: %s", e)
            try:
                engine._flight_dump(
                    "spmd-wedge",
                    extra={
                        "last-seq": last_seq,
                        "watchdog-s": wd,
                        "why": str(e),
                    },
                )
            except Exception:  # noqa: BLE001 — the exit must proceed
                log.exception("spmd-wedge dump failed")
            raise SpmdWedgeError(
                f"leader silent past 2x the {wd:.1f}s watchdog (last seq "
                f"{last_seq}); follower exiting for a coordinated restart"
            ) from e
        if block.seq:
            if expected_seq is not None and block.seq != expected_seq:
                _divergence(
                    block,
                    f"announcement sequence gap: got seq {block.seq} after "
                    f"{last_seq} (expected {expected_seq}; a block was "
                    "lost or reordered)",
                    resyncable=True,
                )
            last_seq = block.seq
            expected_seq = block.seq % SpmdChannel.SEQ_MOD + 1  # wrap rule
        if block.op == OP_STOP:
            channel.close()
            return
        if block.op == OP_IDLE:
            continue
        if block.op == OP_RECOVER:
            # leader loop crash: run the IDENTICAL deterministic rebuild
            # (the OP_WARMUP rule — same config, same dispatch sequence),
            # drop any unechoed replay results (the leader's in-flight
            # chunks died unprocessed), and rejoin at the epoch base
            log.warning(
                "SPMD leader announced recovery (epoch %d); rebuilding "
                "device state in place", block.count,
            )
            pending_echo.clear()
            engine._spmd_follower_recover(block.count)
            last_seq = 0
            expected_seq = 1  # the epoch base — strictly
            resync_pending = False
            # the full rebuild wiped whatever state the repeat-divergence
            # window was guarding — a post-rebuild transient drop gets a
            # fresh one-resync allowance instead of a stale fatality
            last_resync_t = 0.0
            continue
        if block.op == OP_RESYNC:
            _apply_resync(engine, block)  # raises when verification fails
            log.warning(
                "SPMD resync verified; rejoining at epoch %d", block.long_idx,
            )
            last_seq = 0
            expected_seq = 1  # the epoch base — strictly
            resync_pending = False
            last_resync_t = time.monotonic()
            continue
        try:
            _replay(engine, block, channel, pending_echo)
        except SpmdDivergenceError as e:
            if not getattr(e, "resyncable", False):
                raise
            _divergence(block, str(e), resyncable=True)
        except Exception:
            log.exception("SPMD replay failed (op=%d); crashing replica", block.op)
            _dump_divergence(engine, block, "replay raised")
            raise


def _dump_divergence(engine: Any, block: ControlBlock, why: str) -> None:
    """Best-effort flight-recorder dump on a detected divergence — the
    SPMD incident artifact. Debounced per reason like every other dump
    path (a resync storm must not write N dumps per second); the FIRST
    detection in a burst is the evidence that matters."""
    try:
        engine._flight_dump(
            "spmd-divergence",
            extra={"seq": block.seq, "op": block.op, "why": why},
        )
    except Exception:  # noqa: BLE001 — the crash must proceed regardless
        import logging

        logging.getLogger(__name__).exception("divergence dump failed")


def _fail_divergence(
    engine: Any, block: ControlBlock, why: str, resyncable: bool = False,
) -> None:
    _dump_divergence(engine, block, why)
    raise SpmdDivergenceError(
        f"SPMD divergence at seq {block.seq} (op {block.op}): {why}",
        resyncable=resyncable,
    )


def _apply_resync(engine: Any, block: ControlBlock) -> None:
    """Verify the leader's authoritative OP_RESYNC snapshot against this
    follower's state: per-slot page tables (paged layouts) and device
    positions must MATCH — a match proves the divergence was transient
    wire loss and the follower rejoins; a mismatch means real state
    divergence and stays fatal (non-resyncable — a second resync could
    not change the verdict). The active-slot mask is NOT part of the
    snapshot: it is per-dispatch wire data, re-shipped authoritatively
    on every decode/verify block."""
    import jax

    b, tl = block.n_rows, block.width
    data = np.asarray(block.echo[: block.count], np.int32)
    if block.count != b * tl + b or len(data) != block.count:
        _fail_divergence(
            engine, block,
            f"resync payload shape mismatch: {block.count} elements for "
            f"{b} slots × table_len {tl} (config drift between hosts)",
        )
    if tl:
        theirs = data[: b * tl].reshape(b, tl)
        mine = np.asarray(engine._pagepool.tables[:b, :tl], np.int32)
        if not np.array_equal(mine, theirs):
            _fail_divergence(
                engine, block,
                "resync verification failed: per-slot page tables diverged "
                "(real allocator-state divergence, not wire loss)",
            )
    theirs_pos = data[b * tl :]
    mine_pos = np.asarray(
        jax.device_get(engine._positions_dev), np.int32
    )[:b]
    if not np.array_equal(mine_pos, theirs_pos):
        _fail_divergence(
            engine, block,
            "resync verification failed: device positions diverged (a "
            "material dispatch was lost, not just a heartbeat)",
        )


def _replay(
    engine: Any,
    block: ControlBlock,
    channel: SpmdChannel,
    pending_echo,
) -> None:
    if block.op == OP_PREFILL:
        engine._dev_prefill(
            block.width,
            block.tokens,
            block.lengths,
            block.temps,
            block.top_ks,
            block.top_ps,
            block.slots,
        )
    elif block.op == OP_LONG_SEG:
        if engine._paged:
            # paged segments (long-prompt chunks AND warm suffix segments)
            # write straight into the slot's wire-bound pages
            engine._dev_paged_segment(
                block.tokens,
                block.s0,
                block.seg_len,
                block.long_idx,
                float(block.temps[0]),
                int(block.top_ks[0]),
                float(block.top_ps[0]),
                final=block.long_final,
                prompt_len=block.prompt_len,
            )
        else:
            engine._dev_long_segment(
                block.tokens,
                block.s0,
                block.seg_len,
                block.kv_bound,
                block.t_long,
                float(block.temps[0]),
                int(block.top_ks[0]),
                float(block.top_ps[0]),
                start=block.long_start,
                final=block.long_final,
                idx=block.long_idx,
                prompt_len=block.prompt_len,
                prefix_row=block.entry_row if block.entry_row >= 0 else None,
            )
    elif block.op == OP_RING:
        # the padded prompt streams in (prefill_batch*max_width)-token
        # chunks; the final chunk triggers the one-dispatch ring admit,
        # evolving the follower's sharded state in lockstep with the leader
        if block.long_start:
            engine._spmd_ring_buf = []
        engine._spmd_ring_buf.append(
            np.asarray(block.tokens, np.int32).reshape(-1)[: block.seg_len]
        )
        if block.long_final:
            prompt = np.concatenate(engine._spmd_ring_buf)
            engine._spmd_ring_buf = []
            # reconstruct the leader's pow2 padding locally (deterministic
            # from the shared mesh/max_seq_len config) — only the prompt
            # itself rides the channel
            s_pad = engine._ring_pad(block.prompt_len)
            tokens = np.zeros((1, s_pad), np.int32)
            tokens[0, : len(prompt)] = prompt
            engine._dev_ring(
                tokens,
                block.prompt_len,
                float(block.temps[0]),
                int(block.top_ks[0]),
                float(block.top_ps[0]),
                block.long_idx,
            )
    elif block.op == OP_DECODE:
        # kv_bound=0 replays pre-bound announcements as unbounded
        chunk = engine._dev_decode(
            block.steps, block.slots, block.kv_bound or None, mask=block.mask
        )
        if channel.echo:
            pending_echo.append((ECHO_DECODE, chunk))
    elif block.op == OP_VERIFY:
        k = block.steps  # drafts per slot (engine.spec_tokens on the leader)
        packed = engine._dev_verify(
            np.asarray(block.drafts[:, :k], np.int32),
            block.slots,
            block.kv_bound,
            mask=block.mask,
        )
        if channel.echo:
            pending_echo.append((ECHO_VERIFY, packed))
    elif block.op == OP_PREFIX_ADMIT:
        engine._dev_prefix_admit(
            block.tokens,
            block.s0,
            block.seg_len,
            block.kv_bound,
            block.entry_row,
            float(block.temps[0]),
            int(block.top_ks[0]),
            float(block.top_ps[0]),
            block.long_idx,
        )
    elif block.op == OP_PREFIX_PUBLISH:
        engine._dev_prefix_publish(block.long_idx, block.entry_row)
    elif block.op == OP_PAGE_BIND:
        engine._spmd_apply_bind(
            block.long_idx,
            list(block.pages),
            block.cow_src if block.cow_src >= 0 else None,
            block.cow_dst if block.cow_dst >= 0 else None,
        )
    elif block.op == OP_PAGE_FREE:
        # the follower tracks TABLES only (never the free list/refcounts —
        # future reservations arrive as explicit BIND results)
        engine._pagepool.free_slot(block.long_idx)
    elif block.op == OP_PAGE_ZERO:
        engine._dev_page_zero(list(block.pages))
    elif block.op == OP_ROW_RESET:
        engine._dev_row_reset(list(block.slots))
    elif block.op == OP_WARMUP:
        _replay_warmup(engine, block)
    elif block.op == OP_ECHO:
        _check_echo(engine, block, pending_echo)
    else:
        _fail_divergence(engine, block, f"unknown op {block.op}")


def _replay_warmup(engine: Any, block: ControlBlock) -> None:
    """Run the announced precompile family locally — both sides execute the
    identical deterministic dispatch sequence (same config ⇒ same shapes,
    same PRNG consumption), so the warmups cost ONE announcement each."""
    kind = block.count
    if kind == WARMUP_DECODE_LADDER:
        engine._warmup_decode_ladder()
    elif kind == WARMUP_VERIFY_LADDER:
        engine._warmup_verify_ladder()
    elif kind == WARMUP_PAGED:
        engine._warmup_paged()
    elif kind == WARMUP_PREFILL_BUCKETS:
        engine._warmup_prefill_buckets()
    elif kind == WARMUP_PREFIX_PROGRAMS:
        engine._warmup_prefix_programs()
    else:
        _fail_divergence(engine, block, f"unknown warmup kind {kind}")


def _check_echo(engine: Any, block: ControlBlock, pending_echo) -> None:
    """Compare the leader's fetched chunk tokens against the follower's own
    device result for the same dispatch — the strongest per-chunk
    divergence check the protocol offers (opt-in: one device→host sync per
    chunk on the follower)."""
    import jax

    if not pending_echo:
        _fail_divergence(
            engine, block, "echo arrived with no pending replayed dispatch"
        )
    kind, dev = pending_echo.popleft()
    if kind != block.long_idx:
        _fail_divergence(
            engine, block,
            f"echo kind mismatch: leader says {block.long_idx}, follower "
            f"replayed {kind}",
        )
    full = np.asarray(jax.device_get(dev), np.int32).reshape(-1)
    if len(full) != block.count:
        # a shape drift (e.g. mismatched spec_tokens/decode_chunk config)
        # must report as the divergence it is — checked against the FULL
        # follower result, in either direction, before any truncation
        _fail_divergence(
            engine, block,
            f"echo length mismatch: leader sent {block.count} elements, "
            f"follower's replayed result has {len(full)}",
        )
    mine = full[: block.count]
    theirs = np.asarray(block.echo[: block.count], np.int32)
    if not np.array_equal(mine, theirs):
        # token-level disagreement is the one divergence class a transient
        # cause (one corrupted broadcast) can explain — resync-eligible;
        # if it repeats, the window rule makes it fatal (§20)
        bad = int(np.argmax(mine != theirs))
        _fail_divergence(
            engine, block,
            f"token divergence at element {bad}: leader {int(theirs[bad])} "
            f"vs follower {int(mine[bad])}",
            resyncable=True,
        )
