"""Ring attention: causal attention with the sequence axis sharded over the
device mesh (context parallelism for long inputs).

No reference counterpart (SURVEY §5 "long-context: absent") — designed for
TPU from the ring-attention / blockwise-attention pattern: each device holds
one sequence block of Q/K/V; K/V blocks rotate around the ring via
``lax.ppermute`` (ICI neighbour exchange) while a numerically-stable online
softmax (flash-attention style m/l accumulators, fp32) folds in one block's
contribution per step. Peak memory per device is O(S/n · S/n) scores instead
of O(S²), and the K/V transfer overlaps with the block matmul under XLA's
async collectives.

Runs inside ``shard_map`` (parallel.sp wraps the model forward); the axis
name arrives via ``ModelConfig.ring_axis``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from langstream_tpu.models.configs import ModelConfig

# plain Python float, NOT jnp.float32(...): this module is lazily imported
# from inside traced functions (the engine's ring admit, _scan_layers), and
# a module-level jnp constant created during a trace is a TRACER that
# outlives its trace — every later ring dispatch then dies with
# UnexpectedTracerError. A Python scalar weaves into jnp ops just as well
# and can never leak.
_NEG = -1e30


def ring_attention(
    q: jax.Array,  # [B, Sl, H, D] local query block
    k: jax.Array,  # [B, Sl, Hkv, D] local key block
    v: jax.Array,  # [B, Sl, Hkv, D] local value block
    config: ModelConfig,
) -> jax.Array:
    """Causal GQA attention over the ring axis → [B, Sl, H*D] local output.

    Must be called under shard_map with ``config.ring_axis`` mapped; block b
    on device b covers global positions [b·Sl, (b+1)·Sl).
    """
    axis = config.ring_axis
    assert axis is not None, "ring_attention requires config.ring_axis"
    n = lax.psum(1, axis)
    my = lax.axis_index(axis)

    h, hkv = config.n_heads, config.n_kv_heads
    group = h // hkv
    b, sl, _, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(d))

    qg = q.reshape(b, sl, hkv, group, d)
    q_pos = my * sl + jnp.arange(sl)  # global positions of local queries

    def _varying(x):
        if hasattr(lax, "pcast"):
            return lax.pcast(x, (axis,), to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(x, (axis,))
        return x  # jax 0.4.x: no varying-type system, arrays are plain

    # fp32 online-softmax state (cast device-varying on the ring axis: the
    # carry becomes varying the moment block data folds in)
    m0 = _varying(jnp.full((b, hkv, group, sl), _NEG, jnp.float32))
    l0 = _varying(jnp.zeros((b, hkv, group, sl), jnp.float32))
    acc0 = _varying(jnp.zeros((b, sl, hkv, group, d), jnp.float32))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        k_blk, v_blk, m, l, acc = carry
        src = (my - i) % n  # which device's block we hold at this step

        def fold(operand):
            k_blk, m, l, acc = operand
            kv_pos = src * sl + jnp.arange(sl)
            scores = (
                jnp.einsum("bshgd,bthd->bhgst", qg, k_blk).astype(jnp.float32) * scale
            )
            if config.attn_logit_softcap is not None:
                cap = jnp.float32(config.attn_logit_softcap)
                scores = jnp.tanh(scores / cap) * cap
            causal = kv_pos[None, :] <= q_pos[:, None]  # [Sl, T]
            scores = jnp.where(causal[None, None, None, :, :], scores, _NEG)

            m_new = jnp.maximum(m, scores.max(axis=-1))
            p = jnp.exp(scores - m_new[..., None])  # [B,h,g,Sl,T]
            # fully-masked rows: scores=-1e30, m_new=-1e30 → p=1 — zero them
            p = jnp.where(scores <= _NEG, 0.0, p)
            corr = jnp.exp(m - m_new)
            l = l * corr + p.sum(axis=-1)
            pv = jnp.einsum(
                "bhgst,bthd->bshgd", p.astype(v_blk.dtype), v_blk
            ).astype(jnp.float32)
            acc = acc * corr.transpose(0, 3, 1, 2)[..., None] + pv
            return m_new, l, acc

        # causal block skip: when the held block is entirely in this device's
        # future (src > my), every score is masked — skip both matmuls. The
        # cond is per-device control flow (shard_map), so on average each
        # device folds (n+1)/2 of the n blocks instead of all of them; the
        # ppermute below stays OUTSIDE the cond (all devices must participate)
        m, l, acc = lax.cond(
            src <= my, fold, lambda op: (op[1], op[2], op[3]), (k_blk, m, l, acc)
        )

        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return k_blk, v_blk, m, l, acc

    _, _, m, l, acc = lax.fori_loop(0, n, step, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    return out.astype(q.dtype).reshape(b, sl, h * d)
