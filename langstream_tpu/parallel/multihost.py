"""Multi-host bootstrap: one logical agent replica spanning N pods.

The replica-vs-shard distinction (SURVEY §7): ``resources.parallelism``
multiplies broker CONSUMERS (each pod its own process, its own engine);
``resources.tpu.hosts > 1`` splits ONE consumer's device mesh across pods
that form a single ``jax.distributed`` process group over a multi-host TPU
slice. The reference's analogue is the StatefulSet-per-agent assumption in
`AgentResourcesFactory.java:526-556` — which this design must diverge from,
because a JAX multi-host replica needs ordinal-addressed peers and a
coordinator, not just N interchangeable pods.

Topology wiring (emitted by k8s/resources.py, consumed here):
  LANGSTREAM_TPU_HOSTS              pods per logical replica (default 1)
  LANGSTREAM_TPU_SERVICE            headless service for peer DNS
  LANGSTREAM_TPU_COORDINATOR_PORT   jax.distributed port (default 8476)
  POD_NAME                          StatefulSet ordinal source (downward API)

Pod ordinal o → process_index = o % hosts, replica_index = o // hosts;
process 0 of each group is the coordinator AND the only pod that opens the
broker consumer ("one logical consumer, N pods").

The leader-driven SPMD serving dispatch lives in ``spmd_serving.py``: the
leader broadcasts each device dispatch's control block via
``multihost_utils.broadcast_one_to_all``; followers replay the identical
jitted calls (``entrypoint.py`` follower branch). Validated by a REAL
2-process ``jax.distributed`` run with a live coordinator
(tests/test_spmd_serving.py: greedy output equals the single-process
reference) and by state-equality checks on the virtual mesh
(dryrun_multichip). HARDWARE-UNTESTED CAVEAT: no multi-host TPU slice
exists in this environment, so the collectives have only run over the CPU
cross-process backend, not ICI.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Mapping, Optional

log = logging.getLogger(__name__)

DEFAULT_COORDINATOR_PORT = 8476


@dataclass(frozen=True)
class DistributedConfig:
    """One pod's place in its logical replica's process group."""

    num_processes: int = 1
    process_index: int = 0
    replica_index: int = 0
    coordinator: str = ""  # host:port of process 0 (empty when single-host)

    @property
    def is_multihost(self) -> bool:
        return self.num_processes > 1

    @property
    def is_leader(self) -> bool:
        """The pod that owns the broker consumer for this replica."""
        return self.process_index == 0

    @staticmethod
    def from_env(env: Optional[Mapping[str, str]] = None) -> "DistributedConfig":
        env = os.environ if env is None else env
        hosts = int(env.get("LANGSTREAM_TPU_HOSTS", "1") or 1)
        if hosts <= 1:
            return DistributedConfig()
        pod_name = env.get("POD_NAME", "")
        base, _, tail = pod_name.rpartition("-")
        if not tail.isdigit():
            raise ValueError(
                f"LANGSTREAM_TPU_HOSTS={hosts} requires a StatefulSet POD_NAME "
                f"with an ordinal suffix, got {pod_name!r}"
            )
        ordinal = int(tail)
        service = env.get("LANGSTREAM_TPU_SERVICE", "")
        port = int(env.get("LANGSTREAM_TPU_COORDINATOR_PORT", DEFAULT_COORDINATOR_PORT))
        group_start = (ordinal // hosts) * hosts
        coordinator_pod = f"{base}-{group_start}"
        host = f"{coordinator_pod}.{service}" if service else coordinator_pod
        return DistributedConfig(
            num_processes=hosts,
            process_index=ordinal % hosts,
            replica_index=ordinal // hosts,
            coordinator=f"{host}:{port}",
        )


def bootstrap(config: DistributedConfig) -> None:
    """``jax.distributed.initialize`` for a multi-host replica. Must run
    before the first jax backend touch (entrypoint calls it first thing)."""
    if not config.is_multihost:
        return
    import jax

    log.info(
        "joining process group: %d/%d via %s (replica %d)",
        config.process_index,
        config.num_processes,
        config.coordinator,
        config.replica_index,
    )
    jax.distributed.initialize(
        coordinator_address=config.coordinator,
        num_processes=config.num_processes,
        process_id=config.process_index,
    )


# Mesh construction for a multi-host replica is parallel.mesh.build_mesh
# over the GLOBAL device list — jax.devices() after bootstrap() returns all
# hosts' chips in host-major order, so no separate builder exists (see the
# ordering note on build_mesh).
