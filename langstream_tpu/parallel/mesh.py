"""Device mesh construction from TpuSpec / axis dicts.

Axes (any may be size 1): "data" (DP/replica), "model" (TP over ICI),
"expert" (EP for MoE), "seq" (SP/context parallelism for long sequences).
The planner validated that the axis product matches the topology chip count
(core/planner._validate_tpu_meshes).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from langstream_tpu.api.model import TpuSpec

AXIS_ORDER = ("data", "expert", "seq", "model")


def build_mesh(
    axes: dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh with canonical axis order; missing axes get size 1.

    "model" is innermost so tensor-parallel collectives ride the fastest ICI
    links (scaling-book recipe: contract the heaviest-traffic axis last).

    Multi-host replicas pass the GLOBAL device list (jax.devices() after
    parallel.multihost.bootstrap) — it is host-major (sorted by
    process_index, then local id), so contiguous mesh blocks land on one
    host and the innermost axis rides intra-host ICI.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = [int(axes.get(a, 1)) for a in AXIS_ORDER]
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(f"mesh {axes} needs {need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(sizes)
    return Mesh(arr, AXIS_ORDER)


def mesh_from_tpu_spec(
    spec: Optional[TpuSpec], devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    if spec is None or not spec.mesh:
        devices = list(devices if devices is not None else jax.devices())
        return build_mesh({"model": 1}, devices[:1])
    return build_mesh(spec.mesh, devices)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    device = device or jax.devices()[0]
    return build_mesh({}, [device])
