"""Parameter / activation sharding rules (the "annotate and let XLA insert
collectives" recipe).

Tensor parallel ("model" axis): attention heads and FFN hidden dim are
column-sharded on the up-projection and row-sharded on the down-projection,
so each layer needs exactly one psum (inserted by XLA) after wo and w_down —
the Megatron schedule, expressed declaratively. Experts shard on "expert";
batch/cache slots on "data"; vocab on "model" for the (un)embedding.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from langstream_tpu.models.configs import ModelConfig

Params = dict


def param_specs(config: ModelConfig) -> Params:
    """PartitionSpec tree matching models.transformer.init_params layout."""
    layers: dict[str, P] = {
        "attn_norm": P(None, None),
        "wq": P(None, None, "model"),
        "wk": P(None, None, "model"),
        "wv": P(None, None, "model"),
        "wo": P(None, "model", None),
        "ffn_norm": P(None, None),
    }
    if config.is_moe:
        layers["router"] = P(None, None, None)
        layers["w_gate"] = P(None, "expert", None, "model")
        layers["w_up"] = P(None, "expert", None, "model")
        layers["w_down"] = P(None, "expert", "model", None)
    else:
        layers["w_gate"] = P(None, None, "model")
        layers["w_up"] = P(None, None, "model")
        layers["w_down"] = P(None, "model", None)

    specs: Params = {
        "embed": P("model", None),  # vocab-sharded; gather rides ICI
        "layers": layers,
        "final_norm": P(None),
    }
    if not config.tie_embeddings:
        specs["lm_head"] = P(None, "model")
    return specs


def _kv_entry_specs(spec: P, quantized: bool):
    """int8 cache entries are {"q": [L,B,Hkv,T,D], "s": [L,B,Hkv,T]} — the
    scale tree shards like the values minus the trailing head-dim axis."""
    if not quantized:
        return spec
    return {"q": spec, "s": P(*list(spec)[:-1])}


def kv_cache_specs(quantized: bool = False) -> dict:
    # [L, B, Hkv, T, D] head-major — slots on data, kv heads on model
    spec = P(None, "data", "model", None, None)
    entry = _kv_entry_specs(spec, quantized)
    return {"k": entry, "v": entry}


def serving_cache_specs(n_kv_heads: int, mesh: Mesh) -> dict[str, P]:
    """Engine KV cache: kv heads on "model", batch REPLICATED — the engine
    scatters into individual slots at runtime indices, which must not cross
    shard boundaries (data parallelism in serving = more agent replicas,
    each with its own engine, not a sharded batch).  When the model axis
    outnumbers the kv heads (GQA with high TP) the cache replicates across
    the extra ways — same as Megatron's kv-head replication."""
    model_ways = int(mesh.shape.get("model", 1))
    if model_ways > 1 and n_kv_heads % model_ways == 0:
        spec = P(None, None, "model", None, None)
    else:
        spec = P(None, None, None, None, None)
    return {"k": spec, "v": spec}


def constrain_serving_local_cache(local_cache: dict, n_kv_heads: int, mesh: Mesh) -> dict:
    """Sharding constraint for a TRACED admission local cache (inside the
    fused admit-group jits): kv heads on "model" per serving_cache_specs,
    int8 scale trees mirroring the values minus the trailing axis. The ONE
    definition both the dense and the paged admit groups apply, so their
    sharding policies cannot drift (they must stay byte-identical — the
    token-exactness invariant rides on the same forward)."""
    from jax.lax import with_sharding_constraint

    quantized = isinstance(local_cache["k"], dict)
    specs = serving_cache_specs(n_kv_heads, mesh)
    if quantized:
        specs = {k: _kv_entry_specs(s, True) for k, s in specs.items()}
    return jax.tree.map(
        lambda x, s: with_sharding_constraint(x, NamedSharding(mesh, s)),
        local_cache,
        specs,
    )


def page_pool_specs(n_kv_heads: int, mesh: Mesh) -> P:
    """Paged KV pool [L, P, Hkv, page_size, D]: kv heads on "model" when
    they divide the axis, replicated otherwise — the same policy (and the
    same Megatron kv-replication fallback) as ``serving_cache_specs``. The
    page axis stays replicated: page ids are runtime table indices, and a
    gather that crossed shard boundaries on the page axis would turn every
    decode read into a collective."""
    model_ways = int(mesh.shape.get("model", 1))
    if model_ways > 1 and n_kv_heads % model_ways == 0:
        return P(None, None, "model", None, None)
    return P(None, None, None, None, None)


def shard_page_pool(pool_dev: dict, mesh: Mesh) -> dict:
    """Place a page-pool device tree (models.transformer.make_page_pool)
    onto the mesh. int8 pools carry {"q": [L,P,Hkv,ps,D], "s": [L,P,Hkv,ps]}
    entries — the scale tree shards like the values minus the trailing
    head-dim axis, exactly like the dense serving cache."""
    quantized = isinstance(pool_dev["k"], dict)
    values = pool_dev["k"]["q"] if quantized else pool_dev["k"]
    spec = page_pool_specs(values.shape[2], mesh)
    entry = _kv_entry_specs(spec, quantized)
    return jax.device_put(pool_dev, _named(mesh, {"k": entry, "v": entry}))


def shard_serving_cache(cache: dict, mesh: Mesh) -> dict:
    quantized = isinstance(cache["k"], dict)
    values = cache["k"]["q"] if quantized else cache["k"]
    specs = serving_cache_specs(values.shape[2], mesh)
    if quantized:
        specs = {
            key: _kv_entry_specs(spec, True) for key, spec in specs.items()
        }
    return jax.device_put(cache, _named(mesh, specs))


def data_spec() -> P:
    return P("data", None)


def _named(mesh: Mesh, tree: Any) -> Any:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Params, mesh: Mesh, config: ModelConfig) -> Params:
    """Place a param tree onto the mesh with TP/EP shardings (int8-quantized
    trees get mirrored specs: q keeps the weight's spec, scales drop the
    contracted axis)."""
    from langstream_tpu.models.quant import is_quantized, quantize_specs_for_params

    specs = param_specs(config)
    if is_quantized(params.get("layers", {}).get("wq")):
        specs = quantize_specs_for_params(specs, params)
    return jax.device_put(params, _named(mesh, specs))


def shard_kv_cache(cache: dict, mesh: Mesh) -> dict:
    return jax.device_put(
        cache, _named(mesh, kv_cache_specs(quantized=isinstance(cache["k"], dict)))
    )


def replicate(tree: Any, mesh: Mesh) -> Any:
    return jax.device_put(
        tree, NamedSharding(mesh, P())
    )
