"""Mesh / sharding / collectives — the intra-agent device-communication plane.

The reference has NO counterpart (SURVEY §2.11: TP/PP/SP/EP absent; inference
was remote HTTP). Here one agent replica = one JAX process group over an ICI
mesh; the broker stays the inter-agent transport, preserving the reference's
L2/L4 split.
"""

from langstream_tpu.parallel.mesh import build_mesh, mesh_from_tpu_spec
from langstream_tpu.parallel.sharding import (
    data_spec,
    kv_cache_specs,
    param_specs,
    shard_params,
)

__all__ = [
    "build_mesh",
    "data_spec",
    "kv_cache_specs",
    "mesh_from_tpu_spec",
    "param_specs",
    "shard_params",
]
