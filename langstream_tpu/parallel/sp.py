"""Sequence/context parallelism entry points (shard_map wrappers).

``sequence_parallel_forward`` runs the full-sequence forward with the
sequence dimension sharded over the mesh's "seq" axis and ring attention
exchanging K/V blocks over ICI — the long-context path (SURVEY §5: absent in
the reference, first-class here). Params are replicated across the seq axis
(combine with TP by also sharding params over "model" outside).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from langstream_tpu.models.configs import ModelConfig
from langstream_tpu.models.transformer import Params, forward


def sequence_parallel_forward(
    params: Params,
    tokens: jax.Array,  # [B, S] with S divisible by mesh axis "seq"
    config: ModelConfig,
    mesh: Mesh,
    axis: str = "seq",
) -> jax.Array:
    """Logits [B, S, V]; S sharded over ``axis`` during compute."""
    n = mesh.shape[axis]
    if tokens.shape[1] % n != 0:
        raise ValueError(
            f"sequence length {tokens.shape[1]} must be divisible by the "
            f"'{axis}' axis size {n} (pad the batch)"
        )
    ring_config = dataclasses.replace(config, ring_axis=axis)

    fwd = shard_map(
        functools.partial(forward, config=ring_config),
        mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis, None),
    )
    return fwd(params, tokens)
