"""Sequence/context parallelism entry points (shard_map wrappers).

``sequence_parallel_forward`` runs the full-sequence forward with the
sequence dimension sharded over the mesh's "seq" axis and ring attention
exchanging K/V blocks over ICI — the long-context path (SURVEY §5: absent in
the reference, first-class here). Params are replicated across the seq axis
(combine with TP by also sharding params over "model" outside).
"""

from __future__ import annotations

import dataclasses
import functools
import inspect

import jax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def _manual_axes_kwargs(mesh: Mesh, axis: str) -> dict:
    """Version-portable kwargs restricting shard_map's MANUAL axes to
    ``axis`` while every other mesh axis stays AUTO (GSPMD keeps
    tensor-parallel params sharded inside the body).

    jax ≥ 0.8 spells this ``axis_names={axis}``. On 0.4.x the complementary
    ``auto = mesh axes - {axis}`` spelling exists but lowers
    ``lax.axis_index`` inside the body to a PartitionId instruction the
    SPMD partitioner rejects (UNIMPLEMENTED) — so the 0.4.x fallback is
    FULLY MANUAL shard_map over every mesh axis: ``in_specs=P()`` then
    all-gathers the weight tree onto each device and the matmuls run
    full-width per sequence block. Numerically identical, but it holds a
    full weight copy per device — fine for the CPU test tier and small
    models; keeping tensor-parallel weights sharded through the ring needs
    the ``axis_names`` form (jax ≥ 0.8)."""
    params = inspect.signature(shard_map).parameters
    if "axis_names" in params:
        return {"axis_names": frozenset({axis})}
    return {}

from langstream_tpu.models.configs import ModelConfig
from langstream_tpu.models.transformer import Params, forward


def sequence_parallel_forward(
    params: Params,
    tokens: jax.Array,  # [B, S] with S divisible by mesh axis "seq"
    config: ModelConfig,
    mesh: Mesh,
    axis: str = "seq",
) -> jax.Array:
    """Logits [B, S, V]; S sharded over ``axis`` during compute."""
    n = mesh.shape[axis]
    if tokens.shape[1] % n != 0:
        raise ValueError(
            f"sequence length {tokens.shape[1]} must be divisible by the "
            f"'{axis}' axis size {n} (pad the batch)"
        )
    ring_config = dataclasses.replace(config, ring_axis=axis)

    fwd = shard_map(
        functools.partial(forward, config=ring_config),
        mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis, None),
    )
    return fwd(params, tokens)


def ring_prefill(
    params: Params,
    tokens: jax.Array,  # [B, S_pad] padded prompts, S_pad divisible by |axis|
    lengths: jax.Array,  # [B] true prompt lengths
    config: ModelConfig,
    mesh: Mesh,
    axis: str = "seq",
) -> tuple[jax.Array, dict]:
    """Single-dispatch LONG-PROMPT prefill with the sequence axis sharded:
    device d embeds prompt block d, ring attention rotates K/V blocks over
    ICI (no device ever holds the full S×S scores), and the prompt's whole
    per-layer K/V comes back position-sharded for the serving-cache splice.

    This is the multi-chip serving counterpart of engine._long_step's
    single-chip segment loop: one compiled call instead of S/W sequential
    segment dispatches. Returns (last-real-token logits [B, V],
    {"k","v"} [L, B, Hkv, S_pad, D] roped head-major K/V)."""
    from langstream_tpu.models.transformer import (
        _embed,
        _rope_freqs,
        _scan_layers,
        _unembed,
    )

    n = mesh.shape[axis]
    b, s = tokens.shape
    if s % n != 0:
        raise ValueError(
            f"padded prompt length {s} must be divisible by the "
            f"'{axis}' axis size {n}"
        )
    ring_config = dataclasses.replace(config, ring_axis=axis)
    sl = s // n

    def local(params, tok_local, lengths):
        import jax.numpy as jnp
        from jax import lax

        my = lax.axis_index(axis)
        positions = jnp.broadcast_to(jnp.arange(sl), (b, sl)) + my * sl
        sin, cos = _rope_freqs(positions, ring_config)
        x = _embed(params, tok_local, ring_config)
        # mask is unused on the ring path (causality lives inside
        # ring_attention's global block positions)
        x, (k, v) = _scan_layers(
            params, x, sin, cos, None, ring_config, collect_kv=True
        )
        # last real token lives in exactly one device's block: that device
        # contributes its hidden state, everyone else zeros, psum selects
        last = jnp.clip(lengths - 1, 0, s - 1)  # [B] global index
        idx = jnp.clip(last - my * sl, 0, sl - 1)
        own = (last // sl) == my  # [B]
        x_last = jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]
        x_last = jnp.where(own[:, None], x_last, jnp.zeros_like(x_last))
        x_last = lax.psum(x_last, axis)
        logits = _unembed(params, x_last[:, None, :], ring_config)[:, 0]
        return logits, {"k": k, "v": v}

    kv_spec = P(None, None, None, axis, None)
    # only the seq axis is MANUAL (axis_names); every other mesh axis
    # (model/expert/data) stays AUTO so GSPMD keeps tensor-parallel params
    # SHARDED inside the ring body (manual over all axes with in_specs=P()
    # would all-gather the full weight pytree onto every device — the exact
    # memory blowup the long-context path exists to avoid)
    fwd = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(None, axis), P()),
            out_specs=(P(), {"k": kv_spec, "v": kv_spec}),
            **_manual_axes_kwargs(mesh, axis),
        )
    )
    return fwd(params, tokens, lengths)
