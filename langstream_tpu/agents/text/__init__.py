"""Text-processing agents.

Parity: reference `langstream-agents-text-processing` (SURVEY §2.5):
`text-extractor` (Tika-based there; stdlib/bs4-based here), `text-splitter`
(`TextSplitter.java` / `RecursiveCharacterTextSplitter.java` — a recursive
character splitter), `language-detector`, `text-normaliser`,
`document-to-json`. Each registers into the agent registry on import.
"""

from __future__ import annotations

import json
import re
import zipfile
from io import BytesIO
from typing import Any, Callable

from langstream_tpu.api.agent import ComponentType, SingleRecordProcessor
from langstream_tpu.api.doc import ConfigModel, ConfigProperty, props
from langstream_tpu.api.record import Record, SimpleRecord
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo

# ---------------------------------------------------------------------------
# text-splitter
# ---------------------------------------------------------------------------


def recursive_split(
    text: str,
    chunk_size: int,
    chunk_overlap: int,
    separators: list[str],
    length_function: Callable[[str], int],
    keep_separator: bool = False,
) -> list[str]:
    """Recursive character splitting (reference RecursiveCharacterTextSplitter,
    itself a port of the LangChain splitter): try the coarsest separator that
    appears in the text; splits still too large recurse on finer separators;
    small neighbouring splits merge back up to chunk_size with overlap."""

    def _split_on(text: str, separator: str) -> list[str]:
        if separator == "":
            return list(text)
        if keep_separator:
            parts = re.split(f"({re.escape(separator)})", text)
            # stitch separators onto the preceding fragment
            merged: list[str] = []
            for i in range(0, len(parts), 2):
                frag = parts[i]
                if i + 1 < len(parts):
                    frag += parts[i + 1]
                if frag:
                    merged.append(frag)
            return merged
        return [p for p in text.split(separator) if p != ""]

    def _merge(splits: list[str], separator: str) -> list[str]:
        joiner = "" if keep_separator else separator
        docs: list[str] = []
        current: list[str] = []
        total = 0
        for s in splits:
            slen = length_function(s)
            if current and total + slen + (len(joiner) if current else 0) > chunk_size:
                docs.append(joiner.join(current))
                # shed from the front until the carried overlap fits the
                # overlap budget AND leaves room for the incoming split
                while current and (
                    total > chunk_overlap
                    or total + slen + (len(joiner) if current else 0) > chunk_size
                ):
                    total -= length_function(current[0]) + (len(joiner) if len(current) > 1 else 0)
                    current.pop(0)

            current.append(s)
            total += slen + (len(joiner) if len(current) > 1 else 0)
        if current:
            docs.append(joiner.join(current))
        return [d for d in (doc.strip() for doc in docs) if d]

    def _split(text: str, separators: list[str]) -> list[str]:
        separator = separators[-1]
        rest: list[str] = []
        for i, sep in enumerate(separators):
            if sep == "" or sep in text:
                separator = sep
                rest = separators[i + 1 :]
                break
        splits = _split_on(text, separator)
        out: list[str] = []
        small: list[str] = []
        for s in splits:
            if length_function(s) < chunk_size:
                small.append(s)
            else:
                if small:
                    out.extend(_merge(small, separator))
                    small = []
                if rest:
                    out.extend(_split(s, rest))
                else:
                    out.append(s)
        if small:
            out.extend(_merge(small, separator))
        return out

    return _split(text, separators)


_TOKEN_ENCODINGS = {"cl100k_base", "p50k_base", "r50k_base", "o200k_base"}


def _token_length_function(encoding: str) -> Callable[[str], int]:
    """Token-count length function (the reference counts cl100k_base tokens via
    jtokkit; no tokenizer vocab ships in this image, so estimate ~4 chars/token
    — same scale, monotonic in text length). Unknown names are rejected so a
    typo doesn't silently change chunk sizes 4x."""
    if encoding not in _TOKEN_ENCODINGS:
        raise ValueError(
            f"unknown length_function {encoding!r}; use 'length' or one of "
            f"{sorted(_TOKEN_ENCODINGS)}"
        )
    return lambda s: max(1, len(s) // 4)


class TextSplitterAgent(SingleRecordProcessor):
    """`text-splitter` (reference TextSplitter.java): one record in, one
    record per chunk out, with chunk bookkeeping headers."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.splitter_type = configuration.get("splitter_type", "RecursiveCharacterTextSplitter")
        self.chunk_size = int(configuration.get("chunk_size", 200))
        self.chunk_overlap = int(configuration.get("chunk_overlap", 100))
        self.keep_separator = bool(configuration.get("keep_separator", False))
        self.separators = list(configuration.get("separators", ["\n\n", "\n", " ", ""]))
        lf = configuration.get("length_function", "length")
        if lf in ("length", "len"):
            self.length_function: Callable[[str], int] = len
        else:
            self.length_function = _token_length_function(lf)

    async def process_record(self, record: Record) -> list[Record]:
        text = record.value
        if isinstance(text, bytes):
            text = text.decode("utf-8", "replace")
        if not isinstance(text, str):
            text = str(text)
        chunks = recursive_split(
            text,
            self.chunk_size,
            self.chunk_overlap,
            self.separators,
            self.length_function,
            self.keep_separator,
        )
        out: list[Record] = []
        for i, chunk in enumerate(chunks):
            out.append(
                SimpleRecord.of(
                    chunk,
                    key=record.key,
                    headers=list(record.headers)
                    + [
                        ("chunk_id", str(i)),
                        ("chunk_num_chunks", str(len(chunks))),
                        ("chunk_text_length", str(self.length_function(chunk))),
                    ],
                    origin=record.origin,
                    timestamp=record.timestamp,
                )
            )
        self.processed(1)
        return out


# ---------------------------------------------------------------------------
# text-extractor
# ---------------------------------------------------------------------------


def _extract_docx(data: bytes) -> str:
    """OOXML word/document.xml text (stdlib replacement for Tika's docx path)."""
    from xml.etree import ElementTree

    with zipfile.ZipFile(BytesIO(data)) as zf:
        xml = zf.read("word/document.xml")
    ns = "http://schemas.openxmlformats.org/wordprocessingml/2006/main"
    root = ElementTree.fromstring(xml)
    paragraphs = []
    for p in root.iter(f"{{{ns}}}p"):
        texts = [t.text or "" for t in p.iter(f"{{{ns}}}t")]
        if texts:
            paragraphs.append("".join(texts))
    return "\n".join(paragraphs)


def _extract_html(data: bytes | str) -> str:
    from bs4 import BeautifulSoup

    soup = BeautifulSoup(data, "html.parser")
    for tag in soup(["script", "style", "noscript"]):
        tag.decompose()
    return re.sub(r"\n{3,}", "\n\n", soup.get_text("\n")).strip()


class TextExtractorAgent(SingleRecordProcessor):
    """`text-extractor` (reference uses Apache Tika; here: HTML via bs4,
    docx via stdlib zip+xml, plain/UTF-8 text passthrough).
    Unsupported binary formats raise → routed to the errors policy."""

    async def process_record(self, record: Record) -> list[Record]:
        value = record.value
        text: str
        if isinstance(value, bytes):
            head = value[:512].lstrip()
            if value[:4] == b"PK\x03\x04":
                text = _extract_docx(value)
            elif head[:1] == b"<" or b"<html" in head.lower():
                text = _extract_html(value)
            elif value[:5] == b"%PDF-":
                raise ValueError("PDF extraction requires an external parser (not bundled)")
            else:
                text = value.decode("utf-8", "replace")
        elif isinstance(value, str):
            text = _extract_html(value) if value.lstrip().startswith("<") else value
        else:
            text = str(value)
        self.processed(1)
        return [SimpleRecord.copy_from(record, value=text)]


# ---------------------------------------------------------------------------
# language-detector
# ---------------------------------------------------------------------------

# Most-frequent function words per language — enough signal to classify the
# document-sized inputs this agent sees (the reference wraps the langdetect
# library; a library-free classifier keeps the image dependency-light).
_LANG_STOPWORDS: dict[str, frozenset[str]] = {
    "en": frozenset("the of and to in is you that it he was for on are as with his they at be this have from or had by but not what all were we when your can said there use an each which she do how their if will up other about out many then them these so some her would make like him into time has look two more".split()),
    "es": frozenset("de la que el en y a los del se las por un para con no una su al lo como más pero sus le ya o este sí porque esta entre cuando muy sin sobre también me hasta hay donde quien desde todo nos durante todos uno les ni contra otros ese eso ante ellos e esto".split()),
    "fr": frozenset("de la le et les des en un du une que est pour qui dans a par plus pas au sur ne se ce il sont avec son ils mais comme ou si leur y dont elle tout nous sa cette ses être aux cela était ont fait aussi".split()),
    "de": frozenset("der die und in den von zu das mit sich des auf für ist im dem nicht ein eine als auch es an werden aus er hat dass sie nach wird bei einer um am sind noch wie einem über einen so zum war haben nur oder aber vor zur bis mehr durch man".split()),
    "it": frozenset("di e il la che in a per è un non sono con si da come le dei io questo ha più ma lo della gli al se mi ci nel anche tu ti su una alla sua delle degli nella questa loro tutto molto".split()),
    "pt": frozenset("de a o que e do da em um para é com não uma os no se na por mais as dos como mas foi ao ele das tem à seu sua ou ser quando muito há nos já está eu também só pelo pela até isso".split()),
    "nl": frozenset("de en van het een in is dat op te zijn met die voor niet aan er om ook als dan maar bij of uit naar door over ze hij nog wordt wel geen worden deze tot hebben meer andere".split()),
}


def detect_language(text: str) -> str:
    words = re.findall(r"[\wÀ-ÿ]+", text.lower())
    if not words:
        return "unknown"
    best, best_score = "unknown", 0
    for lang, stops in _LANG_STOPWORDS.items():
        score = sum(1 for w in words if w in stops)
        if score > best_score:
            best, best_score = lang, score
    return best if best_score > 0 else "unknown"


class LanguageDetectorAgent(SingleRecordProcessor):
    """`language-detector`: annotate records with detected language; drop
    records outside `allowedLanguages` (reference behavior)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.allowed = set(configuration.get("allowedLanguages", []))
        self.property = configuration.get("property", "language")

    async def process_record(self, record: Record) -> list[Record]:
        value = record.value
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        lang = detect_language(str(value))
        self.processed(1)
        if self.allowed and lang not in self.allowed:
            return []
        headers = tuple(h for h in record.headers if h.key != self.property)
        out = SimpleRecord.copy_from(record, headers=headers).with_headers(
            [(self.property, lang)]
        )
        return [out]


# ---------------------------------------------------------------------------
# text-normaliser
# ---------------------------------------------------------------------------


class TextNormaliserAgent(SingleRecordProcessor):
    """`text-normaliser`: lowercase + whitespace-trim knobs."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.make_lowercase = bool(configuration.get("make-lowercase", True))
        self.trim_spaces = bool(configuration.get("trim-spaces", True))

    async def process_record(self, record: Record) -> list[Record]:
        text = record.value
        if isinstance(text, bytes):
            text = text.decode("utf-8", "replace")
        text = str(text)
        if self.make_lowercase:
            text = text.lower()
        if self.trim_spaces:
            text = re.sub(r"[ \t]+", " ", text)
            text = "\n".join(line.strip() for line in text.splitlines()).strip()
        self.processed(1)
        return [SimpleRecord.copy_from(record, value=text)]


# ---------------------------------------------------------------------------
# document-to-json
# ---------------------------------------------------------------------------


class DocumentToJsonAgent(SingleRecordProcessor):
    """`document-to-json`: wrap raw text into a JSON object under
    `text-field`, optionally copying record headers in as fields."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.text_field = configuration.get("text-field", "text")
        self.copy_properties = bool(configuration.get("copy-properties", True))

    async def process_record(self, record: Record) -> list[Record]:
        value = record.value
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        doc: dict[str, Any] = {}
        if self.copy_properties:
            for h in record.headers:
                doc[h.key] = h.value_as_string()
        doc[self.text_field] = value
        self.processed(1)
        return [SimpleRecord.copy_from(record, value=json.dumps(doc))]


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------


def _register() -> None:
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="text-splitter",
            component_type=ComponentType.PROCESSOR,
            factory=TextSplitterAgent,
            composable=True,
            description="Split text into overlapping chunks (recursive character splitter).",
            config_model=ConfigModel(
                type="text-splitter",
                properties=props(
                    ConfigProperty("splitter_type", "splitter algorithm", default="RecursiveCharacterTextSplitter"),
                    ConfigProperty("chunk_size", "max chunk length", type="integer", default=200),
                    ConfigProperty("chunk_overlap", "overlap between chunks", type="integer", default=100),
                    ConfigProperty("keep_separator", "keep separators in chunks", type="boolean", default=False),
                    ConfigProperty("separators", "separator hierarchy", type="array"),
                    ConfigProperty("length_function", "length metric (length|cl100k_base)", default="length"),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="text-extractor",
            component_type=ComponentType.PROCESSOR,
            factory=TextExtractorAgent,
            composable=True,
            description="Extract plain text from documents (HTML, docx, text).",
            config_model=ConfigModel(type="text-extractor", allow_unknown=True),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="language-detector",
            component_type=ComponentType.PROCESSOR,
            factory=LanguageDetectorAgent,
            composable=True,
            description="Detect document language; filter by allowed languages.",
            config_model=ConfigModel(
                type="language-detector",
                properties=props(
                    ConfigProperty("allowedLanguages", "keep only these languages", type="array"),
                    ConfigProperty("property", "header to set", default="language"),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="text-normaliser",
            component_type=ComponentType.PROCESSOR,
            factory=TextNormaliserAgent,
            composable=True,
            description="Lowercase and trim whitespace.",
            config_model=ConfigModel(
                type="text-normaliser",
                properties=props(
                    ConfigProperty("make-lowercase", "lowercase text", type="boolean", default=True),
                    ConfigProperty("trim-spaces", "collapse/trim whitespace", type="boolean", default=True),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="document-to-json",
            component_type=ComponentType.PROCESSOR,
            factory=DocumentToJsonAgent,
            composable=True,
            description="Wrap raw text into a JSON document.",
            config_model=ConfigModel(
                type="document-to-json",
                properties=props(
                    ConfigProperty("text-field", "field name for the text", default="text"),
                    ConfigProperty("copy-properties", "copy headers into the JSON", type="boolean", default=True),
                ),
            ),
        )
    )


_register()
