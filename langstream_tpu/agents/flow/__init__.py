"""Registered on import; see sibling modules."""
