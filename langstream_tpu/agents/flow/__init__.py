"""Flow-control agents.

Parity: reference `langstream-agents-flow-control` (SURVEY §2.5):
`dispatch` (EL-routed fan-out, flow/DispatchAgent.java), `timer-source`
(TimerSource.java), `trigger-event` (TriggerEventProcessor.java),
`log-event` (LogEventProcessor.java). Conditions and field expressions use
the same whitelisted EL as the GenAI toolkit.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any

from langstream_tpu.agents.genai import el
from langstream_tpu.agents.genai.mutable import MutableRecord
from langstream_tpu.api.agent import (
    AgentSource,
    ComponentType,
    SingleRecordProcessor,
)
from langstream_tpu.api.doc import ConfigModel, ConfigProperty, props
from langstream_tpu.api.record import Header, Record, SimpleRecord
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo
from langstream_tpu.runtime.topic_adapters import DESTINATION_HEADER

log = logging.getLogger(__name__)


class DispatchAgent(SingleRecordProcessor):
    """`dispatch`: route each record to the first matching route's
    destination topic; `action: drop` routes discard; non-matching records
    pass through to the default output (reference DispatchAgent)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.routes = list(configuration.get("routes", []))

    async def process_record(self, record: Record) -> list[Record]:
        ctx = MutableRecord.from_record(record)
        self.processed(1)
        for route in self.routes:
            when = route.get("when")
            if when and not el.evaluate_bool(when, ctx):
                continue
            action = route.get("action", "dispatch")
            if action == "drop":
                return []
            destination = route.get("destination")
            if destination:
                headers = tuple(
                    h for h in record.headers if h.key != DESTINATION_HEADER
                ) + (Header(DESTINATION_HEADER, destination),)
                return [SimpleRecord.copy_from(record, headers=headers)]
            return [record]
        return [record]


class TimerSource(AgentSource):
    """`timer-source`: emit one record every `period-seconds`, with fields
    computed by EL expressions (reference TimerSource.java)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.period = float(configuration.get("period-seconds", 60))
        self.fields = list(configuration.get("fields", []))
        self._next_fire = time.monotonic()

    async def read(self) -> list[Record]:
        now = time.monotonic()
        if now < self._next_fire:
            await asyncio.sleep(min(self.period / 20.0, self._next_fire - now))
            return []
        self._next_fire = now + self.period
        ctx = MutableRecord(value={}, timestamp=time.time())
        for f in self.fields:
            ctx.set_field(f.get("name", "value.field"), el.evaluate(f.get("expression", "None"), ctx))
        if not ctx.value:
            ctx.value = {"fired-at": time.time()}
        self.processed(1)
        out = ctx.to_record()
        return [SimpleRecord.copy_from(out, origin="timer-source")]


class TriggerEventProcessor(SingleRecordProcessor):
    """`trigger-event`: when `when` matches, emit a synthetic event record to
    `destination`; `continue-processing` controls whether the original record
    also flows on (reference TriggerEventProcessor.java)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.when = configuration.get("when")
        self.destination = configuration.get("destination", "")
        self.fields = list(configuration.get("fields", []))
        self.continue_processing = bool(configuration.get("continue-processing", True))

    async def process_record(self, record: Record) -> list[Record]:
        ctx = MutableRecord.from_record(record)
        self.processed(1)
        if self.when and not el.evaluate_bool(self.when, ctx):
            return [record]
        event = MutableRecord(value={}, timestamp=time.time())
        for f in self.fields:
            event.set_field(f.get("name", "value.event"), el.evaluate(f.get("expression", "None"), ctx))
        out = event.to_record()
        if self.destination:
            out = SimpleRecord.copy_from(
                out,
                headers=tuple(h for h in out.headers if h.key != DESTINATION_HEADER)
                + (Header(DESTINATION_HEADER, self.destination),),
            )
        return [out, record] if self.continue_processing else [out]


class LogEventProcessor(SingleRecordProcessor):
    """`log-event`: log matching records (with EL-computed fields), pass all
    records through unchanged (reference LogEventProcessor.java)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.when = configuration.get("when")
        self.message = configuration.get("message", "")
        self.fields = list(configuration.get("fields", []))

    async def process_record(self, record: Record) -> list[Record]:
        ctx = MutableRecord.from_record(record)
        self.processed(1)
        if self.when is None or el.evaluate_bool(self.when, ctx):
            extra = {
                f.get("name", f"field{i}"): el.evaluate(f.get("expression", "None"), ctx)
                for i, f in enumerate(self.fields)
            }
            log.info("log-event %s: value=%r %s", self.message, record.value, extra)
        return [record]


def _register() -> None:
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="dispatch",
            component_type=ComponentType.PROCESSOR,
            factory=DispatchAgent,
            composable=False,  # routing must reach the real sink, not a fused peer
            description="Route records to topics by EL conditions.",
            config_model=ConfigModel(
                type="dispatch",
                properties=props(
                    ConfigProperty("routes", "list of {when, destination, action}", type="array"),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="timer-source",
            component_type=ComponentType.SOURCE,
            factory=TimerSource,
            description="Emit a record on a fixed period.",
            config_model=ConfigModel(
                type="timer-source",
                properties=props(
                    ConfigProperty("period-seconds", "emission period", type="number", default=60),
                    ConfigProperty("fields", "list of {name, expression}", type="array"),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="trigger-event",
            component_type=ComponentType.PROCESSOR,
            factory=TriggerEventProcessor,
            composable=False,
            description="Emit a synthetic event record when a condition matches.",
            config_model=ConfigModel(
                type="trigger-event",
                properties=props(
                    ConfigProperty("when", "EL condition"),
                    ConfigProperty("destination", "topic for the event record"),
                    ConfigProperty("fields", "list of {name, expression}", type="array"),
                    ConfigProperty("continue-processing", "forward the original record", type="boolean", default=True),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="log-event",
            component_type=ComponentType.PROCESSOR,
            factory=LogEventProcessor,
            composable=True,
            description="Log matching records; pass-through.",
            config_model=ConfigModel(
                type="log-event",
                properties=props(
                    ConfigProperty("when", "EL condition"),
                    ConfigProperty("message", "log message prefix"),
                    ConfigProperty("fields", "list of {name, expression}", type="array"),
                ),
            ),
        )
    )


_register()
