"""L4 — built-in agent library. Importing this package registers every
built-in agent type into `core.registry.REGISTRY` (the ServiceLoader/NAR
equivalent of the reference's META-INF/services discovery, SURVEY §2.5)."""

from langstream_tpu.agents import builtin  # noqa: F401  (registration side effects)


def _register_all() -> None:
    # Each sub-module registers on import; keep imports in dependency order.
    from langstream_tpu import ai  # noqa: F401  (AI resource types)
    from langstream_tpu.agents import genai  # noqa: F401
    from langstream_tpu.agents import text  # noqa: F401
    from langstream_tpu.agents import flow  # noqa: F401
    from langstream_tpu.agents import http  # noqa: F401
    from langstream_tpu.agents import vector  # noqa: F401
    from langstream_tpu.agents import web  # noqa: F401
    from langstream_tpu.agents import storage  # noqa: F401
    from langstream_tpu.agents import python_agents  # noqa: F401
    from langstream_tpu.agents import connect  # noqa: F401


_register_all()
