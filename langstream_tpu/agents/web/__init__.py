"""Web crawler source.

Parity: reference `langstream-agent-webcrawler` (SURVEY §2.5):
`webcrawler-source` (WebCrawlerSource.java:461 + crawler/WebCrawler.java:493)
— seeded BFS crawl restricted to allowed domains, robots.txt respect,
politeness delay, and a **checkpointed crawl frontier** (visited set +
pending queue) persisted to the agent's state dir
(reference S3StatusStorage / LocalDiskStatusStorage, WebCrawlerSource.java:165-199).
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.robotparser
from typing import Any, Optional
from urllib.parse import urldefrag, urljoin, urlparse

import aiohttp

from langstream_tpu.api.agent import AgentSource, ComponentType
from langstream_tpu.api.doc import ConfigModel, ConfigProperty, props
from langstream_tpu.api.record import Record, SimpleRecord
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo


class CrawlState:
    """Visited/pending frontier with JSON checkpointing. Commit-safe: a URL
    moves from `emitted` to `visited` only when the runtime commits the
    record, so a crash re-crawls at-least-once (reference semantics)."""

    def __init__(self, path: Optional[str]) -> None:
        self.path = path
        self.pending: list[tuple[str, int]] = []  # (url, depth)
        self.visited: set[str] = set()
        self.emitted: set[str] = set()
        self.started_at = time.time()

    def load(self) -> bool:
        if not self.path:
            return False
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return False
        self.pending = [tuple(p) for p in data.get("pending", [])]
        self.visited = set(data.get("visited", []))
        # emitted-but-uncommitted URLs are re-crawled after restart
        self.pending = [(u, d) for u, d in self.pending] + [
            (u, 0) for u in data.get("emitted", []) if u not in self.visited
        ]
        self.started_at = data.get("started_at", time.time())
        return True

    def save(self) -> None:
        if not self.path:
            return
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "pending": list(self.pending),
                    "visited": sorted(self.visited),
                    "emitted": sorted(self.emitted),
                    "started_at": self.started_at,
                },
                f,
            )
        import os

        os.replace(tmp, self.path)


class WebCrawlerSource(AgentSource):
    """`webcrawler-source`: BFS crawl; one record per page (value = raw body,
    key = url, headers: url, content_type, depth)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.seed_urls = list(configuration.get("seed-urls", []))
        self.allowed_domains = list(configuration.get("allowed-domains", []))
        self.forbidden_paths = list(configuration.get("forbidden-paths", []))
        self.max_urls = int(configuration.get("max-urls", 1000))
        self.max_depth = int(configuration.get("max-depth", 50))
        self.min_time_between_requests = (
            float(configuration.get("min-time-between-requests", 500)) / 1000.0
        )
        self.user_agent = configuration.get("user-agent", "langstream-tpu-crawler")
        self.handle_robots = bool(configuration.get("handle-robots-file", True))
        self.http_timeout = float(configuration.get("http-timeout", 10000)) / 1000.0
        self.max_error_count = int(configuration.get("max-error-count", 5))
        self.reindex_interval = float(configuration.get("reindex-interval-seconds", 0))
        self._session: Optional[aiohttp.ClientSession] = None
        self._robots: dict[str, urllib.robotparser.RobotFileParser] = {}
        self._errors: dict[str, int] = {}
        self._last_request = 0.0
        self._state: Optional[CrawlState] = None

    async def start(self) -> None:
        state_path = None
        if self.context is not None:
            state_dir = self.context.get_persistent_state_directory()
            if state_dir is not None:
                state_path = str(state_dir / "webcrawler.status.json")
        self._state = CrawlState(state_path)
        if not self._state.load():
            self._state.pending = [(u, 0) for u in self.seed_urls]
        self._session = aiohttp.ClientSession(
            headers={"User-Agent": self.user_agent},
            timeout=aiohttp.ClientTimeout(total=self.http_timeout),
        )

    async def close(self) -> None:
        if self._state is not None:
            self._state.save()
        if self._session is not None:
            await self._session.close()

    # -- crawl policy -------------------------------------------------------

    def _domain_allowed(self, url: str) -> bool:
        host = urlparse(url).netloc.split(":")[0]
        if not self.allowed_domains:
            return True
        return any(host == d or host.endswith(f".{d}") for d in self.allowed_domains)

    def _path_allowed(self, url: str) -> bool:
        path = urlparse(url).path or "/"
        return not any(path.startswith(p) for p in self.forbidden_paths)

    async def _robots_allowed(self, url: str) -> bool:
        if not self.handle_robots:
            return True
        parsed = urlparse(url)
        origin = f"{parsed.scheme}://{parsed.netloc}"
        rp = self._robots.get(origin)
        if rp is None:
            rp = urllib.robotparser.RobotFileParser()
            assert self._session is not None
            try:
                async with self._session.get(f"{origin}/robots.txt") as resp:
                    if resp.status == 200:
                        rp.parse((await resp.text()).splitlines())
                    else:
                        rp.allow_all = True
            except (aiohttp.ClientError, asyncio.TimeoutError):
                rp.allow_all = True
            self._robots[origin] = rp
        return rp.can_fetch(self.user_agent, url)

    # -- source contract ----------------------------------------------------

    async def read(self) -> list[Record]:
        assert self._state is not None and self._session is not None
        state = self._state
        while state.pending:
            if len(state.visited) + len(state.emitted) >= self.max_urls:
                break
            url, depth = state.pending.pop(0)
            url = urldefrag(url)[0]
            if url in state.visited or url in state.emitted:
                continue
            if not (self._domain_allowed(url) and self._path_allowed(url)):
                continue
            if not await self._robots_allowed(url):
                continue
            # politeness delay
            wait = self.min_time_between_requests - (time.monotonic() - self._last_request)
            if wait > 0:
                await asyncio.sleep(wait)
            self._last_request = time.monotonic()
            try:
                async with self._session.get(url) as resp:
                    body = await resp.read()
                    content_type = resp.content_type
                    status = resp.status
            except (aiohttp.ClientError, asyncio.TimeoutError):
                self._errors[url] = self._errors.get(url, 0) + 1
                if self._errors[url] < self.max_error_count:
                    state.pending.append((url, depth))
                continue
            if status >= 400:
                state.visited.add(url)
                continue
            if "html" in content_type and depth < self.max_depth:
                for link in self._extract_links(url, body):
                    if link not in state.visited and link not in state.emitted:
                        state.pending.append((link, depth + 1))
            state.emitted.add(url)
            state.save()
            self.processed(1)
            return [
                SimpleRecord.of(
                    body,
                    key=url,
                    headers=[
                        ("url", url),
                        ("content_type", content_type),
                        ("depth", str(depth)),
                    ],
                    origin="webcrawler-source",
                )
            ]

        # frontier exhausted: optionally reindex after the interval
        if (
            self.reindex_interval > 0
            and not state.pending
            and time.time() - state.started_at > self.reindex_interval
        ):
            state.started_at = time.time()
            state.visited.clear()
            state.pending = [(u, 0) for u in self.seed_urls]
            state.save()
        await asyncio.sleep(0.05)
        return []

    def _extract_links(self, base: str, body: bytes) -> list[str]:
        from bs4 import BeautifulSoup

        try:
            soup = BeautifulSoup(body, "html.parser")
        except Exception:  # noqa: BLE001 — malformed HTML: just no links
            return []
        links = []
        for a in soup.find_all("a", href=True):
            link = urldefrag(urljoin(base, a["href"]))[0]
            if link.startswith(("http://", "https://")):
                links.append(link)
        return links

    async def commit(self, records: list[Record]) -> None:
        assert self._state is not None
        for r in records:
            url = str(r.key)
            self._state.emitted.discard(url)
            self._state.visited.add(url)
        self._state.save()

    def agent_info(self) -> dict[str, Any]:
        info = super().agent_info()
        if self._state is not None:
            info["crawl"] = {
                "pending": len(self._state.pending),
                "visited": len(self._state.visited),
                "in-flight": len(self._state.emitted),
            }
        return info


def _register() -> None:
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="webcrawler-source",
            component_type=ComponentType.SOURCE,
            factory=WebCrawlerSource,
            description="Crawl websites; one record per page; checkpointed frontier.",
            config_model=ConfigModel(
                type="webcrawler-source",
                properties=props(
                    ConfigProperty("seed-urls", "starting urls", type="array", required=True),
                    ConfigProperty("allowed-domains", "domain allowlist", type="array"),
                    ConfigProperty("forbidden-paths", "path prefixes to skip", type="array"),
                    ConfigProperty("max-urls", "crawl budget", type="integer", default=1000),
                    ConfigProperty("max-depth", "link depth limit", type="integer", default=50),
                    ConfigProperty("min-time-between-requests", "politeness delay (ms)", type="number", default=500),
                    ConfigProperty("user-agent", "User-Agent header", default="langstream-tpu-crawler"),
                    ConfigProperty("handle-robots-file", "respect robots.txt", type="boolean", default=True),
                    ConfigProperty("http-timeout", "request timeout (ms)", type="number", default=10000),
                    ConfigProperty("max-error-count", "retries per url", type="integer", default=5),
                    ConfigProperty("reindex-interval-seconds", "re-crawl period", type="number", default=0),
                ),
            ),
        )
    )


_register()
