"""GenAI toolkit agents (reference `langstream-ai-agents`, SURVEY §2.5)."""

from langstream_tpu.agents.genai.agent import (  # noqa: F401
    GenAIToolKitAgent,
    register_genai_agents,
)

register_genai_agents()
