"""`ai-chat-completions` / `ai-text-completions` steps.

Parity: reference `ChatCompletionsStep.java:42,115,137` and
`TextCompletionsStep.java` — prompt templates rendered per record, completion
via the resolved CompletionsService, streamed chunks written to
`stream-to-topic` with `stream-id`/`stream-index`/`stream-last-message`
properties BEFORE the final record commits (this is what gives the gateway
its TTFT), final answer into `completion-field`, request metadata into
`log-field`.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from langstream_tpu.agents.genai import el
from langstream_tpu.agents.genai.mutable import MutableRecord
from langstream_tpu.agents.genai.steps import Step
from langstream_tpu.ai.provider import ChatChunk, ChatMessage
from langstream_tpu.tracing import TRACE_HEADER, TRACER


def _set_result_field(record: MutableRecord, field: Optional[str], content: str) -> None:
    if field:
        record.set_field(field, content)
    else:
        record.value = content
        record._value_was_json = False


class _BaseCompletionsStep(Step):
    streaming_field_key = "stream-response-completion-field"

    def __init__(self, config: dict[str, Any]) -> None:
        super().__init__(config)
        self.model = config.get("model", "")
        self.completion_field = config.get("completion-field")
        self.log_field = config.get("log-field")
        self.stream_to_topic = config.get("stream-to-topic")
        self.stream_response_field = config.get(self.streaming_field_key)
        self.min_chunks = int(config.get("min-chunks-per-message", 20))
        self.ai_service = config.get("ai-service")
        self._producer = None
        self._service = None

    async def start(self, context: Any) -> None:
        registry = context.get_service_provider_registry()
        provider = registry.get_provider(self.ai_service)
        self._service = provider.get_completions_service(dict(self.config))
        if self.stream_to_topic:
            self._producer = context.get_topic_producer(self.stream_to_topic)
            await self._producer.start()
        # serving gauges (SURVEY §5: "same shape, plus tokens/sec, TTFT,
        # batch occupancy" — counters match the reference's
        # openai_*_num_calls_total naming scheme)
        # per-agent scope (multiple completions agents share one registry)
        metrics = context.get_metrics_reporter().with_prefix(
            f"agent_{context.get_global_agent_id()}_completions"
        )
        self._m_calls = metrics.counter("num_calls_total", "completion calls")
        self._m_tokens = metrics.counter("completion_tokens_total", "generated tokens")
        self._m_prompt = metrics.counter("prompt_tokens_total", "prompt tokens")
        self._m_ttft = metrics.gauge("last_ttft_ms", "last time-to-first-token")
        self._m_rate = metrics.gauge("last_tokens_per_sec", "last request decode rate")
        self._m_active = metrics.gauge("engine_active_slots", "busy KV-cache slots")
        self._m_queued = metrics.gauge("engine_queued_requests", "requests waiting for a slot")
        self._m_hbm = metrics.gauge(
            "engine_hbm_gbps", "achieved HBM read bandwidth per decode step"
        )
        self._m_step = metrics.gauge(
            "engine_decode_step_ms", "measured decode step time (EMA)"
        )
        self._m_programs = metrics.gauge(
            "engine_compiled_programs",
            "distinct device programs dispatched (growth after warmup = "
            "a mid-traffic XLA compile stall)",
        )
        # prefix KV reuse (serving/prefix_cache.py) — all sourced from the
        # engine's cumulative stats, so gauges (not counters) carry them
        self._m_prefix_hit = metrics.gauge(
            "engine_prefix_cache_hit_rate",
            "fraction of admissions that reused a cached prompt prefix",
        )
        self._m_prefix_saved = metrics.gauge(
            "engine_prefill_tokens_saved_total",
            "prompt tokens NOT re-prefilled thanks to prefix KV reuse "
            "(cumulative)",
        )
        self._m_prefix_bytes = metrics.gauge(
            "engine_prefix_pool_bytes_in_use",
            "device HBM held by live prefix-cache entries",
        )
        self._m_prefix_evict = metrics.gauge(
            "engine_prefix_cache_evictions_total",
            "prefix-cache LRU evictions (cumulative)",
        )
        # self-speculative decoding (serving/engine.py _verify_chunk):
        # engine-cumulative ratios, so gauges carry them like the prefix set
        self._m_spec_accept = metrics.gauge(
            "engine_spec_acceptance_rate",
            "fraction of proposed draft tokens the model accepted "
            "(speculative decoding; 0 when off)",
        )
        self._m_spec_per_step = metrics.gauge(
            "engine_spec_accepted_tokens_per_step",
            "tokens emitted per verify dispatch (each dispatch = ONE weight "
            "read; 1.0 means speculation is buying nothing)",
        )
        self._m_spec_hit = metrics.gauge(
            "engine_spec_draft_hit_rate",
            "fraction of draft lookups where the n-gram index had a proposal",
        )
        # unified paged KV pool (serving/pagepool.py): live pool pressure,
        # aliasing effectiveness, and the copy traffic aliasing eliminated
        self._m_kv_pages = metrics.gauge(
            "engine_kv_pages_in_use",
            "physical KV pages currently allocated (paged layout; 0 dense)",
        )
        self._m_kv_alias = metrics.gauge(
            "engine_kv_page_alias_rate",
            "fraction of reserved KV pages satisfied by prefix aliasing "
            "instead of fresh allocation (cumulative; 0 when dense)",
        )
        self._m_prefix_copy_saved = metrics.gauge(
            "engine_prefix_copy_bytes_saved_total",
            "bytes of KV copy eliminated by page aliasing vs the dense "
            "gather-per-hit design (cumulative)",
        )
        # tiered KV: host-RAM spill + session hibernation (serving/
        # pagepool.HostPageTier, docs/SERVING.md §16) — arena occupancy,
        # spill/restore byte traffic, and the restore-vs-recompute split
        self._m_host_pages_total = metrics.gauge(
            "engine_host_pages_total",
            "host-tier KV arena capacity in pages (0 with the tier off)",
        )
        self._m_host_pages = metrics.gauge(
            "engine_host_pages_in_use",
            "host-tier arena pages holding hibernated prefix KV",
        )
        self._m_spill_bytes = metrics.gauge(
            "engine_spill_bytes_total",
            "KV bytes spilled device→host (hibernation), cumulative",
        )
        self._m_restore_bytes = metrics.gauge(
            "engine_restore_bytes_total",
            "KV bytes restored host→device (session wake), cumulative",
        )
        self._m_restored_hits = metrics.gauge(
            "engine_restored_hits_total",
            "warm admissions served by a host-tier restore instead of a "
            "re-prefill, cumulative",
        )
        self._m_recompute_fallbacks = metrics.gauge(
            "engine_recompute_fallbacks_total",
            "host-tier hits that fell back to recompute (failed/corrupt/"
            "no-room restore), cumulative",
        )
        # request lifecycle / fault recovery (serving/engine.py): sourced
        # from the engine's cumulative stats, gauges like the prefix set
        self._m_shed = metrics.gauge(
            "engine_shed_total",
            "requests shed at admission (full queue / hopeless deadline / "
            "draining), cumulative",
        )
        self._m_deadline = metrics.gauge(
            "engine_deadline_exceeded_total",
            "requests past their deadline (in queue or mid-decode), cumulative",
        )
        self._m_cancelled = metrics.gauge(
            "engine_cancelled_total",
            "requests cancelled (client disconnect / timeout), cumulative",
        )
        self._m_quarantined = metrics.gauge(
            "engine_quarantined_slots_total",
            "slots failed by device faults or the NaN-logits guard, cumulative",
        )
        self._m_restarts = metrics.gauge(
            "engine_restarts_total",
            "engine-loop restarts after a crash (bounded-backoff recovery), "
            "cumulative",
        )
        # SPMD slice resilience (parallel/spmd_serving.py, docs/SERVING.md
        # §20): coordinated recover-in-place epochs, divergence resyncs
        # and watchdog escalations — zeros single-host, gauges like the
        # lifecycle set above
        self._m_spmd_recoveries = metrics.gauge(
            "engine_spmd_recoveries_total",
            "coordinated SPMD recoveries (leader crash -> OP_RECOVER, both "
            "sides rebuilt in place, zero process exits), cumulative",
        )
        self._m_spmd_epoch = metrics.gauge(
            "engine_spmd_recovery_epoch",
            "current SPMD recovery epoch (bumped per coordinated recovery "
            "or divergence resync; 0 = never recovered)",
        )
        self._m_spmd_resyncs = metrics.gauge(
            "engine_spmd_resyncs_total",
            "coordinated divergence resyncs granted (OP_RESYNC answered a "
            "follower's echo-mismatch/seq-gap report), cumulative",
        )
        self._m_spmd_watchdog = metrics.gauge(
            "engine_spmd_watchdog_trips_total",
            "leader-side watchdog escalations (a wedged iteration's fetch "
            "exceeded spmd-watchdog-s and forced OP_RECOVER), cumulative",
        )
        # the agentic serving tier (serving/adapters.py + constrain.py,
        # docs/SERVING.md §15): adapter residency/swap pressure and the
        # constrained-decoding volume + host-side mask overhead
        self._m_adapters_resident = metrics.gauge(
            "engine_adapters_resident",
            "LoRA adapters currently resident in the device pool",
        )
        self._m_adapter_swaps = metrics.gauge(
            "engine_adapter_swaps_total",
            "adapter hot-swaps onto the device (LRU residency misses), "
            "cumulative — sustained growth means the pool is too small",
        )
        self._m_constrained = metrics.gauge(
            "engine_constrained_requests_total",
            "requests decoded under a response_format grammar, cumulative",
        )
        self._m_constrain_overhead = metrics.gauge(
            "engine_constrain_overhead_ms",
            "host-side constrained-decoding bookkeeping per dispatch "
            "(grammar swaps + verify state tables), EMA ms",
        )
        self._m_grammar_pool_bytes = metrics.gauge(
            "engine_grammar_pool_bytes",
            "HBM held by the packed grammar pool (bitmask + default/"
            "exception planes across all slots), bytes",
        )
        self._m_grammar_rows = metrics.gauge(
            "engine_grammar_rows_resident",
            "grammars currently resident in the device pool (swap "
            "pressure shows in engine_grammar_swaps via stats)",
        )
        # multi-tenant overload control (serving/tenancy.py, docs/
        # SERVING.md §19): cross-tenant shed volume, the worst tenant's
        # queue-wait EMA (the noisy-neighbor victim signal — per-tenant
        # detail lives in stats()["tenants"] and the fleet beacons), and
        # the brownout ladder level
        self._m_tenant_shed = metrics.gauge(
            "tenant_shed_total",
            "requests shed across ALL tenants (quota, queue share, "
            "brownout, overload), cumulative — per-tenant split in "
            "engine stats and beacons",
        )
        self._m_tenant_wait = metrics.gauge(
            "tenant_queue_wait",
            "WORST per-tenant queue-wait EMA (s) — the noisy-neighbor "
            "victim signal; flat while the aggregate climbs means "
            "isolation is holding",
        )
        self._m_brownout_level = metrics.gauge(
            "brownout_level",
            "brownout degradation-ladder level (0 normal, 1 spec-shrink, "
            "2 spec-off, 3 reject-low, 4 reject-quota)",
        )
        self._m_brownout_transitions = metrics.gauge(
            "brownout_transitions_total",
            "brownout ladder transitions (either direction), cumulative",
        )
        # observability layer (serving/observability.py, docs/SERVING.md
        # §12): the engine-derived load score the replica balancer routes
        # on, the flight-recorder dump counter, and the full streaming-
        # latency histogram set. The engine owns the live histograms; the
        # exporter MIRRORS their snapshots into the Prometheus registry so
        # /metrics carries real _bucket/_sum/_count series (the Grafana
        # TTFT heatmap reads them).
        self._m_load = metrics.gauge(
            "engine_load_score",
            "queue-wait p90 (s) + slot occupancy + page-pool pressure — "
            "relative load signal for cache-aware replica balancing",
        )
        self._m_flight_dumps = metrics.gauge(
            "engine_flight_dumps_total",
            "flight-recorder postmortem dumps produced (quarantines, "
            "restarts, shed bursts, on-demand), cumulative",
        )
        # fleet routing tier (serving/fleet.py, docs/SERVING.md §13):
        # router-cumulative counters carried as gauges like the engine
        # sets; zeros while fleet: off so the exporter is unconditional
        self._m_fleet_affinity = metrics.gauge(
            "fleet_routed_affinity_total",
            "requests routed by prefix affinity (incl. sticky sessions) — "
            "the cache-aware hits, cumulative",
        )
        self._m_fleet_balanced = metrics.gauge(
            "fleet_routed_balanced_total",
            "requests routed by load only (no usable prefix anywhere), "
            "cumulative",
        )
        self._m_fleet_replicas = metrics.gauge(
            "fleet_replica_count",
            "replicas the fleet router fronts (routable or not)",
        )
        # fleet wire hardening (docs/SERVING.md §17): mid-stream warm
        # failovers, the per-replica circuit breaker, beacon probe health,
        # and the remote-hop latency histogram (mirrored from the router
        # the same way the engine histograms are)
        self._m_fleet_stream_failovers = metrics.gauge(
            "fleet_stream_failovers_total",
            "mid-STREAM warm failovers — a replica died after delivering "
            "tokens and the router resumed on a survivor, cumulative",
        )
        self._m_fleet_circuit_open = metrics.gauge(
            "fleet_circuit_open_total",
            "per-replica circuit-breaker OPEN transitions (consecutive "
            "beacon/dispatch failures past the threshold), cumulative",
        )
        self._m_fleet_beacon_failures = metrics.gauge(
            "fleet_beacon_failures_total",
            "beacon (/state) fetch failures across the fleet — sustained "
            "growth on one replica means its probe is in backoff, "
            "cumulative",
        )
        # disaggregated prefill/decode (serving/migrate.py + fleet.py,
        # docs/SERVING.md §18): KV-page migration traffic and the
        # decode-in-place fallback counter — a rising fallback share
        # means the migration wire (or the decode pool) is unhealthy
        self._m_fleet_migrations = metrics.gauge(
            "fleet_migrations_total",
            "completed KV-page migrations (receiver-ACKed, sender "
            "released), cumulative",
        )
        self._m_fleet_migrate_pages = metrics.gauge(
            "fleet_pages_migrated_total",
            "KV pages moved between replicas by completed migrations, "
            "cumulative",
        )
        self._m_fleet_migrate_bytes = metrics.gauge(
            "fleet_migrate_bytes_total",
            "bytes moved between replicas by completed migrations "
            "(int8 pools ship half the bf16 bytes), cumulative",
        )
        self._m_fleet_migrate_fallbacks = metrics.gauge(
            "fleet_migrate_fallbacks_total",
            "migrations that failed (checksum, cut, deadline, exhaustion) "
            "and fell back to decode-in-place, cumulative",
        )
        # binary fleet wire v2 + P2P page fetch (docs/SERVING.md §21):
        # bytes on the replica-to-replica wire by protocol (the v1-vs-v2
        # overhead pair), and the radix-miss fetch outcomes — a rising
        # fallback share means the P2P wire (or the owners' arenas) is
        # unhealthy while requests silently re-prefill cold
        self._m_fleet_wire_bytes = {
            proto: metrics.gauge(
                "fleet_wire_bytes_total",
                "bytes written to the replica-to-replica fleet wire by "
                "protocol (v1 NDJSON vs v2 binary), sender-side, "
                "cumulative",
                labels={"proto": proto},
            )
            for proto in ("v1", "v2")
        }
        self._m_fleet_p2p_fetch = metrics.gauge(
            "fleet_p2p_fetch_total",
            "peer-to-peer page fetches that bound warm on a radix miss "
            "(owner kept its copy), cumulative",
        )
        self._m_fleet_p2p_fallback = metrics.gauge(
            "fleet_p2p_fetch_fallback_total",
            "peer-to-peer page fetches that failed (checksum, net-cut, "
            "deadline, no capable peer) and re-prefilled locally, "
            "cumulative",
        )
        self._m_fleet_p2p_bytes_in = metrics.gauge(
            "fleet_p2p_bytes_in_total",
            "page bytes admitted from peers by completed P2P fetches "
            "(receiver-ACKed), cumulative",
        )
        # durable session tier (serving/durable.py, docs/SERVING.md §23):
        # disk checkpoint/restore volume plus the two failure modes an
        # operator alerts on — restore failures (rot, torn writes) and
        # dead entries (checkpoints discarded as unreadable). All
        # engine-cumulative, gauges like the spill set above.
        self._m_durable_entries = metrics.gauge(
            "durable_entries",
            "session checkpoints resident in the durable tier's on-disk "
            "index right now",
        )
        self._m_durable_bytes = metrics.gauge(
            "durable_bytes_on_disk",
            "bytes the durable tier currently holds on disk (frame "
            "streams + manifests)",
        )
        self._m_durable_checkpoints = metrics.gauge(
            "durable_checkpoints_total",
            "session checkpoints durably committed (temp+fsync+rename "
            "landed), cumulative",
        )
        self._m_durable_ckpt_bytes = metrics.gauge(
            "durable_checkpoint_bytes_total",
            "bytes durably committed by session checkpoints, cumulative",
        )
        self._m_durable_restores = metrics.gauge(
            "durable_restores_total",
            "sessions resurrected from the durable tier (disk → device "
            "bind verified), cumulative",
        )
        self._m_durable_restore_bytes = metrics.gauge(
            "durable_restore_bytes_total",
            "bytes read back by durable-tier restores, cumulative",
        )
        self._m_durable_restore_failures = metrics.gauge(
            "durable_restore_failures_total",
            "durable restores that failed (torn frame, checksum "
            "mismatch, stall, dead entry) and degraded to local cold "
            "prefill, cumulative",
        )
        self._m_durable_dead = metrics.gauge(
            "durable_dead_entries_total",
            "checkpoints discarded as unreadable (torn write, rot, "
            "missing manifest), cumulative",
        )
        # prefetch-on-hint (§23): beacon-driven warm fetches issued ahead
        # of request routing, router-cumulative like the P2P set
        self._m_fleet_prefetch = metrics.gauge(
            "fleet_prefetch_total",
            "prefetch hints accepted by the router (beacon said a deeper "
            "owner exists), cumulative",
        )
        self._m_fleet_prefetch_fetch = metrics.gauge(
            "fleet_prefetch_fetch_total",
            "prefetch hints that completed a P2P/durable page fetch "
            "before the request routed, cumulative",
        )
        self._m_fleet_cost_routed = metrics.gauge(
            "fleet_p2p_cost_routed_total",
            "P2P fetch decisions made by the bytes-vs-prefill cost model "
            "(rather than the flat threshold floor), cumulative",
        )
        self._m_weight_load_s = metrics.gauge(
            "weight_load_s",
            "checkpoint→device weight load wall time for this engine "
            "build (read + transform + transfer, s); the cold-start drill "
            "compares streamed vs eager on this gauge",
        )
        self._m_weight_load_bytes = metrics.gauge(
            "weight_load_bytes_total",
            "checkpoint bytes read by the engine weight load (streamed: "
            "summed tensor spans; eager: materialized tree bytes)",
        )
        from langstream_tpu.serving.observability import (
            ENGINE_HISTOGRAMS,
            FLEET_HISTOGRAMS,
        )

        self._m_hists = {
            name: metrics.histogram(name, spec["help"], spec["buckets"])
            for name, spec in ENGINE_HISTOGRAMS.items()
        }
        self._m_fleet_hists = {
            name: metrics.histogram(name, spec["help"], spec["buckets"])
            for name, spec in FLEET_HISTOGRAMS.items()
        }

    def _record_metrics(self, result: Any) -> None:
        self._m_calls.count()
        self._m_tokens.count(result.completion_tokens)
        self._m_prompt.count(result.prompt_tokens)
        ttft_ms = result.ttft_ms or 0.0
        if ttft_ms:
            self._m_ttft.set(round(ttft_ms, 3))
        decode_s = max((result.total_ms or 0.0) - ttft_ms, 0.0) / 1000.0
        if decode_s > 0 and result.completion_tokens:
            self._m_rate.set(round(result.completion_tokens / decode_s, 2))
        # batch occupancy (SURVEY §5): engine-backed services report slots
        stats = getattr(self._service, "engine_stats", lambda: None)() or {}
        # always set: stale occupancy must decay to 0, not freeze
        self._m_active.set(stats.get("active-slots", 0))
        self._m_queued.set(stats.get("queued", 0))
        self._m_hbm.set(stats.get("hbm-gbps-decode", 0))
        self._m_step.set(stats.get("decode-step-ms", 0))
        self._m_programs.set(stats.get("compiled_programs", 0))
        self._m_prefix_hit.set(stats.get("prefix-cache-hit-rate", 0))
        self._m_prefix_saved.set(stats.get("prefill-tokens-saved-total", 0))
        self._m_prefix_bytes.set(stats.get("prefix-pool-bytes-in-use", 0))
        self._m_prefix_evict.set(stats.get("prefix-cache-evictions-total", 0))
        self._m_spec_accept.set(stats.get("spec-acceptance-rate", 0))
        self._m_spec_per_step.set(stats.get("spec-accepted-tokens-per-step", 0))
        self._m_spec_hit.set(stats.get("spec-draft-hit-rate", 0))
        self._m_kv_pages.set(stats.get("kv-pages-in-use", 0))
        self._m_kv_alias.set(stats.get("kv-page-alias-rate", 0))
        self._m_prefix_copy_saved.set(stats.get("prefix-copy-bytes-saved-total", 0))
        self._m_host_pages_total.set(stats.get("host-pages-total", 0))
        self._m_host_pages.set(stats.get("host-pages-in-use", 0))
        self._m_spill_bytes.set(stats.get("spill-bytes-total", 0))
        self._m_restore_bytes.set(stats.get("restore-bytes-total", 0))
        self._m_restored_hits.set(stats.get("restored-hits-total", 0))
        self._m_recompute_fallbacks.set(stats.get("recompute-fallbacks-total", 0))
        self._m_shed.set(stats.get("shed-total", 0))
        self._m_deadline.set(stats.get("deadline-exceeded-total", 0))
        self._m_cancelled.set(stats.get("cancelled-total", 0))
        self._m_quarantined.set(stats.get("quarantined-slots-total", 0))
        self._m_restarts.set(stats.get("engine-restarts-total", 0))
        self._m_spmd_recoveries.set(stats.get("spmd-recoveries-total", 0))
        self._m_spmd_epoch.set(stats.get("spmd-recovery-epoch", 0))
        self._m_spmd_resyncs.set(stats.get("spmd-resyncs-total", 0))
        self._m_spmd_watchdog.set(stats.get("spmd-watchdog-trips-total", 0))
        self._m_adapters_resident.set(stats.get("adapters-resident", 0))
        self._m_adapter_swaps.set(stats.get("adapter-swaps-total", 0))
        self._m_constrained.set(stats.get("constrained-requests-total", 0))
        self._m_constrain_overhead.set(stats.get("constrain-overhead-ms", 0))
        self._m_grammar_pool_bytes.set(stats.get("grammar-pool-bytes", 0))
        self._m_grammar_rows.set(stats.get("grammars-resident", 0))
        tenants = stats.get("tenants") or {}
        self._m_tenant_shed.set(
            sum(int(t.get("shed-total", 0)) for t in tenants.values())
        )
        self._m_tenant_wait.set(
            max(
                (
                    float(t.get("queue-wait-ema-s", 0.0))
                    for t in tenants.values()
                ),
                default=0.0,
            )
        )
        self._m_brownout_level.set(stats.get("brownout-level", 0))
        self._m_brownout_transitions.set(
            stats.get("brownout-transitions-total", 0)
        )
        self._m_load.set(stats.get("load-score", 0))
        self._m_flight_dumps.set(stats.get("flight-dumps-total", 0))
        self._m_weight_load_s.set(stats.get("weight-load-s", 0))
        self._m_weight_load_bytes.set(stats.get("weight-load-bytes-total", 0))
        self._m_durable_entries.set(stats.get("durable-entries", 0))
        self._m_durable_bytes.set(stats.get("durable-bytes-on-disk", 0))
        self._m_durable_checkpoints.set(
            stats.get("durable-checkpoints-total", 0)
        )
        self._m_durable_ckpt_bytes.set(
            stats.get("durable-checkpoint-bytes-total", 0)
        )
        self._m_durable_restores.set(stats.get("durable-restores-total", 0))
        self._m_durable_restore_bytes.set(
            stats.get("durable-restore-bytes-total", 0)
        )
        self._m_durable_restore_failures.set(
            stats.get("durable-restore-failures-total", 0)
        )
        self._m_durable_dead.set(stats.get("durable-dead-entries-total", 0))
        fleet = getattr(self._service, "fleet_stats", lambda: None)() or {}
        self._m_fleet_affinity.set(
            fleet.get("fleet-routed-affinity-total", 0)
            + fleet.get("fleet-routed-sticky-total", 0)
        )
        self._m_fleet_balanced.set(fleet.get("fleet-routed-balanced-total", 0))
        self._m_fleet_replicas.set(fleet.get("fleet-replica-count", 0))
        self._m_fleet_stream_failovers.set(
            fleet.get("fleet-stream-failovers-total", 0)
        )
        self._m_fleet_circuit_open.set(fleet.get("fleet-circuit-open-total", 0))
        self._m_fleet_beacon_failures.set(
            fleet.get("fleet-beacon-failures-total", 0)
        )
        self._m_fleet_migrations.set(fleet.get("fleet-migrations-total", 0))
        self._m_fleet_migrate_pages.set(
            fleet.get("fleet-migrate-pages-total", 0)
        )
        self._m_fleet_migrate_bytes.set(
            fleet.get("fleet-migrate-bytes-total", 0)
        )
        self._m_fleet_migrate_fallbacks.set(
            fleet.get("fleet-migrate-fallbacks-total", 0)
        )
        self._m_fleet_wire_bytes["v1"].set(
            fleet.get("fleet-wire-bytes-v1-total", 0)
        )
        self._m_fleet_wire_bytes["v2"].set(
            fleet.get("fleet-wire-bytes-v2-total", 0)
        )
        self._m_fleet_p2p_fetch.set(fleet.get("fleet-p2p-fetch-total", 0))
        self._m_fleet_p2p_fallback.set(
            fleet.get("fleet-p2p-fetch-fallback-total", 0)
        )
        self._m_fleet_p2p_bytes_in.set(
            fleet.get("fleet-p2p-bytes-in-total", 0)
        )
        self._m_fleet_prefetch.set(fleet.get("fleet-prefetch-total", 0))
        self._m_fleet_prefetch_fetch.set(
            fleet.get("fleet-prefetch-fetch-total", 0)
        )
        self._m_fleet_cost_routed.set(
            fleet.get("fleet-p2p-cost-routed-total", 0)
        )
        for name, snap in (stats.get("histograms") or {}).items():
            mirror = self._m_hists.get(name)
            if mirror is not None:
                try:
                    mirror.load(snap)
                except ValueError:  # bucket-spec drift — skip, don't crash
                    pass
        for name, snap in (fleet.get("histograms") or {}).items():
            mirror = self._m_fleet_hists.get(name)
            if mirror is not None:
                try:
                    mirror.load(snap)
                except ValueError:  # bucket-spec drift — skip, don't crash
                    pass

    async def close(self) -> None:
        if self._producer is not None:
            await self._producer.close()
            self._producer = None

    def _options(self) -> dict[str, Any]:
        opts = {
            k: self.config[k]
            for k in (
                "max-tokens", "temperature", "top-p", "top-k", "stop",
                "logit-bias", "user", "presence-penalty", "frequency-penalty",
                "options", "deadline", "max-queue-wait",
                # the agentic tier (docs/SERVING.md §15): per-request
                # adapter selection + structured-output grammar — these
                # MUST be forwarded or the documented knobs are dead code
                # (the round-8 whitelist lesson)
                "adapter", "response-format",
                # multi-tenant overload control (docs/SERVING.md §19):
                # the tenant/priority/cost-budget policy inputs — the
                # per-record tenant header overrides `tenant` in process()
                "tenant", "priority", "max-cost-tokens",
            )
            if self.config.get(k) is not None
        }
        opts["model"] = self.model
        opts["min-chunks-per-message"] = self.min_chunks
        return opts

    def _chunk_writer(
        self, record: MutableRecord, loop, futures: list,
        trace_id: Optional[str] = None,
    ) -> Any:
        """Returns a chunks_consumer that writes each chunk as its own record
        to the stream topic. May be invoked from the engine thread → schedule
        onto the agent event loop; the write futures are collected so
        process() can await them (chunks must not be silently lost)."""
        import asyncio

        step = self

        def consume(chunk: ChatChunk) -> None:
            copy = MutableRecord(
                key=record.key,
                value=record.value,
                properties=dict(record.properties),
                origin=record.origin,
                timestamp=record.timestamp,
                _key_was_json=record._key_was_json,
                _value_was_json=record._value_was_json,
            )
            copy.properties["stream-id"] = chunk.answer_id
            copy.properties["stream-index"] = str(chunk.index)
            copy.properties["stream-last-message"] = str(chunk.last).lower()
            if trace_id:
                # echo the trace id on every streamed chunk EXPLICITLY:
                # this callback runs on the engine thread, outside the
                # agent span context, so the producer's contextvars-based
                # stamping cannot reach it — without this the client-side
                # and engine-side traces never join (docs/SERVING.md §12)
                copy.properties.setdefault(TRACE_HEADER, trace_id)
            _set_result_field(copy, step.stream_response_field, chunk.content)
            out = copy.to_record()
            if step._producer is not None:
                futures.append(
                    asyncio.run_coroutine_threadsafe(step._producer.write(out), loop)
                )

        return consume

    async def process(self, record: MutableRecord, context: Any) -> None:
        import asyncio

        assert self._service is not None, "step not started"
        options = self._options()
        # client-disconnect cancellation: hand the record's chat session id
        # to the service so the gateway's ClientDisconnected handler can
        # cancel the in-flight generation (serving/lifecycle.py; only the
        # tpu-serving provider acts on it, remote providers ignore it)
        from langstream_tpu.serving.lifecycle import SESSION_HEADER
        from langstream_tpu.serving.tenancy import TENANT_HEADER

        session_id = record.properties.get(SESSION_HEADER)
        if session_id:
            options["cancel-key"] = str(session_id)
        # multi-tenant overload control (docs/SERVING.md §19): the record's
        # gateway-stamped tenant header is the per-request truth — it wins
        # over any static `tenant` in the step config (the gateway already
        # resolved client-header-vs-path precedence at the front door)
        record_tenant = record.properties.get(TENANT_HEADER)
        if record_tenant:
            options["tenant"] = str(record_tenant)
        # trace propagation: the record's gateway-stamped ls-trace-id (or
        # the agent span the runner opened for this batch) rides into the
        # GenerationRequest AND back out on every streamed chunk, so the
        # gateway→engine→fetch path stitches into ONE trace on /traces
        trace_id = record.properties.get(TRACE_HEADER) or TRACER.current_trace_id()
        if trace_id:
            options["trace-id"] = str(trace_id)
        chunks_consumer = None
        chunk_futures: list = []
        if self.stream_to_topic:
            chunks_consumer = self._chunk_writer(
                record, asyncio.get_running_loop(), chunk_futures,
                trace_id=str(trace_id) if trace_id else None,
            )
        try:
            result = await self._complete(record, options, chunks_consumer)
        except RuntimeError as shed:
            # quota/overload shed (engine ShedError / mapped fleet shed:
            # any RuntimeError carrying retry_after_s). On a SERVICE
            # gateway request/reply roundtrip, answer the caller with a
            # shed REPLY record instead of erroring the pipeline — the
            # gateway maps the properties to HTTP 429 + Retry-After
            # (docs/SERVING.md §19). Topic-driven flows keep the raise:
            # their errors policy (retry/dead-letter) owns the outcome.
            from langstream_tpu.serving.tenancy import (
                RETRY_AFTER_PROPERTY,
                SERVICE_REQUEST_ID_PROPERTY,
                SHED_PROPERTY,
            )

            retry_after = getattr(shed, "retry_after_s", None)
            if (
                retry_after is None
                or not record.properties.get(SERVICE_REQUEST_ID_PROPERTY)
            ):
                raise
            record.properties[SHED_PROPERTY] = "true"
            record.properties[RETRY_AFTER_PROPERTY] = (
                f"{max(float(retry_after), 0.05):.3f}"
            )
            _set_result_field(record, self.completion_field, "")
            return
        self._record_metrics(result)
        if chunk_futures:
            # all chunks reach the stream topic before the final record commits
            await asyncio.gather(*(asyncio.wrap_future(f) for f in chunk_futures))
        _set_result_field(record, self.completion_field, result.content)
        if self.log_field:
            record.set_field(
                self.log_field,
                json.dumps({"model": self.model, "options": {k: v for k, v in options.items() if k != "options"}, "messages": self._log_messages(record)}),
            )

    # subclass hooks -------------------------------------------------------

    async def _complete(self, record, options, chunks_consumer):
        raise NotImplementedError

    def _log_messages(self, record: MutableRecord) -> Any:
        raise NotImplementedError


class ChatCompletionsStep(_BaseCompletionsStep):
    def _messages(self, record: MutableRecord) -> list[ChatMessage]:
        return [
            ChatMessage(
                role=m.get("role", "user"),
                content=el.render_template(m.get("content", ""), record),
            )
            for m in self.config.get("messages", [])
        ]

    async def _complete(self, record, options, chunks_consumer):
        return await self._service.get_chat_completions(
            self._messages(record), options, chunks_consumer
        )

    def _log_messages(self, record: MutableRecord) -> Any:
        return [{"role": m.role, "content": m.content} for m in self._messages(record)]


class TextCompletionsStep(_BaseCompletionsStep):
    streaming_field_key = "stream-response-completion-field"

    def _prompts(self, record: MutableRecord) -> list[str]:
        return [el.render_template(p, record) for p in self.config.get("prompt", [])]

    async def _complete(self, record, options, chunks_consumer):
        return await self._service.get_text_completions(
            self._prompts(record), options, chunks_consumer
        )

    def _log_messages(self, record: MutableRecord) -> Any:
        return self._prompts(record)
