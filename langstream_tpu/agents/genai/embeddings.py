"""`compute-ai-embeddings` step.

Parity: reference `ComputeAIEmbeddingsStep.java:46,70-102` — renders the
`text` template per record, computes embeddings via the resolved
EmbeddingsService, writes the vector into `embeddings-field`. The reference
batches via OrderedAsyncBatchExecutor (`batch-size`/`flush-interval`); here
the whole `process()` batch goes to the service in one call (the TPU provider
does its own device-side batching), with `loop-over` support for embedding a
list of sub-documents in one record.
"""

from __future__ import annotations

from typing import Any

from langstream_tpu.agents.genai import el
from langstream_tpu.agents.genai.mutable import MutableRecord
from langstream_tpu.agents.genai.steps import Step


class ComputeAIEmbeddingsStep(Step):
    def __init__(self, config: dict[str, Any]) -> None:
        super().__init__(config)
        self.text_template = config.get("text", "{{ value }}")
        self.embeddings_field = config.get("embeddings-field", "embeddings")
        self.loop_over = config.get("loop-over")
        self.ai_service = config.get("ai-service")
        self._service = None

    async def start(self, context: Any) -> None:
        registry = context.get_service_provider_registry()
        provider = registry.get_provider(self.ai_service)
        self._service = provider.get_embeddings_service(dict(self.config))

    async def process(self, record: MutableRecord, context: Any) -> None:
        assert self._service is not None, "step not started"
        if self.loop_over:
            items = el.evaluate(self.loop_over, record) or []
            texts = [
                el.render_template(self.text_template, record, extra={"record": item})
                for item in items
            ]
            if not texts:
                return
            vectors = await self._service.compute_embeddings(texts)
            # embeddings-field is relative to each item ("record.embeddings")
            field = self.embeddings_field
            if field.startswith("record."):
                field = field[len("record."):]
            for item, vec in zip(items, vectors):
                if isinstance(item, dict):
                    item[field] = vec
        else:
            text = el.render_template(self.text_template, record)
            vectors = await self._service.compute_embeddings([text])
            record.set_field(self.embeddings_field, vectors[0])
