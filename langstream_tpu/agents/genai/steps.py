"""GenAI toolkit transform steps: compute, cast, drop, drop-fields, flatten,
merge-key-value, unwrap-key-value.

Parity: reference step implementations behind
`GenAIToolKitFunctionAgentProvider.java:53-85` (planner-side types) and the
ai-agents step classes; behavior follows the documented semantics, expressed
over our MutableRecord/EL instead of the Java transform library.
Every step honours the base-config `when` condition
(BaseGenAIStepConfiguration.java:36).
"""

from __future__ import annotations

import abc
import json
from typing import Any, Optional

from langstream_tpu.agents.genai import el
from langstream_tpu.agents.genai.mutable import MutableRecord


class Step(abc.ABC):
    """One transform applied in-place to a MutableRecord."""

    def __init__(self, config: dict[str, Any]) -> None:
        self.config = config
        self.when: Optional[str] = config.get("when")

    def applies(self, record: MutableRecord) -> bool:
        if not self.when:
            return True
        return el.evaluate_bool(self.when, record)

    async def apply(self, record: MutableRecord, context: Any) -> None:
        if self.applies(record):
            await self.process(record, context)

    @abc.abstractmethod
    async def process(self, record: MutableRecord, context: Any) -> None: ...

    async def start(self, context: Any) -> None:  # noqa: B027
        pass

    async def close(self) -> None:  # noqa: B027
        pass


def _cast_scalar(val: Any, type_: str) -> Any:
    if val is None:
        return None
    t = type_.upper()
    if t in ("STRING", "TEXT"):
        return el._to_str(val)
    if t in ("INT8", "INT16", "INT32", "INT64", "INT", "LONG"):
        return int(float(val))
    if t in ("FLOAT", "DOUBLE"):
        return float(val)
    if t in ("BOOLEAN", "BOOL"):
        if isinstance(val, str):
            return val.strip().lower() in ("true", "1", "yes")
        return bool(val)
    if t == "BYTES":
        return el._to_str(val).encode()
    if t in ("ARRAY", "LIST"):
        return list(val) if not isinstance(val, list) else val
    if t in ("DATE", "TIMESTAMP", "DATETIME", "TIME", "INSTANT", "LOCAL_DATE", "LOCAL_TIME", "LOCAL_DATE_TIME"):
        return val  # stored as-is; serialisation formats them
    raise ValueError(f"unknown cast type {type_!r}")


class ComputeStep(Step):
    """`compute` — evaluate expressions into named fields
    (ComputeConfiguration.java: fields[{name, expression, type, optional}])."""

    async def process(self, record: MutableRecord, context: Any) -> None:
        for f in self.config.get("fields", []):
            name = f["name"]
            expression = f["expression"]
            try:
                val = el.evaluate(expression, record)
            except el.ExpressionError:
                if f.get("optional"):
                    continue
                raise
            type_ = f.get("type")
            if type_:
                val = _cast_scalar(val, type_)
            record.set_field(name, val)


class CastStep(Step):
    """`cast` — convert key/value to `schema-type`."""

    async def process(self, record: MutableRecord, context: Any) -> None:
        schema_type = self.config.get("schema-type", "string")
        part = self.config.get("part")
        if part in (None, "value"):
            record.value = _cast_scalar(record.value, schema_type)
        if part in (None, "key") and record.key is not None:
            record.key = _cast_scalar(record.key, schema_type)


class DropStep(Step):
    """`drop` — discard the record (combined with `when`)."""

    async def process(self, record: MutableRecord, context: Any) -> None:
        record.dropped = True


class DropFieldsStep(Step):
    """`drop-fields` — remove fields from a record part."""

    async def process(self, record: MutableRecord, context: Any) -> None:
        part = self.config.get("part")
        for name in self.config.get("fields", []):
            if "." in name or part is None:
                record.drop_field(name)
            else:
                record.drop_field(f"{part}.{name}")


def _flatten(obj: Any, prefix: str, delimiter: str, out: dict) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}{delimiter}{k}" if prefix else str(k)
            if isinstance(v, dict):
                _flatten(v, key, delimiter, out)
            else:
                out[key] = v
    else:
        out[prefix] = obj


class FlattenStep(Step):
    """`flatten` — flatten nested structures with a delimiter (default `_`)."""

    async def process(self, record: MutableRecord, context: Any) -> None:
        delimiter = self.config.get("delimiter", "_")
        part = self.config.get("part")
        if part in (None, "value") and isinstance(record.value, dict):
            out: dict = {}
            _flatten(record.value, "", delimiter, out)
            record.value = out
        if part in (None, "key") and isinstance(record.key, dict):
            out = {}
            _flatten(record.key, "", delimiter, out)
            record.key = out


class MergeKeyValueStep(Step):
    """`merge-key-value` — merge the key map into the value map."""

    async def process(self, record: MutableRecord, context: Any) -> None:
        if isinstance(record.key, dict) and isinstance(record.value, dict):
            record.value = {**record.key, **record.value}
            record._value_was_json = True


class UnwrapKeyValueStep(Step):
    """`unwrap-key-value` — replace the record with its value (or key when
    `unwrapKey` is set)."""

    async def process(self, record: MutableRecord, context: Any) -> None:
        unwrap_key = bool(self.config.get("unwrapKey", self.config.get("unwrap-key", False)))
        record.value = record.key if unwrap_key else record.value
        if unwrap_key:
            record.key = None


class DocumentToJsonStep(Step):
    """`document-to-json` — wrap a raw text value into a one-field JSON doc
    (reference text-processing agent `document-to-json`; lives here because
    it is a pure record transform)."""

    async def process(self, record: MutableRecord, context: Any) -> None:
        field_name = self.config.get("text-field", "text")
        copy_props = bool(self.config.get("copy-properties", True))
        doc = {field_name: el._to_str(record.value)}
        if copy_props:
            doc.update(record.properties)
        record.value = doc
        record._value_was_json = True


TRANSFORM_STEPS: dict[str, type[Step]] = {
    "compute": ComputeStep,
    "cast": CastStep,
    "drop": DropStep,
    "drop-fields": DropFieldsStep,
    "flatten": FlattenStep,
    "merge-key-value": MergeKeyValueStep,
    "unwrap-key-value": UnwrapKeyValueStep,
    "document-to-json": DocumentToJsonStep,
}
