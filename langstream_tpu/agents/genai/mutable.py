"""Mutable transform context for GenAI toolkit steps.

Parity: reference `langstream-agents-commons` `MutableRecord.java` (the
record-under-transformation that all steps mutate) — key/value parsed into
navigable structures, headers as properties, destination-topic override, and
a final materialisation back into a Record.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Optional

from langstream_tpu.api.record import Header, Record, SimpleRecord


def _parse_side(raw: Any) -> tuple[Any, bool, Any]:
    """Parse a record side (key or value) → (parsed, was_json, avro_schema).
    JSON objects/arrays become dicts/lists (was_json=True → serialised back
    to JSON on materialise); Avro values become their JSON-compatible datum
    with the schema remembered for re-encoding (AvroUtil analog)."""
    from langstream_tpu.api.avro import AvroValue, datum_to_json

    if isinstance(raw, AvroValue):
        return datum_to_json(raw.data), False, raw.schema
    if isinstance(raw, (bytes, bytearray)):
        try:
            raw = raw.decode("utf-8")
        except UnicodeDecodeError:
            return raw, False, None
    if isinstance(raw, str):
        s = raw.strip()
        if s.startswith("{") or s.startswith("["):
            try:
                return json.loads(s), True, None
            except (json.JSONDecodeError, ValueError):
                return raw, False, None
    return raw, False, None


@dataclass
class MutableRecord:
    key: Any = None
    value: Any = None
    properties: dict[str, Any] = field(default_factory=dict)
    origin: Optional[str] = None
    timestamp: Optional[float] = None
    destination_topic: Optional[str] = None
    dropped: bool = False
    _key_was_json: bool = False
    _value_was_json: bool = False
    # Avro provenance: the side re-encodes under this schema on materialise
    # (falls back to JSON if the mutated shape no longer fits the schema)
    _key_avro_schema: Any = None
    _value_avro_schema: Any = None

    @staticmethod
    def from_record(record: Record) -> "MutableRecord":
        from langstream_tpu.runtime.topic_adapters import DESTINATION_HEADER

        key, key_json, key_schema = _parse_side(record.key)
        value, value_json, value_schema = _parse_side(record.value)
        properties = {h.key: h.value for h in record.headers}
        destination = properties.pop(DESTINATION_HEADER, None)
        return MutableRecord(
            key=key,
            value=value,
            properties=properties,
            origin=record.origin,
            timestamp=record.timestamp,
            destination_topic=destination,
            _key_was_json=key_json,
            _value_was_json=value_json,
            _key_avro_schema=key_schema,
            _value_avro_schema=value_schema,
        )

    # -- field-path access ("value", "value.a.b", "key.x", "properties.p",
    #    "destinationTopic", "origin", "timestamp") --------------------------

    def _root(self, name: str) -> Any:
        if name == "value":
            return self.value
        if name == "key":
            return self.key
        if name in ("properties", "headers"):
            return self.properties
        if name == "destinationTopic":
            return self.destination_topic
        if name == "origin":
            return self.origin
        if name in ("timestamp", "eventTime"):
            return self.timestamp
        raise KeyError(f"unknown record part {name!r}")

    def get_field(self, path: str) -> Any:
        parts = path.split(".")
        current = self._root(parts[0])
        for p in parts[1:]:
            if current is None:
                return None
            if isinstance(current, dict):
                current = current.get(p)
            else:
                current = getattr(current, p, None)
        return current

    def set_field(self, path: str, val: Any) -> None:
        parts = path.split(".")
        root = parts[0]
        if len(parts) == 1:
            if root == "value":
                self.value = val
            elif root == "key":
                self.key = val
            elif root == "destinationTopic":
                self.destination_topic = val
            elif root in ("timestamp", "eventTime"):
                self.timestamp = val
            else:
                raise KeyError(f"cannot set record part {path!r}")
            return
        if root in ("properties", "headers"):
            if len(parts) != 2:
                raise KeyError(f"properties paths are flat: {path!r}")
            self.properties[parts[1]] = val
            return
        if root == "value":
            if not isinstance(self.value, dict):
                self.value = {}
                self._value_was_json = True
            container: Any = self.value
        elif root == "key":
            if not isinstance(self.key, dict):
                self.key = {}
                self._key_was_json = True
            container = self.key
        else:
            raise KeyError(f"cannot set into record part {root!r}")
        for p in parts[1:-1]:
            nxt = container.get(p)
            if not isinstance(nxt, dict):
                nxt = {}
                container[p] = nxt
            container = nxt
        container[parts[-1]] = val

    def drop_field(self, path: str) -> None:
        parts = path.split(".")
        root = parts[0]
        if root in ("properties", "headers") and len(parts) == 2:
            self.properties.pop(parts[1], None)
            return
        if len(parts) == 1:
            # bare field name → drop from value (reference drop-fields default)
            if isinstance(self.value, dict):
                self.value.pop(parts[0], None)
            return
        container = self._root(root)
        for p in parts[1:-1]:
            if not isinstance(container, dict):
                return
            container = container.get(p)
        if isinstance(container, dict):
            container.pop(parts[-1], None)

    # -- materialisation ----------------------------------------------------

    def _serialise(self, side: Any, was_json: bool, avro_schema: Any) -> Any:
        if avro_schema is not None:
            from langstream_tpu.api.avro import AvroError, AvroValue, encode, json_to_datum

            try:
                # strict: mutated-in fields the schema lacks must NOT be
                # silently dropped — they force the JSON fallback below
                datum = json_to_datum(avro_schema, side, strict=True)
                encode(avro_schema, datum)  # validates the mutated shape
                return AvroValue(avro_schema, datum)
            except AvroError:
                # schema no longer fits (field added/dropped): degrade to JSON
                if isinstance(side, (dict, list)):
                    return json.dumps(side)
                return side
        if was_json and isinstance(side, (dict, list)):
            return json.dumps(side)
        return side

    def to_record(self) -> SimpleRecord:
        headers = [Header(k, v) for k, v in self.properties.items()]
        if self.destination_topic:
            from langstream_tpu.runtime.topic_adapters import DESTINATION_HEADER

            headers.append(Header(DESTINATION_HEADER, self.destination_topic))
        return SimpleRecord(
            key=self._serialise(self.key, self._key_was_json, self._key_avro_schema),
            value=self._serialise(
                self.value, self._value_was_json, self._value_avro_schema
            ),
            headers=tuple(headers),
            origin=self.origin,
            timestamp=self.timestamp,
        )
