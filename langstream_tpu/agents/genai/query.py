"""`query` step — run a parameterised query against a datasource resource.

Parity: reference `QueryStep.java` + `QueryConfiguration.java` — `fields`
are expressions evaluated per record into query params, results land in
`output-field` (list of rows, or the first row with `only-first`),
`loop-over` iterates sub-documents, `mode: execute` runs DML and stores
`generated-keys`.
"""

from __future__ import annotations

from typing import Any

from langstream_tpu.agents.genai import el
from langstream_tpu.agents.genai.mutable import MutableRecord
from langstream_tpu.agents.genai.steps import Step


class QueryStep(Step):
    def __init__(self, config: dict[str, Any]) -> None:
        super().__init__(config)
        self.query = config.get("query", "")
        self.fields = config.get("fields", [])
        self.output_field = config.get("output-field", "value.query-result")
        self.only_first = bool(config.get("only-first", False))
        self.loop_over = config.get("loop-over")
        self.mode = config.get("mode", "query")
        self.datasource_name = config.get("datasource")
        self._datasource = None

    async def start(self, context: Any) -> None:
        registry = context.get_service_provider_registry()
        self._datasource = registry.get_datasource(self.datasource_name)

    def _params(self, record: MutableRecord, extra: dict | None = None) -> list[Any]:
        return [el.evaluate(f, record, extra) for f in self.fields]

    async def _run(self, record: MutableRecord, extra: dict | None = None) -> Any:
        params = self._params(record, extra)
        if self.mode == "execute":
            return await self._datasource.execute_statement(self.query, params)
        rows = await self._datasource.fetch_data(self.query, params)
        if self.only_first:
            return rows[0] if rows else None
        return rows

    async def process(self, record: MutableRecord, context: Any) -> None:
        assert self._datasource is not None, "step not started"
        if self.loop_over:
            items = el.evaluate(self.loop_over, record) or []
            field = self.output_field
            if field.startswith("record."):
                field = field[len("record."):]
            for item in items:
                result = await self._run(record, extra={"record": item})
                if isinstance(item, dict):
                    item[field] = result
        else:
            record.set_field(self.output_field, await self._run(record))
