"""GenAI toolkit agent: a processor that runs a chain of steps per record.

Parity: reference `GenAIToolKitAgent.java:53` (AgentProcessor wrapping a step
list). The planner registers each step type as its own agent type (the
reference planner does the same via GenAIToolKitFunctionAgentProvider, then
fuses adjacent composable agents); one agent instance may carry several steps
when configured with a `steps` list.
"""

from __future__ import annotations

from typing import Any

from langstream_tpu.agents.genai.completions import ChatCompletionsStep, TextCompletionsStep
from langstream_tpu.agents.genai.embeddings import ComputeAIEmbeddingsStep
from langstream_tpu.agents.genai.mutable import MutableRecord
from langstream_tpu.agents.genai.query import QueryStep
from langstream_tpu.agents.genai.steps import TRANSFORM_STEPS, Step
from langstream_tpu.api.agent import AgentProcessor, ProcessorResult
from langstream_tpu.api.record import Record

STEP_TYPES: dict[str, type[Step]] = {
    **TRANSFORM_STEPS,
    "ai-chat-completions": ChatCompletionsStep,
    "ai-text-completions": TextCompletionsStep,
    "compute-ai-embeddings": ComputeAIEmbeddingsStep,
    "query": QueryStep,
}


def make_step(step_type: str, config: dict[str, Any]) -> Step:
    if step_type not in STEP_TYPES:
        raise ValueError(f"unknown GenAI step type {step_type!r}")
    return STEP_TYPES[step_type](config)


class GenAIToolKitAgent(AgentProcessor):
    """Runs one or more GenAI steps over each record.

    Configuration is either a single step's config (agent `type:` selects the
    step) or `{"steps": [{"type": ..., ...}, ...]}` for a pre-fused chain.
    """

    def __init__(self, step_type: str | None = None) -> None:
        super().__init__()
        self._declared_type = step_type
        self.steps: list[Step] = []

    async def init(self, configuration: dict[str, Any]) -> None:
        if "steps" in configuration and isinstance(configuration["steps"], list):
            self.steps = [
                make_step(s["type"], {k: v for k, v in s.items() if k != "type"})
                for s in configuration["steps"]
            ]
        else:
            assert self._declared_type is not None, "agent type missing"
            self.steps = [make_step(self._declared_type, configuration)]

    async def start(self) -> None:
        for step in self.steps:
            await step.start(self.context)

    async def close(self) -> None:
        for step in self.steps:
            await step.close()

    async def process(self, records: list[Record]) -> list[ProcessorResult]:
        # records fan out CONCURRENTLY (reference GenAIToolKitAgent processes
        # each record on its own CompletableFuture chain): with an
        # engine-backed completions step this is what fills the continuous
        # batcher's slots — a sequential await would serialize the whole
        # batch through one KV-cache slot. gather preserves input order;
        # ordering is enforced at COMMIT time by the tracker, not here.
        import asyncio

        return list(
            await asyncio.gather(*(self._process_one(r) for r in records))
        )

    async def _process_one(self, record: Record) -> ProcessorResult:
        try:
            mutable = MutableRecord.from_record(record)
            for step in self.steps:
                await step.apply(mutable, self.context)
                if mutable.dropped:
                    break
            out = [] if mutable.dropped else [mutable.to_record()]
            self.processed(1)
            return ProcessorResult.ok(record, out)
        except Exception as e:  # noqa: BLE001 — per-record error routing
            return ProcessorResult.failed(record, e)


def _make_factory(step_type: str):
    def factory() -> GenAIToolKitAgent:
        return GenAIToolKitAgent(step_type)

    return factory


def register_genai_agents() -> None:
    from langstream_tpu.api.agent import ComponentType
    from langstream_tpu.api.doc import ConfigModel
    from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo

    for step_type in STEP_TYPES:
        REGISTRY.register_agent(
            AgentTypeInfo(
                type=step_type,
                component_type=ComponentType.PROCESSOR,
                factory=_make_factory(step_type),
                composable=True,
                description=f"GenAI toolkit step: {step_type}",
                config_model=ConfigModel(type=step_type, allow_unknown=True),
            )
        )
