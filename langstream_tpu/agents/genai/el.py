"""Safe expression language for steps, filters and templates.

Parity: reference `langstream-agents-commons` JSTL engine
(`jstl/JstlEvaluator.java`, `JstlFunctions.java`) — the language used by
`compute` expressions, `when` conditions, gateway filters and prompt
templates. Rebuilt as a whitelisted-AST Python evaluator instead of JSTL:
same surface (record parts as variables, `fn:`-style helpers), no arbitrary
code execution.

Expressions see the record parts as variables: ``value``, ``key``,
``properties``, ``destinationTopic``, ``origin``, ``timestamp``; dotted
access works on dicts (``value.chunk_id``). Helper functions are available
both bare (``lowercase(x)``) and with the reference's ``fn:`` prefix
(``fn:lowercase(x)``). ``fn:``/``util:``-prefixed names ALWAYS resolve to
the function registry, even when a record binding shadows the bare name —
``fn:timestamp()`` calls the helper, bare ``timestamp`` is the record's
event time.
"""

from __future__ import annotations

import ast
import base64
import datetime
import functools
import json
import re
import time
import uuid
from typing import Any, Mapping, Optional

from langstream_tpu.agents.genai.mutable import MutableRecord


class ExpressionError(ValueError):
    pass


# -- helper functions (JstlFunctions parity) --------------------------------


def _to_str(x: Any) -> str:
    if x is None:
        return ""
    if isinstance(x, bytes):
        return x.decode("utf-8", "replace")
    if isinstance(x, (dict, list)):
        return json.dumps(x)
    return str(x)


def _concat(*args: Any) -> str:
    return "".join(_to_str(a) for a in args)


def _coalesce(*args: Any) -> Any:
    for a in args:
        if a is not None:
            return a
    return None


def _timestamp_add(ts: Any, delta: Any, unit: str) -> float:
    base = float(ts)
    mult = {
        "millis": 1e-3, "seconds": 1.0, "minutes": 60.0, "hours": 3600.0,
        "days": 86400.0,
    }.get(unit)
    if mult is None:
        raise ExpressionError(f"unknown time unit {unit!r}")
    return base + float(delta) * mult


FUNCTIONS: dict[str, Any] = {
    # strings
    "uppercase": lambda s: _to_str(s).upper(),
    "lowercase": lambda s: _to_str(s).lower(),
    "trim": lambda s: _to_str(s).strip(),
    "concat": _concat,
    "concat3": _concat,
    "contains": lambda s, sub: _to_str(sub) in _to_str(s),
    "replace": lambda s, a, b: _to_str(s).replace(_to_str(a), _to_str(b)),
    "replaceRegex": lambda s, a, b: re.sub(_to_str(a), _to_str(b), _to_str(s)),
    "split": lambda s, sep: _to_str(s).split(_to_str(sep)),
    "str": _to_str,
    "toString": _to_str,
    "length": lambda x: len(x) if x is not None else 0,
    "len": lambda x: len(x) if x is not None else 0,
    # numbers
    "toInt": lambda x: int(float(x)) if x is not None else None,
    "toDouble": lambda x: float(x) if x is not None else None,
    "abs": abs,
    "min": min,
    "max": max,
    "round": round,
    # json
    "toJson": lambda x: json.dumps(x),
    "fromJson": lambda s: json.loads(_to_str(s)),
    # collections
    "emptyList": lambda: [],
    "emptyMap": lambda: {},
    "listAdd": lambda lst, x: (list(lst or []) + [x]),
    "listOf": lambda *xs: list(xs),
    "mapOf": lambda *kv: {kv[i]: kv[i + 1] for i in range(0, len(kv), 2)},
    "mapPut": lambda m, k, v: {**(m or {}), k: v},
    "listToText": lambda lst, sep=" ": _to_str(sep).join(_to_str(x) for x in (lst or [])),
    "filter": lambda lst, pred: [x for x in (lst or []) if pred(x)],
    # misc
    "coalesce": _coalesce,
    "uuid": lambda: str(uuid.uuid4()),
    "randomUUID": lambda: str(uuid.uuid4()),
    "now": lambda: time.time(),
    "timestamp": lambda: time.time(),
    "currentTimeMillis": lambda: int(time.time() * 1000),
    "timestampAdd": _timestamp_add,
    "dateadd": _timestamp_add,
    "decimalFromUnscaled": lambda unscaled, scale: float(unscaled) / (10 ** int(scale)),
    "base64encode": lambda s: base64.b64encode(_to_str(s).encode()).decode(),
    "base64decode": lambda s: base64.b64decode(_to_str(s)).decode("utf-8", "replace"),
    "fromUnixMillis": lambda ms: datetime.datetime.fromtimestamp(
        float(ms) / 1000, tz=datetime.timezone.utc
    ).isoformat(),
}

_ALLOWED_NODES = (
    ast.Expression, ast.Constant, ast.Name, ast.Load, ast.Attribute,
    ast.Subscript, ast.Index, ast.Slice, ast.Tuple, ast.List, ast.Dict,
    ast.BoolOp, ast.And, ast.Or, ast.UnaryOp, ast.Not, ast.USub, ast.UAdd,
    ast.BinOp, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
    ast.Pow, ast.Compare, ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt,
    ast.GtE, ast.In, ast.NotIn, ast.Is, ast.IsNot, ast.Call, ast.IfExp,
    ast.keyword,
)


class _Evaluator(ast.NodeVisitor):
    def __init__(self, scope: Mapping[str, Any]):
        self.scope = scope

    def visit(self, node: ast.AST) -> Any:
        if not isinstance(node, _ALLOWED_NODES):
            raise ExpressionError(f"disallowed syntax: {type(node).__name__}")
        return super().visit(node)

    def visit_Expression(self, node: ast.Expression) -> Any:
        return self.visit(node.body)

    def visit_Constant(self, node: ast.Constant) -> Any:
        return node.value

    def visit_Name(self, node: ast.Name) -> Any:
        if node.id.startswith("__fn__"):
            # explicit fn:/util: namespace — registry only, never record scope
            name = node.id[len("__fn__"):]
            if name in FUNCTIONS:
                return FUNCTIONS[name]
            raise ExpressionError(f"unknown function fn:{name}")
        if node.id in self.scope:
            return self.scope[node.id]
        if node.id in FUNCTIONS:
            return FUNCTIONS[node.id]
        if node.id == "true":
            return True
        if node.id == "false":
            return False
        if node.id == "null":
            return None
        raise ExpressionError(f"unknown name {node.id!r}")

    def visit_Attribute(self, node: ast.Attribute) -> Any:
        base = self.visit(node.value)
        if base is None:
            return None
        if isinstance(base, Mapping):
            return base.get(node.attr)
        if node.attr.startswith("_"):
            raise ExpressionError("private attribute access is not allowed")
        return getattr(base, node.attr, None)

    def visit_Subscript(self, node: ast.Subscript) -> Any:
        base = self.visit(node.value)
        if base is None:
            return None
        idx = self.visit(node.slice)
        try:
            return base[idx]
        except (KeyError, IndexError, TypeError):
            return None

    def visit_Slice(self, node: ast.Slice) -> Any:
        return slice(
            self.visit(node.lower) if node.lower else None,
            self.visit(node.upper) if node.upper else None,
            self.visit(node.step) if node.step else None,
        )

    def visit_Tuple(self, node: ast.Tuple) -> Any:
        return tuple(self.visit(e) for e in node.elts)

    def visit_List(self, node: ast.List) -> Any:
        return [self.visit(e) for e in node.elts]

    def visit_Dict(self, node: ast.Dict) -> Any:
        return {
            self.visit(k): self.visit(v)
            for k, v in zip(node.keys, node.values)
            if k is not None
        }

    def visit_BoolOp(self, node: ast.BoolOp) -> Any:
        if isinstance(node.op, ast.And):
            result: Any = True
            for v in node.values:
                result = self.visit(v)
                if not result:
                    return result
            return result
        for v in node.values:
            result = self.visit(v)
            if result:
                return result
        return result

    def visit_UnaryOp(self, node: ast.UnaryOp) -> Any:
        val = self.visit(node.operand)
        if isinstance(node.op, ast.Not):
            return not val
        if isinstance(node.op, ast.USub):
            return -val
        return +val

    def visit_BinOp(self, node: ast.BinOp) -> Any:
        left, right = self.visit(node.left), self.visit(node.right)
        op = type(node.op)
        if op is ast.Add:
            if isinstance(left, str) or isinstance(right, str):
                return _to_str(left) + _to_str(right)
            return left + right
        if op is ast.Sub:
            return left - right
        if op is ast.Mult:
            return left * right
        if op is ast.Div:
            return left / right
        if op is ast.FloorDiv:
            return left // right
        if op is ast.Mod:
            return left % right
        if op is ast.Pow:
            return left**right
        raise ExpressionError(f"disallowed operator {op.__name__}")

    def visit_Compare(self, node: ast.Compare) -> Any:
        left = self.visit(node.left)
        for op, comparator in zip(node.ops, node.comparators):
            right = self.visit(comparator)
            ok = {
                ast.Eq: lambda a, b: a == b,
                ast.NotEq: lambda a, b: a != b,
                ast.Lt: lambda a, b: a < b,
                ast.LtE: lambda a, b: a <= b,
                ast.Gt: lambda a, b: a > b,
                ast.GtE: lambda a, b: a >= b,
                ast.In: lambda a, b: a in b,
                ast.NotIn: lambda a, b: a not in b,
                ast.Is: lambda a, b: a is b,
                ast.IsNot: lambda a, b: a is not b,
            }[type(op)](left, right)
            if not ok:
                return False
            left = right
        return True

    def visit_Call(self, node: ast.Call) -> Any:
        fn = self.visit(node.func)
        if not callable(fn):
            raise ExpressionError("attempt to call a non-function")
        args = [self.visit(a) for a in node.args]
        kwargs = {kw.arg: self.visit(kw.value) for kw in node.keywords if kw.arg}
        return fn(*args, **kwargs)

    def visit_IfExp(self, node: ast.IfExp) -> Any:
        return self.visit(node.body) if self.visit(node.test) else self.visit(node.orelse)


_FN_PREFIX = re.compile(r"\bfn:([A-Za-z_][A-Za-z0-9_]*)")
_UTIL_PREFIX = re.compile(r"\butil:([A-Za-z_][A-Za-z0-9_]*)")


# split into string-literal and code spans so JSTL rewrites never touch
# quoted text ('it!' must stay 'it!', not 'it not ')
_SPANS = re.compile(r"('(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\")")


def _rewrite_prefixes(e: str) -> str:
    """fn:name / util:name → __fn__name, outside quoted spans. Must run
    BEFORE the ternary rewrite: otherwise the ':' in ``fn:name`` inside a
    ternary branch is mistaken for the ternary separator."""
    parts = _SPANS.split(e)
    return "".join(
        part
        if i % 2
        else _UTIL_PREFIX.sub(r"__fn__\1", _FN_PREFIX.sub(r"__fn__\1", part))
        for i, part in enumerate(parts)
    )


def _rewrite_code(e: str) -> str:
    e = re.sub(r"&&", " and ", e)
    e = re.sub(r"\|\|", " or ", e)
    e = re.sub(r"(?<![=!<>])!(?!=)", " not ", e)
    e = re.sub(r"\beq\b", "==", e)
    e = re.sub(r"\bne\b", "!=", e)
    return e


def _rewrite_ternary(e: str) -> str:
    """JSTL ``cond ? then : else`` → python conditional expression.

    First recurses into every top-level bracketed group (so parenthesized
    nested ternaries anywhere get rewritten), then splits this level on its
    first top-level '?' and the matching ':' — right-associative like JSTL.
    Quoted text is never touched; subscripts/slices keep their ':'."""
    # pass 1: rewrite inside (), [] groups
    out: list[str] = []
    i, n = 0, len(e)
    while i < n:
        ch = e[i]
        if ch in "'\"":
            j = i + 1
            while j < n and (e[j] != ch or e[j - 1] == "\\"):
                j += 1
            out.append(e[i : j + 1])
            i = j + 1
            continue
        if ch in "([":
            close = ")" if ch == "(" else "]"
            depth = 1
            j = i + 1
            while j < n and depth:
                c = e[j]
                if c in "'\"":
                    k = j + 1
                    while k < n and (e[k] != c or e[k - 1] == "\\"):
                        k += 1
                    j = k
                elif c == ch:
                    depth += 1
                elif c == close:
                    depth -= 1
                j += 1
            out.append(ch + _rewrite_ternary(e[i + 1 : j - 1]) + close)
            i = j
            continue
        out.append(ch)
        i += 1
    e = "".join(out)
    # pass 2: split this level's ternary
    depth = 0
    quote: Optional[str] = None
    q_pos = -1
    for i, ch in enumerate(e):
        if quote:
            if ch == quote and e[i - 1] != "\\":
                quote = None
            continue
        if ch in "'\"":
            quote = ch
        elif ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "?" and depth == 0 and q_pos < 0:
            q_pos = i
        elif ch == ":" and depth == 0 and q_pos >= 0:
            cond = e[:q_pos]
            then = _rewrite_ternary(e[q_pos + 1 : i])
            other = _rewrite_ternary(e[i + 1 :])
            return f"(({then}) if ({cond}) else ({other}))"
    return e


def _rewrite(expression: str) -> str:
    # JSTL artifacts: fn:/util: namespaces, && / || / ! operators, ${...}
    # shell, ternary ?:
    e = expression.strip()
    if e.startswith("${") and e.endswith("}"):
        e = e[2:-1]
    e = _rewrite_prefixes(e)
    e = _rewrite_ternary(e)
    parts = _SPANS.split(e)
    return "".join(
        part if i % 2 else _rewrite_code(part) for i, part in enumerate(parts)
    )


@functools.lru_cache(maxsize=4096)
def _compile(expression: str) -> ast.Expression:
    rewritten = _rewrite(expression)
    try:
        return ast.parse(rewritten, mode="eval")
    except SyntaxError as e:
        raise ExpressionError(f"cannot parse expression {expression!r}: {e}") from e


def scope_for(record: MutableRecord, extra: Optional[Mapping[str, Any]] = None) -> dict:
    scope: dict[str, Any] = {
        "value": record.value,
        "key": record.key,
        "properties": record.properties,
        "headers": record.properties,
        "destinationTopic": record.destination_topic,
        "origin": record.origin,
        "timestamp": record.timestamp,
        "eventTime": record.timestamp,
        "record": record,
    }
    if extra:
        scope.update(extra)
    return scope


def evaluate(expression: str, record: MutableRecord, extra: Optional[Mapping[str, Any]] = None) -> Any:
    """Evaluate an expression against a record's transform context."""
    return _Evaluator(scope_for(record, extra)).visit(_compile(expression))


def evaluate_bool(expression: str, record: MutableRecord, extra: Optional[Mapping[str, Any]] = None) -> bool:
    return bool(evaluate(expression, record, extra))


_MUSTACHE = re.compile(r"\{\{\{?\s*(.*?)\s*\}?\}\}")


def render_template(template: str, record: MutableRecord, extra: Optional[Mapping[str, Any]] = None) -> str:
    """Render ``{{ expr }}`` placeholders (the prompt-template surface of
    ChatCompletionsStep — reference renders Mustache over record fields)."""

    def repl(m: re.Match) -> str:
        return _to_str(evaluate(m.group(1), record, extra))

    return _MUSTACHE.sub(repl, template)
