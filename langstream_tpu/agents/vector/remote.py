"""SDK-free HTTP clients for remote vector databases.

Parity: reference `langstream-vector-agents` per-DB datasources/writers —
`pinecone/PineconeDataSource.java`, `opensearch/OpenSearchDataSource.java`
+ `OpenSearchWriter.java`, `solr/SolrDataSource.java` + writer. Each spoke
an official SDK; here the REST APIs are driven directly with aiohttp (the
image has no egress, so these are exercised against local HTTP stubs —
`tests/test_vector_remote.py`, the google/github auth-provider pattern).

Query convention (the reference's for non-SQL stores): the `query` string
is a JSON document; positional `fields` values substitute `"?"`
placeholders in order (shared `_substitute_params`).
"""

from __future__ import annotations

import json
from typing import Any, Optional

import aiohttp

from langstream_tpu.agents.genai import el
from langstream_tpu.agents.genai.mutable import MutableRecord
from langstream_tpu.api.storage import DataSource, VectorDatabaseWriter


def _substitute_params(obj: Any, params: list[Any]) -> Any:
    if isinstance(obj, dict):
        return {k: _substitute_params(v, params) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_substitute_params(v, params) for v in obj]
    if obj == "?" and params:
        return params.pop(0)
    return obj


def _parse_query(query: str, params: list[Any]) -> dict[str, Any]:
    try:
        parsed = json.loads(query)
    except json.JSONDecodeError as e:
        raise ValueError(f"remote vector query must be JSON: {e}") from e
    return _substitute_params(parsed, list(params))


class _HttpDataSource(DataSource):
    """Shared aiohttp session + JSON request plumbing."""

    def __init__(self, config: dict[str, Any]) -> None:
        self.config = dict(config)
        self._session: Optional[aiohttp.ClientSession] = None

    async def _request(
        self, method: str, url: str, body: Optional[dict] = None,
        headers: Optional[dict[str, str]] = None,
    ) -> dict[str, Any]:
        if self._session is None:
            self._session = aiohttp.ClientSession()
        async with self._session.request(
            method, url, json=body, headers=headers or {}
        ) as resp:
            text = await resp.text()
            if resp.status >= 400:
                raise RuntimeError(f"{type(self).__name__} {method} {url}: "
                                   f"{resp.status} {text[:300]}")
            return json.loads(text) if text else {}

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def execute_statement(self, query: str, params: list[Any]) -> dict[str, Any]:
        raise ValueError(f"{type(self).__name__} does not support execute mode")


# ---------------------------------------------------------------------------
# Pinecone
# ---------------------------------------------------------------------------


class PineconeDataSource(_HttpDataSource):
    """`service: pinecone` — REST index endpoint. Query JSON mirrors the
    reference (`PineconeDataSource.java`): {"vector": [...], "topK": N,
    "filter": {...}, "includeMetadata": true}; rows come back as
    {id, similarity, **metadata}."""

    def __init__(self, config: dict[str, Any]) -> None:
        super().__init__(config)
        # endpoint: the index host URL (https://{index}-{project}.svc...);
        # tests point it at a local stub
        self.endpoint = str(config.get("endpoint", "")).rstrip("/")
        self.api_key = config.get("api-key", "")
        if not self.endpoint:
            raise ValueError("pinecone datasource requires 'endpoint'")

    def _headers(self) -> dict[str, str]:
        return {"Api-Key": str(self.api_key)}

    async def fetch_data(self, query: str, params: list[Any]) -> list[dict[str, Any]]:
        body = _parse_query(query, params)
        body.setdefault("topK", 10)
        body.setdefault("includeMetadata", True)
        out = await self._request(
            "POST", f"{self.endpoint}/query", body, self._headers()
        )
        rows = []
        for match in out.get("matches", []):
            row = {"id": match.get("id"), "similarity": match.get("score")}
            row.update(match.get("metadata") or {})
            rows.append(row)
        return rows

    async def upsert(self, id_: str, vector: list[float], metadata: dict) -> None:
        await self._request(
            "POST",
            f"{self.endpoint}/vectors/upsert",
            {"vectors": [{"id": id_, "values": vector, "metadata": metadata}]},
            self._headers(),
        )


class PineconeWriter(VectorDatabaseWriter):
    def __init__(self, datasource: PineconeDataSource, config: dict[str, Any]) -> None:
        self.datasource = datasource
        self.id_expr = config.get("id", "fn:uuid()")
        self.vector_expr = config.get("vector", "value.embeddings")
        self.metadata_fields = list(config.get("fields", []))

    async def upsert(self, record: Any, context: dict[str, Any]) -> None:
        ctx = MutableRecord.from_record(record)
        id_ = str(el.evaluate(self.id_expr, ctx))
        vector = el.evaluate(self.vector_expr, ctx)
        if vector is None:
            raise ValueError(f"vector expression {self.vector_expr!r} produced None")
        meta = {
            f["name"]: el.evaluate(f.get("expression", "value"), ctx)
            for f in self.metadata_fields
        }
        await self.datasource.upsert(id_, list(map(float, vector)), meta)


# ---------------------------------------------------------------------------
# OpenSearch
# ---------------------------------------------------------------------------


class OpenSearchDataSource(_HttpDataSource):
    """`service: opensearch` — `_search` REST API. The query JSON is the
    standard search DSL (knn / match / whatever); rows are the hits with
    {id, similarity, **_source} (OpenSearchDataSource.java semantics)."""

    def __init__(self, config: dict[str, Any]) -> None:
        super().__init__(config)
        self.endpoint = str(config.get("endpoint", "")).rstrip("/")
        self.index = config.get("index-name", "langstream")
        self.username = config.get("username")
        self.password = config.get("password")
        if not self.endpoint:
            raise ValueError("opensearch datasource requires 'endpoint'")

    def _headers(self) -> dict[str, str]:
        if self.username:
            import base64

            token = base64.b64encode(
                f"{self.username}:{self.password or ''}".encode()
            ).decode()
            return {"Authorization": f"Basic {token}"}
        return {}

    async def fetch_data(self, query: str, params: list[Any]) -> list[dict[str, Any]]:
        body = _parse_query(query, params)
        out = await self._request(
            "POST", f"{self.endpoint}/{self.index}/_search", body, self._headers()
        )
        rows = []
        for hit in out.get("hits", {}).get("hits", []):
            row = {"id": hit.get("_id"), "similarity": hit.get("_score")}
            row.update(hit.get("_source") or {})
            rows.append(row)
        return rows

    async def index_document(self, id_: str, document: dict[str, Any]) -> None:
        await self._request(
            "PUT",
            f"{self.endpoint}/{self.index}/_doc/{id_}?refresh=true",
            document,
            self._headers(),
        )


class OpenSearchWriter(VectorDatabaseWriter):
    """vector-db-sink writer: each record becomes one document; the vector
    lands in `vector-field` alongside the computed fields
    (OpenSearchWriter.java's bulk-index semantics, one-at-a-time here)."""

    def __init__(self, datasource: OpenSearchDataSource, config: dict[str, Any]) -> None:
        self.datasource = datasource
        self.id_expr = config.get("id", "fn:uuid()")
        self.vector_expr = config.get("vector", "value.embeddings")
        self.vector_field = config.get("vector-field", "embeddings")
        self.metadata_fields = list(config.get("fields", []))

    async def upsert(self, record: Any, context: dict[str, Any]) -> None:
        ctx = MutableRecord.from_record(record)
        id_ = str(el.evaluate(self.id_expr, ctx))
        doc = {
            f["name"]: el.evaluate(f.get("expression", "value"), ctx)
            for f in self.metadata_fields
        }
        vector = el.evaluate(self.vector_expr, ctx)
        if vector is not None:
            doc[self.vector_field] = list(map(float, vector))
        await self.datasource.index_document(id_, doc)


# ---------------------------------------------------------------------------
# Solr
# ---------------------------------------------------------------------------


class SolrDataSource(_HttpDataSource):
    """`service: solr` — JSON Request API on a collection. The query JSON
    is Solr's {"query": "...", "limit": N, ...} body (knn via
    {!knn f=vector topK=10}); rows are the response docs
    (SolrDataSource.java semantics)."""

    def __init__(self, config: dict[str, Any]) -> None:
        super().__init__(config)
        self.endpoint = str(config.get("endpoint", "")).rstrip("/")
        self.collection = config.get("collection-name", "langstream")
        if not self.endpoint:
            raise ValueError("solr datasource requires 'endpoint'")

    async def fetch_data(self, query: str, params: list[Any]) -> list[dict[str, Any]]:
        body = _parse_query(query, params)
        out = await self._request(
            "POST", f"{self.endpoint}/solr/{self.collection}/select", body
        )
        return list(out.get("response", {}).get("docs", []))

    async def add_documents(self, docs: list[dict[str, Any]]) -> None:
        await self._request(
            "POST",
            f"{self.endpoint}/solr/{self.collection}/update/json/docs?commit=true",
            docs[0] if len(docs) == 1 else docs,  # Solr accepts either form
        )


class SolrWriter(VectorDatabaseWriter):
    def __init__(self, datasource: SolrDataSource, config: dict[str, Any]) -> None:
        self.datasource = datasource
        self.id_expr = config.get("id", "fn:uuid()")
        self.vector_expr = config.get("vector", "value.embeddings")
        self.vector_field = config.get("vector-field", "embeddings")
        self.metadata_fields = list(config.get("fields", []))

    async def upsert(self, record: Any, context: dict[str, Any]) -> None:
        ctx = MutableRecord.from_record(record)
        doc = {"id": str(el.evaluate(self.id_expr, ctx))}
        for f in self.metadata_fields:
            doc[f["name"]] = el.evaluate(f.get("expression", "value"), ctx)
        vector = el.evaluate(self.vector_expr, ctx)
        if vector is not None:
            doc[self.vector_field] = list(map(float, vector))
        await self.datasource.add_documents([doc])
