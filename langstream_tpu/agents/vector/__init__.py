"""Vector / SQL datasource agents.

Parity: reference `langstream-vector-agents` (SURVEY §2.5): `vector-db-sink`
and `query-vector-db` over per-DB datasources, plus asset managers for
declarative table/index creation. The reference ships clients for
Cassandra/Astra/Pinecone/Milvus/OpenSearch/Solr/JDBC; none of those client
libraries is bundled here, so the in-tree backends are:

- ``service: jdbc`` → SQLite (stdlib) — the relational path,
- ``service: local-vector`` → a TPU-first brute-force vector store whose
  top-k similarity search is one jitted matmul over a padded [capacity, dim]
  matrix (MXU-shaped; on CPU the identical code path runs under XLA:CPU).

Other services register their config models for validation but raise a
clear "client not bundled" error when instantiated.

Also here: `re-rank` (MMR — reference rerank/ReRankAgent.java) and
`flare-controller` (reference flare/FlareControllerAgent.java).
"""

from __future__ import annotations

import asyncio
import json
import math
import re
import sqlite3
from typing import Any, Optional

import numpy as np

from langstream_tpu.agents.genai import el
from langstream_tpu.agents.genai.mutable import MutableRecord
from langstream_tpu.api.agent import (
    AgentSink,
    ComponentType,
    SingleRecordProcessor,
)
from langstream_tpu.api.doc import ConfigModel, ConfigProperty, props
from langstream_tpu.api.record import Record
from langstream_tpu.api.storage import AssetManager, DataSource, VectorDatabaseWriter
from langstream_tpu.core.registry import (
    REGISTRY,
    AgentTypeInfo,
    AssetTypeInfo,
    ResourceTypeInfo,
)

# ---------------------------------------------------------------------------
# SQLite datasource (the bundled "jdbc" driver)
# ---------------------------------------------------------------------------


class SqliteDataSource(DataSource):
    """`service: jdbc` datasource backed by stdlib sqlite3 (reference
    jdbc/JdbcDataSource). Queries use `?` positional params; sqlite calls run
    in a worker thread to keep the event loop free."""

    def __init__(self, config: dict[str, Any]) -> None:
        url = config.get("url", ":memory:")
        if url.startswith("jdbc:sqlite:"):
            url = url[len("jdbc:sqlite:") :]
        # URI-style urls (file:...?cache=shared — the only way to share an
        # in-memory DB between connections) need uri=True or sqlite treats
        # them as literal filenames.
        uri = url.startswith("file:")
        if url.startswith(":memory:"):
            url = ":memory:"
        self._conn = sqlite3.connect(url, uri=uri, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._lock = asyncio.Lock()

    async def fetch_data(self, query: str, params: list[Any]) -> list[dict[str, Any]]:
        async with self._lock:
            rows = await asyncio.to_thread(self._fetch, query, params)
        return rows

    def _fetch(self, query: str, params: list[Any]) -> list[dict[str, Any]]:
        cur = self._conn.execute(query, [_to_sql_param(p) for p in params])
        return [dict(r) for r in cur.fetchall()]

    async def execute_statement(self, query: str, params: list[Any]) -> dict[str, Any]:
        async with self._lock:
            return await asyncio.to_thread(self._execute, query, params)

    def _execute(self, query: str, params: list[Any]) -> dict[str, Any]:
        cur = self._conn.execute(query, [_to_sql_param(p) for p in params])
        self._conn.commit()
        return {"generated-keys": [cur.lastrowid], "count": cur.rowcount}

    async def close(self) -> None:
        self._conn.close()


def _to_sql_param(p: Any) -> Any:
    if isinstance(p, (list, dict)):
        return json.dumps(p)
    return p


class JdbcTableWriter(VectorDatabaseWriter):
    """vector-db-sink writer for SQL tables: upsert by configured fields
    (reference jdbc/JdbcWriter)."""

    def __init__(self, datasource: SqliteDataSource, config: dict[str, Any]) -> None:
        self.datasource = datasource
        self.table = config.get("table-name", "documents")
        self.fields = list(config.get("fields", []))

    async def upsert(self, record: Any, context: dict[str, Any]) -> None:
        ctx = MutableRecord.from_record(record)
        names, values, keys = [], [], []
        for f in self.fields:
            names.append(f["name"])
            values.append(_to_sql_param(el.evaluate(f.get("expression", "value"), ctx)))
            if f.get("primary-key"):
                keys.append(f["name"])
        cols = ", ".join(names)
        placeholders = ", ".join("?" for _ in names)
        sql = f"INSERT INTO {self.table} ({cols}) VALUES ({placeholders})"
        if keys:
            updates = ", ".join(f"{n}=excluded.{n}" for n in names if n not in keys)
            conflict = f" ON CONFLICT ({', '.join(keys)})"
            sql += f"{conflict} DO UPDATE SET {updates}" if updates else f"{conflict} DO NOTHING"
        await self.datasource.execute_statement(sql, values)


# ---------------------------------------------------------------------------
# Local TPU-first vector store
# ---------------------------------------------------------------------------


class _JitSimilarity:
    """Jitted cosine top-k over a padded [capacity, dim] matrix. Capacity
    doubles on growth, so XLA recompiles O(log n) times; each search is a
    single [1, dim] x [dim, capacity] matmul + top_k — the MXU-friendly
    brute-force layout (no index structure to maintain)."""

    def __init__(self) -> None:
        self._fn = None

    def __call__(self, query: np.ndarray, matrix: np.ndarray, valid: np.ndarray, k: int):
        import jax
        import jax.numpy as jnp

        if self._fn is None:

            @jax.jit
            def topk(q, m, mask, k=k):
                qn = q / (jnp.linalg.norm(q) + 1e-9)
                mn = m / (jnp.linalg.norm(m, axis=1, keepdims=True) + 1e-9)
                scores = mn @ qn  # [capacity]
                scores = jnp.where(mask, scores, -jnp.inf)
                return jax.lax.top_k(scores, k)

            self._fn = topk
        return self._fn(query, matrix, valid)


class LocalVectorDataSource(DataSource):
    """`service: local-vector` — an embedded vector database.

    Indexes hold (id, vector, metadata). Query dialect is JSON (the reference
    uses per-DB JSON dialects for Pinecone/Astra too):
        {"index": "docs", "vector": [...], "topK": 5, "include-metadata": true}
    Results: [{"id", "similarity", ...metadata}]. Writes go through the
    vector-db-sink writer. Persistence: optional `path` (one .npz + .json
    per index, saved on close/flush); default in-memory.
    """

    def __init__(self, config: dict[str, Any]) -> None:
        self._indexes: dict[str, dict[str, Any]] = {}
        self._path = config.get("path")
        self._searchers: dict[tuple[str, int, int], _JitSimilarity] = {}
        if self._path:
            self._load()

    def _index(self, name: str, dim: Optional[int] = None) -> dict[str, Any]:
        if name not in self._indexes:
            if dim is None:
                raise ValueError(f"vector index {name!r} does not exist")
            self._indexes[name] = {
                "dim": dim,
                "ids": [],
                "pos": {},
                "matrix": np.zeros((16, dim), dtype=np.float32),
                "meta": [],
            }
        return self._indexes[name]

    def create_index(self, name: str, dim: int) -> None:
        self._index(name, dim)

    def delete_index(self, name: str) -> None:
        self._indexes.pop(name, None)
        if self._path:
            from pathlib import Path

            for suffix in (".npz", ".json"):
                f = Path(self._path) / f"{name}{suffix}"
                if f.exists():
                    f.unlink()

    def flush(self) -> None:
        if self._path:
            self._save()

    def has_index(self, name: str) -> bool:
        return name in self._indexes

    def upsert(self, index: str, id_: str, vector: list[float], meta: dict[str, Any]) -> None:
        idx = self._index(index, dim=len(vector))
        vec = np.asarray(vector, dtype=np.float32)
        if vec.shape != (idx["dim"],):
            raise ValueError(f"vector dim {vec.shape} != index dim {idx['dim']}")
        if id_ in idx["pos"]:
            row = idx["pos"][id_]
            idx["matrix"][row] = vec
            idx["meta"][row] = meta
            return
        row = len(idx["ids"])
        if row >= idx["matrix"].shape[0]:
            grown = np.zeros((idx["matrix"].shape[0] * 2, idx["dim"]), dtype=np.float32)
            grown[:row] = idx["matrix"][:row]
            idx["matrix"] = grown
        idx["matrix"][row] = vec
        idx["ids"].append(id_)
        idx["pos"][id_] = row
        idx["meta"].append(meta)

    def search(
        self,
        index: str,
        vector: list[float],
        top_k: int = 5,
        include_vectors: bool = False,
    ) -> list[dict[str, Any]]:
        idx = self._index(index)
        n = len(idx["ids"])
        if n == 0:
            return []
        capacity = idx["matrix"].shape[0]
        k = min(top_k, capacity)
        searcher = self._searchers.setdefault((index, capacity, k), _JitSimilarity())
        valid = np.zeros(capacity, dtype=bool)
        valid[:n] = True
        scores, rows = searcher(
            np.asarray(vector, dtype=np.float32), idx["matrix"], valid, k
        )
        out = []
        for s, r in zip(np.asarray(scores), np.asarray(rows)):
            if not math.isfinite(float(s)):
                continue
            r = int(r)
            row = {"id": idx["ids"][r], "similarity": float(s), **idx["meta"][r]}
            if include_vectors:
                # opt-in (query "include-vectors": true): re-rankers need the
                # stored vector, but by default it would bloat every record
                # (and prompt) with dim floats per hit; placed AFTER the meta
                # spread so a stale meta "vector" cannot shadow it
                row["vector"] = idx["matrix"][r].tolist()
            out.append(row)
        return out[:top_k]

    # -- DataSource contract (JSON dialect) ---------------------------------

    async def fetch_data(self, query: str, params: list[Any]) -> list[dict[str, Any]]:
        q = json.loads(query) if isinstance(query, str) else dict(query)
        # positional params substitute "?" placeholders anywhere in the doc
        q = _substitute_params(q, list(params))
        index = q.get("index", "default")
        vector = q.get("vector")
        if vector is None:
            raise ValueError("local-vector query requires a 'vector' field")
        return self.search(
            index,
            vector,
            int(q.get("topK", q.get("top-k", 5))),
            include_vectors=bool(q.get("include-vectors", False)),
        )

    async def close(self) -> None:
        if self._path:
            self._save()

    # -- persistence --------------------------------------------------------

    def _save(self) -> None:
        from pathlib import Path

        root = Path(self._path)
        root.mkdir(parents=True, exist_ok=True)
        for name, idx in self._indexes.items():
            n = len(idx["ids"])
            np.savez(root / f"{name}.npz", matrix=idx["matrix"][:n])
            (root / f"{name}.json").write_text(
                json.dumps({"dim": idx["dim"], "ids": idx["ids"], "meta": idx["meta"]})
            )

    def _load(self) -> None:
        from pathlib import Path

        root = Path(self._path)
        if not root.exists():
            return
        for meta_file in root.glob("*.json"):
            name = meta_file.stem
            info = json.loads(meta_file.read_text())
            data = np.load(root / f"{name}.npz")["matrix"]
            self.create_index(name, info["dim"])
            for i, id_ in enumerate(info["ids"]):
                self.upsert(name, id_, data[i].tolist(), info["meta"][i])


def _substitute_params(obj: Any, params: list[Any]) -> Any:
    if isinstance(obj, dict):
        return {k: _substitute_params(v, params) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_substitute_params(v, params) for v in obj]
    if obj == "?" and params:
        return params.pop(0)
    return obj


class LocalVectorWriter(VectorDatabaseWriter):
    """vector-db-sink writer for the local vector store."""

    def __init__(self, datasource: LocalVectorDataSource, config: dict[str, Any]) -> None:
        self.datasource = datasource
        self.index = config.get("index-name", config.get("table-name", "default"))
        self.id_expr = config.get("id", "fn:uuid()")
        self.vector_expr = config.get("vector", "value.embeddings")
        self.metadata_fields = list(config.get("fields", []))

    async def upsert(self, record: Any, context: dict[str, Any]) -> None:
        ctx = MutableRecord.from_record(record)
        id_ = str(el.evaluate(self.id_expr, ctx))
        vector = el.evaluate(self.vector_expr, ctx)
        if vector is None:
            raise ValueError(f"vector expression {self.vector_expr!r} produced None")
        meta = {
            f["name"]: el.evaluate(f.get("expression", "value"), ctx)
            for f in self.metadata_fields
        }
        self.datasource.upsert(self.index, id_, list(map(float, vector)), meta)


# ---------------------------------------------------------------------------
# datasource resource resolution
# ---------------------------------------------------------------------------

# every reference datasource service is bundled SDK-free: sqlite/local
# here, HTTP APIs in remote.py/milvus.py, the CQL native protocol in
# cassandra.py (cql_protocol.py codec)


def build_datasource(config: dict[str, Any]) -> DataSource:
    service = config.get("service", "jdbc")
    if service in ("jdbc", "sqlite"):
        return SqliteDataSource(config)
    if service in ("local-vector", "in-memory", "tpu-vector"):
        return LocalVectorDataSource(config)
    if service in ("pinecone", "opensearch", "solr"):
        from langstream_tpu.agents.vector import remote

        cls = {
            "pinecone": remote.PineconeDataSource,
            "opensearch": remote.OpenSearchDataSource,
            "solr": remote.SolrDataSource,
        }[service]
        return cls(config)
    if service in ("cassandra", "astra", "astra-vector-db"):
        from langstream_tpu.agents.vector.cassandra import CassandraDataSource

        return CassandraDataSource(config)
    if service == "milvus":
        from langstream_tpu.agents.vector.milvus import MilvusDataSource

        return MilvusDataSource(config)
    raise ValueError(f"unknown datasource service {service!r}")


def build_writer(datasource: DataSource, config: dict[str, Any]) -> VectorDatabaseWriter:
    from langstream_tpu.agents.vector import remote

    if isinstance(datasource, LocalVectorDataSource):
        return LocalVectorWriter(datasource, config)
    if isinstance(datasource, SqliteDataSource):
        return JdbcTableWriter(datasource, config)
    if isinstance(datasource, remote.PineconeDataSource):
        return remote.PineconeWriter(datasource, config)
    if isinstance(datasource, remote.OpenSearchDataSource):
        return remote.OpenSearchWriter(datasource, config)
    if isinstance(datasource, remote.SolrDataSource):
        return remote.SolrWriter(datasource, config)
    from langstream_tpu.agents.vector.cassandra import (
        CassandraDataSource,
        CassandraWriter,
    )

    if isinstance(datasource, CassandraDataSource):
        return CassandraWriter(datasource, config)
    from langstream_tpu.agents.vector.milvus import MilvusDataSource, MilvusWriter

    if isinstance(datasource, MilvusDataSource):
        return MilvusWriter(datasource, config)
    raise ValueError(f"no vector writer for datasource {type(datasource).__name__}")


# ---------------------------------------------------------------------------
# agents
# ---------------------------------------------------------------------------


class VectorDBSinkAgent(AgentSink):
    """`vector-db-sink`: upsert each record into the configured datasource
    (reference VectorDBSinkAgent; per-DB writers)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self._config = dict(configuration)
        self._writer: Optional[VectorDatabaseWriter] = None

    async def start(self) -> None:
        assert self.context is not None
        registry = self.context.get_service_provider_registry()
        datasource = registry.get_datasource(self._config.get("datasource"))
        self._writer = build_writer(datasource, self._config)
        await self._writer.init(self._config)

    async def write(self, record: Record) -> None:
        assert self._writer is not None
        await self._writer.upsert(record, {})
        self.processed(1)

    async def close(self) -> None:
        if self._writer is not None:
            await self._writer.close()


class QueryVectorDBAgent(SingleRecordProcessor):
    """`query-vector-db`: standalone query agent (reference
    QueryVectorDBAgentProvider) — same semantics as the GenAI `query` step."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.query = configuration.get("query", "")
        self.fields = list(configuration.get("fields", []))
        self.output_field = configuration.get("output-field", "value.query-result")
        self.only_first = bool(configuration.get("only-first", False))
        self.mode = configuration.get("mode", "query")
        self.datasource_name = configuration.get("datasource")
        self._datasource: Optional[DataSource] = None

    async def start(self) -> None:
        assert self.context is not None
        registry = self.context.get_service_provider_registry()
        self._datasource = registry.get_datasource(self.datasource_name)

    async def process_record(self, record: Record) -> list[Record]:
        assert self._datasource is not None
        ctx = MutableRecord.from_record(record)
        params = [el.evaluate(f, ctx) for f in self.fields]
        if self.mode == "execute":
            result: Any = await self._datasource.execute_statement(self.query, params)
        else:
            rows = await self._datasource.fetch_data(self.query, params)
            result = (rows[0] if rows else None) if self.only_first else rows
        ctx.set_field(self.output_field, result)
        self.processed(1)
        return [ctx.to_record()]


def _cosine(a: np.ndarray, b: np.ndarray) -> float:
    denom = float(np.linalg.norm(a) * np.linalg.norm(b)) + 1e-9
    return float(np.dot(a, b)) / denom


class ReRankAgent(SingleRecordProcessor):
    """`re-rank`: re-order candidate documents against the query with MMR
    (Maximal Marginal Relevance) — reference rerank/ReRankAgent.java.

    Reads candidates from `field` (list of docs), the query embedding from
    `query-embeddings`, per-doc embeddings from `embeddings-field` (an EL
    evaluated with `record` bound to the doc), writes the top `max` docs to
    `output-field`.
    """

    async def init(self, configuration: dict[str, Any]) -> None:
        self.field = configuration.get("field", "value.query-result")
        self.output_field = configuration.get("output-field", self.field)
        self.query_embeddings = configuration.get("query-embeddings", "value.embeddings")
        self.embeddings_field = configuration.get("embeddings-field", "record.embeddings")
        self.text_field = configuration.get("text-field", "record.text")
        self.algorithm = configuration.get("algorithm", "MMR")
        self.lambda_ = float(configuration.get("lambda", 0.5))
        self.max = int(configuration.get("max", 5))
        # "documents" (default) writes the ranked doc dicts; "text" writes
        # only each doc's text — what prompt templates actually interpolate
        # (full dicts drag retrieval vectors into the prompt)
        self.output_mode = configuration.get("output-mode", "documents")

    async def process_record(self, record: Record) -> list[Record]:
        ctx = MutableRecord.from_record(record)
        docs = el.evaluate(self.field, ctx) or []
        query_vec = el.evaluate(self.query_embeddings, ctx)
        self.processed(1)
        if not docs or query_vec is None:
            ctx.set_field(self.output_field, self._project(docs, ctx))
            return [ctx.to_record()]
        q = np.asarray(query_vec, dtype=np.float32)
        vecs = []
        for d in docs:
            v = el.evaluate(self.embeddings_field, ctx, extra={"record": d})
            vecs.append(np.asarray(v, dtype=np.float32) if v is not None else None)

        if self.algorithm.upper() == "MMR":
            ranked = self._mmr(docs, vecs, q)
        else:  # plain cosine relevance
            scored = sorted(
                range(len(docs)),
                key=lambda i: -(_cosine(vecs[i], q) if vecs[i] is not None else -1.0),
            )
            ranked = [docs[i] for i in scored[: self.max]]
        ctx.set_field(self.output_field, self._project(ranked, ctx))
        return [ctx.to_record()]

    def _project(self, docs: list, ctx: MutableRecord) -> list:
        if self.output_mode != "text":
            return docs
        return [
            str(el.evaluate(self.text_field, ctx, extra={"record": d}) or "")
            for d in docs
        ]

    def _mmr(self, docs: list, vecs: list, q: np.ndarray) -> list:
        selected: list[int] = []
        candidates = [i for i in range(len(docs)) if vecs[i] is not None]
        while candidates and len(selected) < self.max:
            best, best_score = None, -np.inf
            for i in candidates:
                relevance = _cosine(vecs[i], q)
                redundancy = max(
                    (_cosine(vecs[i], vecs[j]) for j in selected), default=0.0
                )
                score = self.lambda_ * relevance - (1 - self.lambda_) * redundancy
                if score > best_score:
                    best, best_score = i, score
            assert best is not None
            selected.append(best)
            candidates.remove(best)
        return [docs[i] for i in selected]


class FlareControllerAgent(SingleRecordProcessor):
    """`flare-controller` (reference flare/FlareControllerAgent.java): FLARE
    active-RAG loop control. Inspects the tokens/logprobs of a generated
    answer; if any token's probability falls below `min-prob`, extracts the
    low-confidence span as a retrieval query, stores it in
    `retrieve-query-field` and routes the record to `loop-topic` for another
    retrieve→generate round; confident answers pass through."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.tokens_field = configuration.get("tokens-field", "value.tokens")
        self.logprobs_field = configuration.get("logprobs-field", "value.logprobs")
        self.min_prob = float(configuration.get("min-prob", 0.2))
        self.query_field = configuration.get("retrieve-query-field", "value.flare-query")
        self.loop_topic = configuration.get("loop-topic", "")

    async def process_record(self, record: Record) -> list[Record]:
        ctx = MutableRecord.from_record(record)
        tokens = el.evaluate(self.tokens_field, ctx) or []
        logprobs = el.evaluate(self.logprobs_field, ctx) or []
        self.processed(1)
        uncertain = [
            str(tok)
            for tok, lp in zip(tokens, logprobs)
            if math.exp(float(lp)) < self.min_prob
        ]
        if not uncertain:
            return [record]
        # the retrieval query is the low-confidence span, whitespace-joined
        query = re.sub(r"\s+", " ", " ".join(uncertain)).strip()
        ctx.set_field(self.query_field, query)
        if self.loop_topic:
            ctx.destination_topic = self.loop_topic
        return [ctx.to_record()]


# ---------------------------------------------------------------------------
# assets
# ---------------------------------------------------------------------------


class VectorIndexAssetManager(AssetManager):
    """`vector-index` asset: declaratively create/drop a local-vector index
    (the embedded analogue of the reference's per-DB index/table assets).

    Opens its own store instance, so in-memory stores won't share state with
    the pipeline — use a persistent `path` in BOTH the asset's datasource
    config and the `vector-database` resource (same caveat as jdbc-table's
    shared-cache URI)."""

    def __init__(self) -> None:
        self._asset = None
        self._name = ""
        self._path = None
        self._ds_config: dict[str, Any] = {}
        self._store: Optional[LocalVectorDataSource] = None

    async def initialize(self, asset) -> None:
        self._asset = asset
        name = asset.config.get("index-name")
        if not name:
            raise ValueError("vector-index asset requires config.index-name")
        self._name = str(name)
        ds_config = asset.config.get("datasource", {})
        if isinstance(ds_config, dict):
            ds_config = ds_config.get("configuration", ds_config)
        self._ds_config = dict(ds_config)
        self._path = self._ds_config.get("path")

    def _get_store(self) -> LocalVectorDataSource:
        # constructed lazily: loading a persistent store deserializes every
        # index, which an existence check must not pay
        if self._store is None:
            self._store = LocalVectorDataSource(self._ds_config)
        return self._store

    async def asset_exists(self) -> bool:
        if self._path:
            from pathlib import Path

            return (Path(self._path) / f"{self._name}.json").exists()
        return self._get_store().has_index(self._name)

    async def deploy_asset(self) -> None:
        assert self._asset is not None
        store = self._get_store()
        store.create_index(self._name, int(self._asset.config.get("dimension", 0)))
        store.flush()

    async def delete_asset(self) -> None:
        self._get_store().delete_index(self._name)


class JdbcTableAssetManager(AssetManager):
    """`jdbc-table` asset: create/drop a table via DDL statements in the
    asset config (reference JdbcAssetsManagerProvider)."""

    def __init__(self) -> None:
        self._asset = None
        self._datasource: Optional[SqliteDataSource] = None

    async def initialize(self, asset) -> None:
        self._asset = asset
        ds_config = asset.config.get("datasource", {})
        if isinstance(ds_config, dict):
            ds_config = ds_config.get("configuration", ds_config)
        # NOTE: this opens its own connection. For in-memory sqlite to be
        # visible to pipeline agents, use a shared-cache URI in BOTH the
        # asset and the datasource resource: url "file:name?mode=memory&cache=shared"
        self._datasource = SqliteDataSource(ds_config)

    async def close(self) -> None:
        if self._datasource is not None:
            await self._datasource.close()

    async def asset_exists(self) -> bool:
        assert self._asset and self._datasource
        table = self._asset.config.get("table-name", "")
        rows = await self._datasource.fetch_data(
            "SELECT name FROM sqlite_master WHERE type='table' AND name=?", [table]
        )
        return bool(rows)

    async def deploy_asset(self) -> None:
        assert self._asset and self._datasource
        for stmt in self._asset.config.get("create-statements", []):
            await self._datasource.execute_statement(stmt, [])

    async def delete_asset(self) -> None:
        assert self._asset and self._datasource
        stmts = self._asset.config.get("delete-statements") or [
            f"DROP TABLE IF EXISTS {self._asset.config.get('table-name', '')}"
        ]
        for stmt in stmts:
            await self._datasource.execute_statement(stmt, [])


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------


def _register() -> None:
    for rtype in ("datasource", "vector-database"):
        REGISTRY.register_resource(
            ResourceTypeInfo(
                type=rtype,
                factory=build_datasource,
                description="SQL or vector datasource (jdbc/sqlite or local-vector).",
                config_model=ConfigModel(
                    type=rtype,
                    properties=props(
                        ConfigProperty("service", "backend driver", required=True),
                        ConfigProperty("url", "connection url"),
                        ConfigProperty("path", "persistence dir (local-vector)"),
                    ),
                    allow_unknown=True,
                ),
            )
        )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="vector-db-sink",
            component_type=ComponentType.SINK,
            factory=VectorDBSinkAgent,
            description="Upsert records into a vector/SQL datasource.",
            config_model=ConfigModel(
                type="vector-db-sink",
                properties=props(
                    ConfigProperty("datasource", "resource id", required=True),
                    ConfigProperty("table-name", "SQL table (jdbc)"),
                    ConfigProperty("index-name", "vector index (local-vector)"),
                    ConfigProperty("id", "EL for the row/vector id"),
                    ConfigProperty("vector", "EL for the embedding vector"),
                    ConfigProperty("fields", "list of {name, expression, primary-key}", type="array"),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="query-vector-db",
            component_type=ComponentType.PROCESSOR,
            factory=QueryVectorDBAgent,
            composable=True,
            description="Query a vector/SQL datasource per record.",
            config_model=ConfigModel(
                type="query-vector-db",
                properties=props(
                    ConfigProperty("datasource", "resource id"),
                    ConfigProperty("query", "query text / JSON dialect", required=True),
                    ConfigProperty("fields", "EL expressions for params", type="array"),
                    ConfigProperty("output-field", "where results land", default="value.query-result"),
                    ConfigProperty("only-first", "store only the first row", type="boolean"),
                    ConfigProperty("mode", "query|execute", default="query"),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="re-rank",
            component_type=ComponentType.PROCESSOR,
            factory=ReRankAgent,
            composable=True,
            description="Re-rank retrieved documents (MMR).",
            config_model=ConfigModel(
                type="re-rank",
                properties=props(
                    ConfigProperty("field", "EL for the candidate docs list"),
                    ConfigProperty("output-field", "where ranked docs land"),
                    ConfigProperty("query-embeddings", "EL for the query vector"),
                    ConfigProperty("embeddings-field", "EL for a doc's vector (record bound)"),
                    ConfigProperty("text-field", "EL for a doc's text (record bound)"),
                    ConfigProperty("algorithm", "MMR|cosine", default="MMR"),
                    ConfigProperty("output-mode", "documents|text", default="documents"),
                    ConfigProperty("lambda", "MMR relevance/diversity trade-off", type="number", default=0.5),
                    ConfigProperty("max", "documents to keep", type="integer", default=5),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="flare-controller",
            component_type=ComponentType.PROCESSOR,
            factory=FlareControllerAgent,
            composable=False,  # routes to the loop topic
            description="FLARE active-RAG loop controller.",
            config_model=ConfigModel(
                type="flare-controller",
                properties=props(
                    ConfigProperty("tokens-field", "EL for generated tokens"),
                    ConfigProperty("logprobs-field", "EL for per-token logprobs"),
                    ConfigProperty("min-prob", "confidence threshold", type="number", default=0.2),
                    ConfigProperty("retrieve-query-field", "where the retrieval query lands"),
                    ConfigProperty("loop-topic", "topic for another RAG round"),
                ),
            ),
        )
    )
    REGISTRY.register_asset(
        AssetTypeInfo(
            type="vector-index",
            factory=VectorIndexAssetManager,
            description="Create/drop a local-vector index declaratively.",
            config_model=ConfigModel(
                type="vector-index",
                properties=props(
                    ConfigProperty("index-name", "index to manage", required=True),
                    ConfigProperty("dimension", "vector dimension", type="integer", required=True),
                    ConfigProperty("datasource", "datasource config", type="object"),
                ),
                allow_unknown=True,
            ),
        )
    )
    REGISTRY.register_asset(
        AssetTypeInfo(
            type="jdbc-table",
            factory=JdbcTableAssetManager,
            description="Create/drop a SQL table from DDL statements.",
            config_model=ConfigModel(
                type="jdbc-table",
                properties=props(
                    ConfigProperty("table-name", "table to manage", required=True),
                    ConfigProperty("create-statements", "DDL to create", type="array"),
                    ConfigProperty("delete-statements", "DDL to drop", type="array"),
                    ConfigProperty("datasource", "datasource config", type="object"),
                ),
                allow_unknown=True,
            ),
        )
    )

    def _cassandra_table_factory():
        from langstream_tpu.agents.vector.cassandra import CassandraTableAssetManager

        return CassandraTableAssetManager()

    def _cassandra_keyspace_factory():
        from langstream_tpu.agents.vector.cassandra import (
            CassandraKeyspaceAssetManager,
        )

        return CassandraKeyspaceAssetManager()

    for type_ in ("cassandra-table", "astra-table"):
        REGISTRY.register_asset(
            AssetTypeInfo(
                type=type_,
                factory=_cassandra_table_factory,
                description="Create/drop a Cassandra/Astra table from CQL DDL.",
                config_model=ConfigModel(
                    type=type_,
                    properties=props(
                        ConfigProperty("table-name", "table to manage", required=True),
                        ConfigProperty("keyspace", "keyspace"),
                        ConfigProperty("create-statements", "CQL DDL", type="array"),
                        ConfigProperty("delete-statements", "CQL DDL", type="array"),
                        ConfigProperty("datasource", "datasource config", type="object"),
                    ),
                    allow_unknown=True,
                ),
            )
        )
    for type_ in ("cassandra-keyspace", "astra-keyspace"):
        REGISTRY.register_asset(
            AssetTypeInfo(
                type=type_,
                factory=_cassandra_keyspace_factory,
                description="Create/drop a Cassandra/Astra keyspace.",
                config_model=ConfigModel(
                    type=type_,
                    properties=props(
                        ConfigProperty("keyspace", "keyspace to manage", required=True),
                        ConfigProperty("datasource", "datasource config", type="object"),
                    ),
                    allow_unknown=True,
                ),
            )
        )

    def _milvus_collection_factory():
        from langstream_tpu.agents.vector.milvus import MilvusCollectionAssetManager

        return MilvusCollectionAssetManager()

    REGISTRY.register_asset(
        AssetTypeInfo(
            type="milvus-collection",
            factory=_milvus_collection_factory,
            description="Create/drop a Milvus collection (REST v2 API).",
            config_model=ConfigModel(
                type="milvus-collection",
                properties=props(
                    ConfigProperty("collection-name", "collection", required=True),
                    ConfigProperty("dimension", "vector dim", type="integer"),
                    ConfigProperty("datasource", "datasource config", type="object"),
                ),
                allow_unknown=True,
            ),
        )
    )


_register()
