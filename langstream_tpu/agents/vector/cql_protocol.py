"""Cassandra CQL native protocol v4 — stdlib-only codec.

Parity: reference `langstream-vector-agents/.../cassandra/` talks to
Cassandra/Astra through the DataStax Java driver; this rebuild speaks the
native protocol directly (the `kafka_protocol.py` approach — no driver, no
SDK). Framing (protocol spec v4):

    [version u8][flags u8][stream i16][opcode u8][length u32][body]

Request version 0x04, response 0x84. The subset implemented is what the
vector datasource/writer agents need: STARTUP/READY, the SASL-plain
AUTHENTICATE dance (Astra's token auth: user ``token``, password
``AstraCS:...``), QUERY with bound positional values, and RESULT decoding
(Void / Rows / SetKeyspace / SchemaChange) with the common CQL types plus
``vector<float, n>`` (Cassandra 5 / Astra vector search).
"""

from __future__ import annotations

import struct
import uuid as uuid_mod
from typing import Any, Optional

VERSION_REQUEST = 0x04
VERSION_RESPONSE = 0x84

OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_OPTIONS = 0x05
OP_SUPPORTED = 0x06
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_PREPARE = 0x09
OP_EXECUTE = 0x0A
OP_AUTH_CHALLENGE = 0x0E
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

RESULT_VOID = 0x0001
RESULT_ROWS = 0x0002
RESULT_SET_KEYSPACE = 0x0003
RESULT_PREPARED = 0x0004
RESULT_SCHEMA_CHANGE = 0x0005

CONSISTENCY_ONE = 0x0001
CONSISTENCY_QUORUM = 0x0004
CONSISTENCY_LOCAL_QUORUM = 0x0006

# type option ids (spec §6.2.1)
T_CUSTOM = 0x0000
T_ASCII = 0x0001
T_BIGINT = 0x0002
T_BLOB = 0x0003
T_BOOLEAN = 0x0004
T_COUNTER = 0x0005
T_DECIMAL = 0x0006
T_DOUBLE = 0x0007
T_FLOAT = 0x0008
T_INT = 0x0009
T_TIMESTAMP = 0x000B
T_UUID = 0x000C
T_VARCHAR = 0x000D
T_VARINT = 0x000E
T_TIMEUUID = 0x000F
T_INET = 0x0010
T_SMALLINT = 0x0013
T_TINYINT = 0x0014
T_LIST = 0x0020
T_MAP = 0x0021
T_SET = 0x0022

VECTOR_CLASS = "org.apache.cassandra.db.marshal.VectorType"


class CqlError(RuntimeError):
    def __init__(self, code: int, message: str) -> None:
        super().__init__(f"CQL error 0x{code:04x}: {message}")
        self.code = code
        self.message = message


# ---------------------------------------------------------------------------
# primitive writers / readers
# ---------------------------------------------------------------------------


class Writer:
    def __init__(self) -> None:
        self.buf = bytearray()

    def u8(self, v: int) -> "Writer":
        self.buf += struct.pack(">B", v)
        return self

    def u16(self, v: int) -> "Writer":
        self.buf += struct.pack(">H", v)
        return self

    def i16(self, v: int) -> "Writer":
        self.buf += struct.pack(">h", v)
        return self

    def i32(self, v: int) -> "Writer":
        self.buf += struct.pack(">i", v)
        return self

    def i64(self, v: int) -> "Writer":
        self.buf += struct.pack(">q", v)
        return self

    def string(self, s: str) -> "Writer":
        data = s.encode()
        self.u16(len(data))
        self.buf += data
        return self

    def long_string(self, s: str) -> "Writer":
        data = s.encode()
        self.i32(len(data))
        self.buf += data
        return self

    def bytes_(self, b: Optional[bytes]) -> "Writer":
        if b is None:
            self.i32(-1)
        else:
            self.i32(len(b))
            self.buf += b
        return self

    def short_bytes(self, b: bytes) -> "Writer":
        self.u16(len(b))
        self.buf += b
        return self

    def string_map(self, m: dict[str, str]) -> "Writer":
        self.u16(len(m))
        for k, v in m.items():
            self.string(k)
            self.string(v)
        return self

    def build(self) -> bytes:
        return bytes(self.buf)


class Reader:
    def __init__(self, buf: bytes) -> None:
        self.buf = buf
        self.pos = 0

    def u8(self) -> int:
        (v,) = struct.unpack_from(">B", self.buf, self.pos)
        self.pos += 1
        return v

    def u16(self) -> int:
        (v,) = struct.unpack_from(">H", self.buf, self.pos)
        self.pos += 2
        return v

    def i16(self) -> int:
        (v,) = struct.unpack_from(">h", self.buf, self.pos)
        self.pos += 2
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from(">i", self.buf, self.pos)
        self.pos += 4
        return v

    def i64(self) -> int:
        (v,) = struct.unpack_from(">q", self.buf, self.pos)
        self.pos += 8
        return v

    def string(self) -> str:
        n = self.u16()
        s = self.buf[self.pos : self.pos + n].decode()
        self.pos += n
        return s

    def long_string(self) -> str:
        n = self.i32()
        s = self.buf[self.pos : self.pos + n].decode()
        self.pos += n
        return s

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        b = self.buf[self.pos : self.pos + n]
        self.pos += n
        return b

    def string_map(self) -> dict[str, str]:
        return {self.string(): self.string() for _ in range(self.u16())}

    def short_bytes(self) -> bytes:
        n = self.u16()
        b = self.buf[self.pos : self.pos + n]
        self.pos += n
        return b

    def remaining(self) -> int:
        return len(self.buf) - self.pos


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def frame(opcode: int, body: bytes, stream: int = 0, version: int = VERSION_REQUEST) -> bytes:
    return struct.pack(">BBhBI", version, 0, stream, opcode, len(body)) + body


def parse_header(header: bytes) -> tuple[int, int, int, int]:
    """→ (version, stream, opcode, body length)."""
    version, _flags, stream, opcode, length = struct.unpack(">BBhBI", header)
    return version, stream, opcode, length


HEADER_SIZE = 9


# ---------------------------------------------------------------------------
# type options (result metadata)
# ---------------------------------------------------------------------------


def write_type(w: Writer, type_: Any) -> None:
    """type_ is an int id, ("list", inner), ("set", inner), ("map", k, v) or
    ("vector", n)."""
    if isinstance(type_, int):
        w.u16(type_)
        return
    kind = type_[0]
    if kind == "list":
        w.u16(T_LIST)
        write_type(w, type_[1])
    elif kind == "set":
        w.u16(T_SET)
        write_type(w, type_[1])
    elif kind == "map":
        w.u16(T_MAP)
        write_type(w, type_[1])
        write_type(w, type_[2])
    elif kind == "vector":
        w.u16(T_CUSTOM)
        w.string(f"{VECTOR_CLASS}(FloatType, {type_[1]})")
    else:  # pragma: no cover - schema bug
        raise TypeError(f"bad type {type_!r}")


def read_type(r: Reader) -> Any:
    id_ = r.u16()
    if id_ == T_LIST:
        return ("list", read_type(r))
    if id_ == T_SET:
        return ("set", read_type(r))
    if id_ == T_MAP:
        return ("map", read_type(r), read_type(r))
    if id_ == T_CUSTOM:
        cls = r.string()
        if cls.startswith(VECTOR_CLASS):
            inner = cls[len(VECTOR_CLASS) :].strip("()")
            n = int(inner.split(",")[-1].strip()) if "," in inner else 0
            return ("vector", n)
        return ("custom", cls)
    return id_


# ---------------------------------------------------------------------------
# value codecs (python ↔ CQL binary)
# ---------------------------------------------------------------------------


def encode_value(type_: Any, v: Any) -> Optional[bytes]:
    if v is None:
        return None
    if isinstance(type_, tuple):
        kind = type_[0]
        if kind in ("list", "set"):
            out = bytearray(struct.pack(">i", len(v)))
            for item in v:
                b = encode_value(type_[1], item)
                out += struct.pack(">i", -1) if b is None else struct.pack(">i", len(b)) + b
            return bytes(out)
        if kind == "map":
            out = bytearray(struct.pack(">i", len(v)))
            for k, item in v.items():
                kb = encode_value(type_[1], k) or b""
                vb = encode_value(type_[2], item)
                out += struct.pack(">i", len(kb)) + kb
                out += struct.pack(">i", -1) if vb is None else struct.pack(">i", len(vb)) + vb
            return bytes(out)
        if kind == "vector":
            # fixed-length float32 array, NO per-element length prefixes
            return struct.pack(f">{len(v)}f", *[float(x) for x in v])
        raise TypeError(f"bad type {type_!r}")
    if type_ in (T_ASCII, T_VARCHAR):
        return str(v).encode()
    if type_ == T_BLOB:
        return bytes(v)
    if type_ == T_BOOLEAN:
        return b"\x01" if v else b"\x00"
    if type_ in (T_BIGINT, T_TIMESTAMP, T_COUNTER):
        return struct.pack(">q", int(v))
    if type_ == T_INT:
        return struct.pack(">i", int(v))
    if type_ == T_SMALLINT:
        return struct.pack(">h", int(v))
    if type_ == T_TINYINT:
        return struct.pack(">b", int(v))
    if type_ == T_DOUBLE:
        return struct.pack(">d", float(v))
    if type_ == T_FLOAT:
        return struct.pack(">f", float(v))
    if type_ in (T_UUID, T_TIMEUUID):
        u = v if isinstance(v, uuid_mod.UUID) else uuid_mod.UUID(str(v))
        return u.bytes
    if type_ == T_VARINT:
        n = int(v)
        length = max(1, (n.bit_length() + 8) // 8)
        return n.to_bytes(length, "big", signed=True)
    raise TypeError(f"cannot encode CQL type {type_!r}")


def decode_value(type_: Any, b: Optional[bytes]) -> Any:
    if b is None:
        return None
    if isinstance(type_, tuple):
        kind = type_[0]
        if kind in ("list", "set"):
            r = Reader(b)
            n = r.i32()
            return [decode_value(type_[1], r.bytes_()) for _ in range(n)]
        if kind == "map":
            r = Reader(b)
            n = r.i32()
            return {
                decode_value(type_[1], r.bytes_()): decode_value(type_[2], r.bytes_())
                for _ in range(n)
            }
        if kind == "vector":
            n = len(b) // 4
            return list(struct.unpack(f">{n}f", b))
        if kind == "custom":
            return b
        raise TypeError(f"bad type {type_!r}")
    if type_ in (T_ASCII, T_VARCHAR):
        return b.decode()
    if type_ == T_BLOB:
        return b
    if type_ == T_BOOLEAN:
        return b != b"\x00"
    if type_ in (T_BIGINT, T_TIMESTAMP, T_COUNTER):
        return struct.unpack(">q", b)[0]
    if type_ == T_INT:
        return struct.unpack(">i", b)[0]
    if type_ == T_SMALLINT:
        return struct.unpack(">h", b)[0]
    if type_ == T_TINYINT:
        return struct.unpack(">b", b)[0]
    if type_ == T_DOUBLE:
        return struct.unpack(">d", b)[0]
    if type_ == T_FLOAT:
        return struct.unpack(">f", b)[0]
    if type_ in (T_UUID, T_TIMEUUID):
        return str(uuid_mod.UUID(bytes=b))
    if type_ == T_VARINT:
        return int.from_bytes(b, "big", signed=True)
    return b


def guess_type(v: Any) -> Any:
    """Binding helper for un-prepared QUERY values: infer the CQL type from
    the python value (matches how the agents bind positional params)."""
    if isinstance(v, bool):
        return T_BOOLEAN
    if isinstance(v, int):
        return T_BIGINT
    if isinstance(v, float):
        return T_DOUBLE
    if isinstance(v, bytes):
        return T_BLOB
    if isinstance(v, uuid_mod.UUID):
        return T_UUID
    if isinstance(v, (list, tuple)):
        if v and all(isinstance(x, (int, float)) for x in v):
            return ("vector", len(v))
        return ("list", T_VARCHAR)
    if isinstance(v, dict):
        return ("map", T_VARCHAR, T_VARCHAR)
    return T_VARCHAR


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------


def startup_body() -> bytes:
    return Writer().string_map({"CQL_VERSION": "3.0.0"}).build()


def auth_response_body(username: str, password: str) -> bytes:
    token = b"\x00" + username.encode() + b"\x00" + password.encode()
    return Writer().bytes_(token).build()


QUERY_FLAG_VALUES = 0x01


def query_body(
    query: str,
    values: Optional[list[Any]] = None,
    consistency: int = CONSISTENCY_LOCAL_QUORUM,
) -> bytes:
    w = Writer().long_string(query)
    w.u16(consistency)
    if values:
        w.u8(QUERY_FLAG_VALUES)
        w.u16(len(values))
        for v in values:
            w.bytes_(encode_value(guess_type(v), v))
    else:
        w.u8(0)
    return w.build()


def prepare_body(query: str) -> bytes:
    return Writer().long_string(query).build()


def execute_body(
    prepared_id: bytes,
    bind_types: list[Any],
    values: list[Any],
    consistency: int = CONSISTENCY_LOCAL_QUORUM,
) -> bytes:
    """EXECUTE of a prepared statement: values encoded with the
    SERVER-declared bind types (native protocol v4 §4.1.6) — the reason
    prepared statements exist: an `int`/`smallint`/`float` column rejects
    the widths guess_type would pick for plain python numbers."""
    if len(values) != len(bind_types):
        raise CqlError(
            0x2200,
            f"statement has {len(bind_types)} bind markers but "
            f"{len(values)} values were supplied",
        )
    w = Writer().short_bytes(prepared_id)
    w.u16(consistency)
    if values:
        w.u8(QUERY_FLAG_VALUES)
        w.u16(len(values))
        for type_, v in zip(bind_types, values):
            w.bytes_(encode_value(type_, v))
    else:
        w.u8(0)
    return w.build()


def prepared_result_body(prepared_id: bytes, bind_types: list[Any]) -> bytes:
    """RESULT/Prepared (v4 §4.2.5.4): id + bind-variable metadata (types the
    client must use in EXECUTE) + empty result metadata (NO_METADATA)."""
    w = Writer().i32(RESULT_PREPARED)
    w.short_bytes(prepared_id)
    w.i32(0)  # metadata flags: no global table spec
    w.i32(len(bind_types))
    w.i32(0)  # pk_count
    for i, type_ in enumerate(bind_types):
        w.string("")  # keyspace
        w.string("")  # table
        w.string(f"p{i}")
        write_type(w, type_)
    w.i32(0x0004)  # result metadata: NO_METADATA
    w.i32(0)
    return w.build()


def parse_prepare_body(body: bytes) -> str:
    return Reader(body).long_string()


def parse_execute_body(body: bytes) -> tuple[bytes, list[Optional[bytes]], int]:
    """Server side: → (prepared id, raw value blobs, consistency)."""
    r = Reader(body)
    prepared_id = r.short_bytes()
    consistency = r.u16()
    flags = r.u8()
    raw_values: list[Optional[bytes]] = []
    if flags & QUERY_FLAG_VALUES:
        n = r.u16()
        raw_values = [r.bytes_() for _ in range(n)]
    return prepared_id, raw_values, consistency


def parse_query_body(body: bytes) -> tuple[str, list[Optional[bytes]], int]:
    """Server side: → (query, raw value blobs, consistency)."""
    r = Reader(body)
    query = r.long_string()
    consistency = r.u16()
    flags = r.u8()
    raw_values: list[Optional[bytes]] = []
    if flags & QUERY_FLAG_VALUES:
        n = r.u16()
        raw_values = [r.bytes_() for _ in range(n)]
    return query, raw_values, consistency


ROWS_FLAG_GLOBAL_TABLES_SPEC = 0x0001


def rows_body(
    keyspace: str,
    table: str,
    columns: list[tuple[str, Any]],
    rows: list[list[Any]],
) -> bytes:
    """RESULT/Rows with global table spec; columns = [(name, type), ...]."""
    w = Writer()
    w.i32(RESULT_ROWS)
    w.i32(ROWS_FLAG_GLOBAL_TABLES_SPEC)
    w.i32(len(columns))
    w.string(keyspace)
    w.string(table)
    for name, type_ in columns:
        w.string(name)
        write_type(w, type_)
    w.i32(len(rows))
    for row in rows:
        for (name, type_), value in zip(columns, row):
            w.bytes_(encode_value(type_, value))
    return w.build()


def void_body() -> bytes:
    return Writer().i32(RESULT_VOID).build()


def schema_change_body(change: str, target: str, keyspace: str, name: str = "") -> bytes:
    w = Writer().i32(RESULT_SCHEMA_CHANGE)
    w.string(change)
    w.string(target)
    w.string(keyspace)
    if target != "KEYSPACE":
        w.string(name)
    return w.build()


def error_body(code: int, message: str) -> bytes:
    return Writer().i32(code).string(message).build()


def parse_result_body(body: bytes) -> dict[str, Any]:
    """Client side: RESULT body → {"kind": ..., "rows": [dict], ...}."""
    r = Reader(body)
    kind = r.i32()
    if kind == RESULT_VOID:
        return {"kind": "void"}
    if kind == RESULT_SET_KEYSPACE:
        return {"kind": "set_keyspace", "keyspace": r.string()}
    if kind == RESULT_SCHEMA_CHANGE:
        return {"kind": "schema_change", "change": r.string(), "target": r.string()}
    if kind == RESULT_PREPARED:
        prepared_id = r.short_bytes()
        flags = r.i32()
        n_cols = r.i32()
        pk_count = r.i32()
        for _ in range(pk_count):
            r.u16()
        if flags & ROWS_FLAG_GLOBAL_TABLES_SPEC:
            r.string()
            r.string()
        bind_types: list[Any] = []
        for _ in range(n_cols):
            if not flags & ROWS_FLAG_GLOBAL_TABLES_SPEC:
                r.string()
                r.string()
            r.string()  # name
            bind_types.append(read_type(r))
        return {"kind": "prepared", "id": prepared_id, "bind_types": bind_types}
    if kind != RESULT_ROWS:
        return {"kind": f"unknown_{kind}"}
    flags = r.i32()
    n_cols = r.i32()
    if flags & 0x0002:  # has_more_pages → paging state
        r.bytes_()
    names: list[str] = []
    types: list[Any] = []
    if not flags & 0x0004:  # no_metadata not set
        if flags & ROWS_FLAG_GLOBAL_TABLES_SPEC:
            r.string()
            r.string()
        for _ in range(n_cols):
            if not flags & ROWS_FLAG_GLOBAL_TABLES_SPEC:
                r.string()
                r.string()
            names.append(r.string())
            types.append(read_type(r))
    n_rows = r.i32()
    rows = []
    for _ in range(n_rows):
        row = {}
        for name, type_ in zip(names, types):
            row[name] = decode_value(type_, r.bytes_())
        rows.append(row)
    return {"kind": "rows", "rows": rows, "columns": names}


def parse_error_body(body: bytes) -> CqlError:
    r = Reader(body)
    return CqlError(r.i32(), r.string())
