"""Protocol-level fake Cassandra for tests (the `kafka_fake.py` pattern).

Speaks the CQL v4 subset the client in ``cassandra.py`` does — STARTUP/READY
(optionally the AUTHENTICATE SASL-plain dance, for the Astra token-auth
path), QUERY with bound positional values, Rows/Void/SchemaChange/Error
results — over a real asyncio socket, backed by a small in-memory table
engine that understands the statements the vector agents generate:

    CREATE KEYSPACE / DROP KEYSPACE / USE
    CREATE TABLE (typed columns incl. vector<float, n>) / DROP TABLE
    CREATE [CUSTOM] INDEX (no-op)
    INSERT INTO t (cols) VALUES (?, ...)        -- upsert by primary key
    SELECT cols FROM t [WHERE c = ? [AND ...]] [ORDER BY c ANN OF ?] [LIMIT n]
    DELETE FROM t WHERE c = ?
    SELECT ... FROM system_schema.{tables,keyspaces} WHERE ...

ANN ordering uses cosine similarity (the Astra vector-search default).
This stands in for testcontainers Cassandra in an image with no JVM and no
network egress.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import math
import re
import struct
from dataclasses import dataclass, field
from typing import Any, Optional

from langstream_tpu.agents.vector import cql_protocol as wire

log = logging.getLogger(__name__)

_TYPE_NAMES = {
    "ascii": wire.T_ASCII,
    "text": wire.T_VARCHAR,
    "varchar": wire.T_VARCHAR,
    "int": wire.T_INT,
    "bigint": wire.T_BIGINT,
    "smallint": wire.T_SMALLINT,
    "tinyint": wire.T_TINYINT,
    "varint": wire.T_VARINT,
    "float": wire.T_FLOAT,
    "double": wire.T_DOUBLE,
    "boolean": wire.T_BOOLEAN,
    "blob": wire.T_BLOB,
    "uuid": wire.T_UUID,
    "timeuuid": wire.T_TIMEUUID,
    "timestamp": wire.T_TIMESTAMP,
    "counter": wire.T_COUNTER,
}


def parse_col_type(spec: str) -> Any:
    spec = spec.strip().lower()
    m = re.match(r"vector\s*<\s*float\s*,\s*(\d+)\s*>", spec)
    if m:
        return ("vector", int(m.group(1)))
    m = re.match(r"(list|set)\s*<\s*(\w+)\s*>", spec)
    if m:
        return (m.group(1), _TYPE_NAMES.get(m.group(2), wire.T_VARCHAR))
    m = re.match(r"map\s*<\s*(\w+)\s*,\s*(\w+)\s*>", spec)
    if m:
        return (
            "map",
            _TYPE_NAMES.get(m.group(1), wire.T_VARCHAR),
            _TYPE_NAMES.get(m.group(2), wire.T_VARCHAR),
        )
    return _TYPE_NAMES.get(spec, wire.T_VARCHAR)


def _decode_bound(col_type: Any, b: Optional[bytes]) -> Any:
    """Decode a bound value tolerantly: un-prepared QUERY values are typed by
    the CLIENT's guess (e.g. python int → 8-byte bigint even for an `int`
    column), so integer/float widths are taken from the bytes, not the
    declared column."""
    if b is None:
        return None
    if isinstance(col_type, tuple):
        if col_type[0] == "vector":
            n = len(b) // 4
            return list(struct.unpack(f">{n}f", b))
        return wire.decode_value(col_type, b)
    if col_type in (
        wire.T_INT, wire.T_BIGINT, wire.T_SMALLINT, wire.T_TINYINT,
        wire.T_TIMESTAMP, wire.T_COUNTER, wire.T_VARINT,
    ):
        return int.from_bytes(b, "big", signed=True)
    if col_type in (wire.T_FLOAT, wire.T_DOUBLE):
        return struct.unpack(">f" if len(b) == 4 else ">d", b)[0]
    return wire.decode_value(col_type, b)


@dataclass
class _Table:
    keyspace: str
    name: str
    columns: dict[str, Any]  # name → type
    primary_key: list[str]
    rows: dict[tuple, dict[str, Any]] = field(default_factory=dict)


class FakeCassandra:
    """Single-node fake; optional SASL-plain auth (Astra token mode)."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        require_auth: Optional[tuple[str, str]] = None,
    ) -> None:
        self.host = host
        self.port = port
        self.require_auth = require_auth
        self.keyspaces: set[str] = {"system"}
        self.tables: dict[tuple[str, str], _Table] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._writers: set[asyncio.StreamWriter] = set()
        self.queries: list[str] = []  # observability for tests
        # prepared id → (query, server-declared bind types)
        self._prepared: dict[bytes, tuple[str, list[Any]]] = {}

    async def start(self) -> "FakeCassandra":
        self._server = await asyncio.start_server(self._serve, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def contact_point(self) -> str:
        return f"{self.host}:{self.port}"

    # -- connection ----------------------------------------------------------

    async def _serve(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        keyspace: list[Optional[str]] = [None]  # per-connection USE state
        authenticated = self.require_auth is None
        try:
            while True:
                header = await reader.readexactly(wire.HEADER_SIZE)
                _, stream, opcode, length = wire.parse_header(header)
                body = await reader.readexactly(length) if length else b""
                if opcode == wire.OP_STARTUP:
                    if self.require_auth:
                        out = wire.frame(
                            wire.OP_AUTHENTICATE,
                            wire.Writer()
                            .string("org.apache.cassandra.auth.PasswordAuthenticator")
                            .build(),
                            stream,
                            wire.VERSION_RESPONSE,
                        )
                    else:
                        out = wire.frame(
                            wire.OP_READY, b"", stream, wire.VERSION_RESPONSE
                        )
                elif opcode == wire.OP_AUTH_RESPONSE:
                    token = wire.Reader(body).bytes_() or b""
                    parts = token.split(b"\x00")
                    user = parts[1].decode() if len(parts) > 1 else ""
                    pwd = parts[2].decode() if len(parts) > 2 else ""
                    if self.require_auth and (user, pwd) == self.require_auth:
                        authenticated = True
                        out = wire.frame(
                            wire.OP_AUTH_SUCCESS,
                            wire.Writer().bytes_(None).build(),
                            stream,
                            wire.VERSION_RESPONSE,
                        )
                    else:
                        out = wire.frame(
                            wire.OP_ERROR,
                            wire.error_body(0x0100, "bad credentials"),
                            stream,
                            wire.VERSION_RESPONSE,
                        )
                elif opcode == wire.OP_QUERY:
                    if not authenticated:
                        out = wire.frame(
                            wire.OP_ERROR,
                            wire.error_body(0x0100, "not authenticated"),
                            stream,
                            wire.VERSION_RESPONSE,
                        )
                    else:
                        query, raw_values, _ = wire.parse_query_body(body)
                        self.queries.append(query)
                        try:
                            result = self._execute(query, raw_values, keyspace)
                            out = wire.frame(
                                wire.OP_RESULT, result, stream, wire.VERSION_RESPONSE
                            )
                        except wire.CqlError as e:
                            out = wire.frame(
                                wire.OP_ERROR,
                                wire.error_body(e.code, e.message),
                                stream,
                                wire.VERSION_RESPONSE,
                            )
                        except Exception as e:  # noqa: BLE001 — surface as CQL error
                            log.exception("fake cassandra: query failed: %s", query)
                            out = wire.frame(
                                wire.OP_ERROR,
                                wire.error_body(0x2000, str(e)),
                                stream,
                                wire.VERSION_RESPONSE,
                            )
                elif opcode in (wire.OP_PREPARE, wire.OP_EXECUTE) and not authenticated:
                    out = wire.frame(
                        wire.OP_ERROR,
                        wire.error_body(0x0100, "not authenticated"),
                        stream,
                        wire.VERSION_RESPONSE,
                    )
                elif opcode == wire.OP_PREPARE:
                    query = wire.parse_prepare_body(body)
                    self.queries.append(f"PREPARE: {query}")
                    try:
                        bind_types = self._bind_types(query, keyspace)
                        prepared_id = hashlib.md5(query.encode()).digest()
                        self._prepared[prepared_id] = (query, bind_types)
                        out = wire.frame(
                            wire.OP_RESULT,
                            wire.prepared_result_body(prepared_id, bind_types),
                            stream,
                            wire.VERSION_RESPONSE,
                        )
                    except wire.CqlError as e:
                        out = wire.frame(
                            wire.OP_ERROR,
                            wire.error_body(e.code, e.message),
                            stream,
                            wire.VERSION_RESPONSE,
                        )
                elif opcode == wire.OP_EXECUTE:
                    prepared_id, raw_values, _ = wire.parse_execute_body(body)
                    entry = self._prepared.get(prepared_id)
                    if entry is None:
                        out = wire.frame(
                            wire.OP_ERROR,
                            wire.error_body(0x2500, "unprepared statement"),
                            stream,
                            wire.VERSION_RESPONSE,
                        )
                    else:
                        query, _ = entry
                        self.queries.append(query)
                        try:
                            result = self._execute(query, raw_values, keyspace)
                            out = wire.frame(
                                wire.OP_RESULT, result, stream, wire.VERSION_RESPONSE
                            )
                        except wire.CqlError as e:
                            out = wire.frame(
                                wire.OP_ERROR,
                                wire.error_body(e.code, e.message),
                                stream,
                                wire.VERSION_RESPONSE,
                            )
                        except Exception as e:  # noqa: BLE001
                            log.exception("fake cassandra: execute failed: %s", query)
                            out = wire.frame(
                                wire.OP_ERROR,
                                wire.error_body(0x2000, str(e)),
                                stream,
                                wire.VERSION_RESPONSE,
                            )
                elif opcode == wire.OP_OPTIONS:
                    out = wire.frame(
                        wire.OP_SUPPORTED,
                        wire.Writer().u16(0).build(),
                        stream,
                        wire.VERSION_RESPONSE,
                    )
                else:
                    out = wire.frame(
                        wire.OP_ERROR,
                        wire.error_body(0x000A, f"unsupported opcode {opcode}"),
                        stream,
                        wire.VERSION_RESPONSE,
                    )
                writer.write(out)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._writers.discard(writer)
            writer.close()

    # -- statement engine ----------------------------------------------------

    def _bind_types(
        self, query: str, keyspace: list[Optional[str]]
    ) -> list[Any]:
        """Server side of PREPARE: the declared CQL type of each ``?``
        marker, in order — what a real node derives from the schema. The
        point of the whole prepared path: clients must encode `int` columns
        as 4 bytes, `float` as 4, `list<double>` as doubles, which
        guess_type cannot know."""
        q = query.strip().rstrip(";")
        table: Optional[_Table] = None
        m = re.match(
            r"(?:INSERT\s+INTO|UPDATE|DELETE\s+FROM|SELECT\s+.*?\s+FROM)\s+([\w\".]+)",
            q, re.I | re.S,
        )
        if m:
            table = self.tables.get(self._resolve(m.group(1), keyspace))

        def col_type(name: str) -> Any:
            name = name.replace('"', "")
            if table is not None and name in table.columns:
                return table.columns[name]
            return wire.T_VARCHAR

        im = re.match(
            r"INSERT\s+INTO\s+[\w\".]+\s*\(([^)]*)\)\s*VALUES\s*\((.*)\)",
            q, re.I | re.S,
        )
        if im:
            cols = [c.strip() for c in im.group(1).split(",")]
            vals = self._split_args(im.group(2))
            return [
                col_type(c) for c, v in zip(cols, vals) if v.strip() == "?"
            ]
        types: list[Any] = []
        # blank quoted literals (length-preserving) so a '?' inside a string
        # is not counted as a bind marker
        scrubbed = re.sub(r"'[^']*'", lambda m: "'" + " " * (len(m.group()) - 2) + "'", q)
        for pos in (mm.start() for mm in re.finditer(r"\?", scrubbed)):
            before = q[:pos]
            cm = re.search(
                r"([\w\".]+)\s*(?:=|>=|<=|>|<|CONTAINS)\s*$", before, re.I
            )
            if cm:
                types.append(col_type(cm.group(1)))
                continue
            am = re.search(r"ORDER\s+BY\s+([\w\".]+)\s+ANN\s+OF\s*$", before, re.I)
            if am:
                types.append(col_type(am.group(1)))
                continue
            if re.search(r"LIMIT\s*$", before, re.I):
                types.append(wire.T_INT)
                continue
            types.append(wire.T_VARCHAR)
        return types

    def _resolve(self, name: str, keyspace: list[Optional[str]]) -> tuple[str, str]:
        name = name.replace('"', "")
        if "." in name:
            ks, _, table = name.partition(".")
            return ks, table
        return keyspace[0] or "default", name

    def _execute(
        self, query: str, raw_values: list[Optional[bytes]], keyspace: list[Optional[str]]
    ) -> bytes:
        q = query.strip().rstrip(";")
        upper = q.upper()

        if upper.startswith("USE "):
            ks = q[4:].strip().strip('"')
            keyspace[0] = ks
            self.keyspaces.add(ks)
            return wire.Writer().i32(wire.RESULT_SET_KEYSPACE).string(ks).build()

        if upper.startswith("CREATE KEYSPACE"):
            m = re.match(r"CREATE KEYSPACE (?:IF NOT EXISTS )?([\w\"]+)", q, re.I)
            ks = m.group(1).strip('"')
            self.keyspaces.add(ks)
            return wire.schema_change_body("CREATED", "KEYSPACE", ks)

        if upper.startswith("DROP KEYSPACE"):
            m = re.match(r"DROP KEYSPACE (?:IF EXISTS )?([\w\"]+)", q, re.I)
            ks = m.group(1).strip('"')
            self.keyspaces.discard(ks)
            for key in [k for k in self.tables if k[0] == ks]:
                del self.tables[key]
            return wire.schema_change_body("DROPPED", "KEYSPACE", ks)

        if upper.startswith("CREATE TABLE"):
            m = re.match(
                r"CREATE TABLE (?:IF NOT EXISTS )?([\w.\"]+)\s*\((.*)\)\s*(?:WITH .*)?$",
                q,
                re.I | re.S,
            )
            if not m:
                raise wire.CqlError(0x2000, f"cannot parse CREATE TABLE: {q[:80]}")
            ks, table = self._resolve(m.group(1), keyspace)
            body = m.group(2)
            columns: dict[str, Any] = {}
            pk: list[str] = []
            depth = 0
            parts, cur = [], ""
            for ch in body:
                if ch == "," and depth == 0:
                    parts.append(cur)
                    cur = ""
                    continue
                if ch in "(<":
                    depth += 1
                if ch in ")>":
                    depth -= 1
                cur += ch
            if cur.strip():
                parts.append(cur)
            for part in parts:
                part = part.strip()
                pk_match = re.match(r"PRIMARY KEY\s*\((.*)\)", part, re.I)
                if pk_match:
                    pk = [
                        c.strip().strip('"()')
                        for c in pk_match.group(1).replace("(", "").replace(")", "").split(",")
                    ]
                    continue
                m2 = re.match(r'"?(\w+)"?\s+(.+?)(\s+PRIMARY KEY)?$', part, re.I | re.S)
                if not m2:
                    continue
                col, spec, inline_pk = m2.group(1), m2.group(2), m2.group(3)
                columns[col] = parse_col_type(spec)
                if inline_pk:
                    pk.append(col)
            self.keyspaces.add(ks)
            if (ks, table) not in self.tables:
                self.tables[(ks, table)] = _Table(ks, table, columns, pk or list(columns)[:1])
            return wire.schema_change_body("CREATED", "TABLE", ks, table)

        if upper.startswith("DROP TABLE"):
            m = re.match(r"DROP TABLE (?:IF EXISTS )?([\w.\"]+)", q, re.I)
            ks, table = self._resolve(m.group(1), keyspace)
            self.tables.pop((ks, table), None)
            return wire.schema_change_body("DROPPED", "TABLE", ks, table)

        if upper.startswith("CREATE INDEX") or upper.startswith("CREATE CUSTOM INDEX"):
            return wire.void_body()

        if upper.startswith("INSERT INTO"):
            m = re.match(
                r"INSERT INTO\s+([\w.\"]+)\s*\(([^)]*)\)\s*VALUES\s*\((.*)\)", q, re.I | re.S
            )
            if not m:
                raise wire.CqlError(0x2000, f"cannot parse INSERT: {q[:80]}")
            ks, table_name = self._resolve(m.group(1), keyspace)
            table = self.tables.get((ks, table_name))
            if table is None:
                raise wire.CqlError(0x2200, f"unconfigured table {ks}.{table_name}")
            cols = [c.strip().strip('"') for c in m.group(2).split(",")]
            values: list[Any] = []
            value_it = iter(raw_values)
            for token in self._split_args(m.group(3)):
                token = token.strip()
                if token == "?":
                    col = cols[len(values)]
                    values.append(
                        _decode_bound(table.columns.get(col, wire.T_VARCHAR), next(value_it))
                    )
                else:
                    values.append(self._literal(token))
            row = dict(zip(cols, values))
            key = tuple(row.get(k) for k in table.primary_key)
            existing = table.rows.get(key, {})
            table.rows[key] = {**existing, **row}
            return wire.void_body()

        if upper.startswith("DELETE"):
            m = re.match(r"DELETE\s+FROM\s+([\w.\"]+)\s*(?:WHERE\s+(.*))?$", q, re.I | re.S)
            ks, table_name = self._resolve(m.group(1), keyspace)
            table = self.tables.get((ks, table_name))
            if table is None:
                return wire.void_body()
            conditions = self._conditions(m.group(2), table, raw_values)
            for key in [
                k for k, row in table.rows.items() if self._matches(row, conditions)
            ]:
                del table.rows[key]
            return wire.void_body()

        if upper.startswith("SELECT"):
            return self._select(q, raw_values, keyspace)

        if upper.startswith("TRUNCATE"):
            m = re.match(r"TRUNCATE\s+(?:TABLE\s+)?([\w.\"]+)", q, re.I)
            ks, table_name = self._resolve(m.group(1), keyspace)
            table = self.tables.get((ks, table_name))
            if table is not None:
                table.rows.clear()
            return wire.void_body()

        raise wire.CqlError(0x2000, f"unsupported statement: {q[:80]}")

    @staticmethod
    def _split_args(s: str) -> list[str]:
        parts, cur, depth, quoted = [], "", 0, False
        for ch in s:
            if ch == "'" and depth == 0:
                quoted = not quoted
            if ch == "," and depth == 0 and not quoted:
                parts.append(cur)
                cur = ""
                continue
            if ch in "([{<" and not quoted:
                depth += 1
            if ch in ")]}>" and not quoted:
                depth -= 1
            cur += ch
        if cur.strip():
            parts.append(cur)
        return parts

    @staticmethod
    def _literal(token: str) -> Any:
        token = token.strip()
        if token.startswith("'") and token.endswith("'"):
            return token[1:-1]
        if token.upper() in ("TRUE", "FALSE"):
            return token.upper() == "TRUE"
        if token.upper() == "NULL":
            return None
        if token.startswith("[") and token.endswith("]"):
            return [FakeCassandra._literal(t) for t in FakeCassandra._split_args(token[1:-1])]
        try:
            return int(token)
        except ValueError:
            try:
                return float(token)
            except ValueError:
                return token

    def _conditions(
        self, where: Optional[str], table: _Table, raw_values: list[Optional[bytes]]
    ) -> list[tuple[str, Any]]:
        if not where:
            return []
        where = re.sub(r"\s+ALLOW FILTERING\s*$", "", where.strip(), flags=re.I)
        conditions = []
        bound = [v for v in raw_values]
        # bound values are consumed left-to-right across the whole statement;
        # SELECT/DELETE use them only in WHERE and ANN OF (handled by caller
        # passing the remaining list)
        for clause in re.split(r"\s+AND\s+", where, flags=re.I):
            m = re.match(r'"?([\w]+)"?\s*=\s*(.+)', clause.strip())
            if not m:
                continue
            col, rhs = m.group(1), m.group(2).strip()
            if rhs == "?":
                value = _decode_bound(
                    table.columns.get(col, wire.T_VARCHAR), bound.pop(0)
                )
            else:
                value = self._literal(rhs)
            conditions.append((col, value))
        del raw_values[: len(raw_values) - len(bound)]
        return conditions

    @staticmethod
    def _matches(row: dict[str, Any], conditions: list[tuple[str, Any]]) -> bool:
        return all(row.get(col) == value for col, value in conditions)

    def _select(
        self, q: str, raw_values: list[Optional[bytes]], keyspace: list[Optional[str]]
    ) -> bytes:
        m = re.match(
            r"SELECT\s+(.*?)\s+FROM\s+([\w.\"]+)"
            r"(?:\s+WHERE\s+(.*?))?"
            r"(?:\s+ORDER\s+BY\s+\"?(\w+)\"?\s+ANN\s+OF\s+(\?))?"
            r"(?:\s+LIMIT\s+(\d+))?"
            r"(?:\s+ALLOW\s+FILTERING)?\s*$",
            q,
            re.I | re.S,
        )
        if not m:
            raise wire.CqlError(0x2000, f"cannot parse SELECT: {q[:120]}")
        cols_spec, table_ref, where, ann_col, _ann_q, limit = m.groups()
        ks, table_name = self._resolve(table_ref, keyspace)

        # system_schema introspection
        if ks == "system_schema":
            values = list(raw_values)
            if table_name == "keyspaces":
                target = _decode_bound(wire.T_VARCHAR, values[0]) if values else None
                rows = [[k] for k in sorted(self.keyspaces) if target in (None, k)]
                return wire.rows_body(
                    "system_schema", "keyspaces", [("keyspace_name", wire.T_VARCHAR)], rows
                )
            if table_name == "tables":
                ks_t = _decode_bound(wire.T_VARCHAR, values[0]) if values else None
                t_t = (
                    _decode_bound(wire.T_VARCHAR, values[1]) if len(values) > 1 else None
                )
                rows = [
                    [k[1]]
                    for k in sorted(self.tables)
                    if ks_t in (None, k[0]) and t_t in (None, k[1])
                ]
                return wire.rows_body(
                    "system_schema", "tables", [("table_name", wire.T_VARCHAR)], rows
                )
            raise wire.CqlError(0x2200, f"unknown system table {table_name}")

        table = self.tables.get((ks, table_name))
        if table is None:
            raise wire.CqlError(0x2200, f"unconfigured table {ks}.{table_name}")
        conditions = self._conditions(where, table, raw_values)
        rows = [row for row in table.rows.values() if self._matches(row, conditions)]

        if ann_col:
            query_vec = _decode_bound(("vector", 0), raw_values.pop(0))

            def cosine(row: dict[str, Any]) -> float:
                v = row.get(ann_col) or []
                dot = sum(a * b for a, b in zip(v, query_vec))
                na = math.sqrt(sum(a * a for a in v))
                nb = math.sqrt(sum(b * b for b in query_vec))
                return dot / (na * nb + 1e-12) if na else -1.0

            rows.sort(key=cosine, reverse=True)

        if limit:
            rows = rows[: int(limit)]

        cols_spec = cols_spec.strip()
        similarity_expr = re.search(
            r"similarity_cosine\(\"?(\w+)\"?,\s*\?\)", cols_spec, re.I
        )
        if cols_spec == "*":
            out_cols = [(c, t) for c, t in table.columns.items()]
        else:
            out_cols = []
            for c in self._split_args(cols_spec):
                c = c.strip().strip('"')
                if c in table.columns:
                    out_cols.append((c, table.columns[c]))
        out_rows = [[row.get(c) for c, _ in out_cols] for row in rows]
        if similarity_expr:
            # not commonly used by the agents; report 0.0 column
            out_cols.append(("similarity", wire.T_FLOAT))
            for r in out_rows:
                r.append(0.0)
        return wire.rows_body(ks, table_name, out_cols, out_rows)
