"""Milvus vector datasource + writer + collection asset over the REST v2 API.

Parity: reference `langstream-vector-agents/.../milvus/`
(`MilvusDataSource.java`, `MilvusWriter.java`, assets) — the Java side uses
the Milvus gRPC SDK; this rebuild targets Milvus's RESTful v2 surface
(`/v2/vectordb/entities/{search,insert,delete}`,
`/v2/vectordb/collections/...`), which Zilliz serverless and Milvus ≥2.3
ship by default — same SDK-free approach as the other HTTP datasources
(remote.py).

`query` strings follow the platform's vector-query convention (a JSON object
with `?` placeholders substituted from fields), e.g.:

    {"collection": "docs", "vector": "?", "topK": 5, "output-fields": ["text"]}
"""

from __future__ import annotations

import json
import logging
from typing import Any, Optional

from langstream_tpu.api.storage import AssetManager, DataSource, VectorDatabaseWriter

log = logging.getLogger(__name__)


def _substitute(obj: Any, params: list[Any]) -> Any:
    """Replace "?" placeholders depth-first from params (the remote.py
    convention shared by every JSON-query datasource)."""
    it = iter(params)

    def walk(o: Any) -> Any:
        if o == "?":
            return next(it)
        if isinstance(o, dict):
            return {k: walk(v) for k, v in o.items()}
        if isinstance(o, list):
            return [walk(x) for x in o]
        return o

    return walk(obj)


class MilvusDataSource(DataSource):
    """`service: milvus` — config: ``url`` (or host/port), ``token``
    (api key / user:pass), ``database``."""

    def __init__(self, config: dict[str, Any]) -> None:
        url = config.get("url")
        if not url:
            host = config.get("host", "localhost")
            port = int(config.get("port", 19530))
            url = f"http://{host}:{port}"
        self.url = str(url).rstrip("/")
        self.token = config.get("token") or config.get("api-key") or ""
        if not self.token and config.get("user"):
            self.token = f"{config['user']}:{config.get('password', '')}"
        self.database = config.get("database", "")
        self._session: Any = None

    async def _request(self, path: str, body: dict[str, Any]) -> dict[str, Any]:
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        if self.database:
            body = {"dbName": self.database, **body}
        async with self._session.post(
            f"{self.url}{path}", json=body, headers=headers
        ) as resp:
            payload = await resp.json(content_type=None)
            if resp.status != 200 or (payload or {}).get("code", 0) not in (0, 200):
                raise RuntimeError(
                    f"milvus {path} failed ({resp.status}): {str(payload)[:300]}"
                )
            return payload or {}

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def fetch_data(self, query: str, params: list[Any]) -> list[dict[str, Any]]:
        spec = _substitute(json.loads(query), list(params))
        collection = spec.get("collection") or spec.get("collection-name")
        vector = spec.get("vector")
        body: dict[str, Any] = {
            "collectionName": collection,
            "limit": int(spec.get("topK", spec.get("limit", 10))),
        }
        if spec.get("filter"):
            body["filter"] = spec["filter"]
        if spec.get("output-fields"):
            body["outputFields"] = spec["output-fields"]
        if vector is not None:
            body["data"] = [list(map(float, vector))]
            if spec.get("vector-field"):
                body["annsField"] = spec["vector-field"]
            payload = await self._request("/v2/vectordb/entities/search", body)
        else:
            payload = await self._request("/v2/vectordb/entities/query", body)
        return list(payload.get("data", []))

    async def execute_statement(self, query: str, params: list[Any]) -> dict[str, Any]:
        spec = _substitute(json.loads(query), list(params))
        action = spec.pop("action", "insert")
        collection = spec.get("collection") or spec.get("collection-name")
        if action == "insert":
            payload = await self._request(
                "/v2/vectordb/entities/insert",
                {"collectionName": collection, "data": spec.get("data", [])},
            )
        elif action == "delete":
            payload = await self._request(
                "/v2/vectordb/entities/delete",
                {"collectionName": collection, "filter": spec.get("filter", "")},
            )
        else:
            raise ValueError(f"unknown milvus action {action!r}")
        return {"result": payload.get("data", {})}

    # -- writer/asset helpers -----------------------------------------------

    async def insert_rows(self, collection: str, rows: list[dict[str, Any]]) -> None:
        await self._request(
            "/v2/vectordb/entities/insert",
            {"collectionName": collection, "data": rows},
        )

    async def has_collection(self, name: str) -> bool:
        payload = await self._request(
            "/v2/vectordb/collections/has", {"collectionName": name}
        )
        data = payload.get("data", {})
        return bool(data.get("has", data))

    async def create_collection(self, name: str, dimension: int) -> None:
        await self._request(
            "/v2/vectordb/collections/create",
            {"collectionName": name, "dimension": int(dimension)},
        )

    async def drop_collection(self, name: str) -> None:
        await self._request(
            "/v2/vectordb/collections/drop", {"collectionName": name}
        )


class MilvusWriter(VectorDatabaseWriter):
    """vector-db-sink writer: map fields → one row per record
    (reference MilvusWriter.java)."""

    def __init__(self, datasource: MilvusDataSource, config: dict[str, Any]) -> None:
        self.datasource = datasource
        self.collection = config.get("collection-name", config.get("table-name", "documents"))
        self.fields = list(config.get("fields", []))

    async def upsert(self, record: Any, context: dict[str, Any]) -> None:
        from langstream_tpu.agents.genai import el
        from langstream_tpu.agents.genai.mutable import MutableRecord

        ctx = MutableRecord.from_record(record)
        row = {
            f["name"]: el.evaluate(f.get("expression", "value"), ctx)
            for f in self.fields
        }
        await self.datasource.insert_rows(self.collection, [row])


class MilvusCollectionAssetManager(AssetManager):
    """`milvus-collection` asset (reference MilvusAssetsManagerProvider)."""

    def __init__(self) -> None:
        self._asset = None
        self._datasource: Optional[MilvusDataSource] = None

    async def initialize(self, asset) -> None:
        self._asset = asset
        ds_config = asset.config.get("datasource", {})
        if isinstance(ds_config, dict):
            ds_config = ds_config.get("configuration", ds_config)
        self._datasource = MilvusDataSource(dict(ds_config))

    async def close(self) -> None:
        if self._datasource is not None:
            await self._datasource.close()

    def _name(self) -> str:
        assert self._asset is not None
        return str(
            self._asset.config.get("collection-name")
            or self._asset.config.get("table-name", "")
        )

    async def asset_exists(self) -> bool:
        assert self._datasource
        return await self._datasource.has_collection(self._name())

    async def deploy_asset(self) -> None:
        assert self._asset and self._datasource
        await self._datasource.create_collection(
            self._name(), int(self._asset.config.get("dimension", 0) or 0)
        )

    async def delete_asset(self) -> None:
        assert self._datasource
        await self._datasource.drop_collection(self._name())
