"""Cassandra / Astra vector datasource, writer and asset managers over the
native CQL protocol.

Parity: reference `langstream-vector-agents/.../cassandra/`
(`CassandraDataSource.java`, `CassandraWriter.java`,
`CassandraAssetsManagerProvider.java`, plus the `astra` / `astra-vector-db`
variants) — rebuilt on the stdlib CQL v4 codec (``cql_protocol.py``) instead
of the DataStax driver, the same no-SDK approach as the Kafka/Pulsar data
planes. Astra is the same wire protocol with SASL-plain auth (user
``token``, password ``AstraCS:...``); its cloud secure-connect bundle is TLS
around the same port, configured via ``contact-points`` + ``port`` here.

Supported surface (what the query / query-vector-db / vector-db-sink agents
use): QUERY with positional binds (including ``vector<float, n>`` values for
ANN searches), Rows/Void/SchemaChange results, and DDL for the asset
managers.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import ssl as ssl_mod
from typing import Any, Optional

from langstream_tpu.agents.vector import cql_protocol as wire
from langstream_tpu.api.storage import AssetManager, DataSource, VectorDatabaseWriter

log = logging.getLogger(__name__)


class CqlConnection:
    """One server connection; stream-id multiplexed request/response."""

    def __init__(
        self,
        host: str,
        port: int = 9042,
        username: str = "",
        password: str = "",
        tls: bool = False,
    ) -> None:
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.tls = tls
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._dispatch_task: Optional[asyncio.Task] = None
        self._pending: dict[int, asyncio.Future] = {}
        self._streams = itertools.cycle(range(1, 32768))
        self._write_lock = asyncio.Lock()
        # statement → (prepared id, server-declared bind types)
        self._prepared: dict[str, tuple[bytes, list[Any]]] = {}
        self._prepare_unsupported = False

    async def connect(self) -> None:
        ssl_ctx = ssl_mod.create_default_context() if self.tls else None
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=ssl_ctx
        )
        # handshake is sequential (stream 0), then the dispatch loop starts
        opcode, body = await self._call_sequential(
            wire.OP_STARTUP, wire.startup_body()
        )
        if opcode == wire.OP_AUTHENTICATE:
            opcode, body = await self._call_sequential(
                wire.OP_AUTH_RESPONSE,
                wire.auth_response_body(self.username, self.password),
            )
            if opcode == wire.OP_ERROR:
                raise wire.parse_error_body(body)
            if opcode not in (wire.OP_AUTH_SUCCESS, wire.OP_READY):
                raise wire.CqlError(0, f"unexpected auth opcode 0x{opcode:02x}")
        elif opcode == wire.OP_ERROR:
            raise wire.parse_error_body(body)
        elif opcode != wire.OP_READY:
            raise wire.CqlError(0, f"unexpected startup opcode 0x{opcode:02x}")
        self._dispatch_task = asyncio.create_task(self._dispatch_loop())

    async def _call_sequential(self, opcode: int, body: bytes) -> tuple[int, bytes]:
        assert self._writer is not None and self._reader is not None
        self._writer.write(wire.frame(opcode, body, stream=0))
        await self._writer.drain()
        header = await self._reader.readexactly(wire.HEADER_SIZE)
        _, _, resp_opcode, length = wire.parse_header(header)
        resp_body = await self._reader.readexactly(length) if length else b""
        return resp_opcode, resp_body

    async def _dispatch_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                header = await self._reader.readexactly(wire.HEADER_SIZE)
                _, stream, opcode, length = wire.parse_header(header)
                body = await self._reader.readexactly(length) if length else b""
                fut = self._pending.pop(stream, None)
                if fut is not None and not fut.done():
                    fut.set_result((opcode, body))
        except (asyncio.CancelledError, asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            err = ConnectionError("CQL connection closed")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()

    async def close(self) -> None:
        if self._dispatch_task is not None:
            self._dispatch_task.cancel()
            try:
                await self._dispatch_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._dispatch_task = None
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None

    async def _call(self, opcode: int, payload: bytes) -> dict[str, Any]:
        assert self._writer is not None, "not connected"
        stream = next(self._streams)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[stream] = fut
        data = wire.frame(opcode, payload, stream)
        async with self._write_lock:
            self._writer.write(data)
            await self._writer.drain()
        resp_opcode, body = await asyncio.wait_for(fut, timeout=30)
        if resp_opcode == wire.OP_ERROR:
            raise wire.parse_error_body(body)
        if resp_opcode != wire.OP_RESULT:
            raise wire.CqlError(0, f"unexpected opcode 0x{resp_opcode:02x}")
        return wire.parse_result_body(body)

    async def query(
        self, statement: str, values: Optional[list[Any]] = None
    ) -> dict[str, Any]:
        """Run a statement. With bound values the path is PREPARE + EXECUTE
        so values are encoded with the SERVER-declared column types —
        guess_type's widths (python int → 8-byte bigint, numeric list →
        float32 vector) are rejected or mis-decoded by real Cassandra/Astra
        for int/smallint/float/list<double> columns. Plain QUERY with
        guessed types remains only as a fallback for servers without
        PREPARE (e.g. minimal test stubs)."""
        if not values:
            return await self._call(wire.OP_QUERY, wire.query_body(statement))
        if not self._prepare_unsupported:
            try:
                return await self._execute_prepared(statement, values)
            except wire.CqlError as e:
                if e.code != 0x000A:  # "unsupported opcode"
                    raise
                self._prepare_unsupported = True
        return await self._call(
            wire.OP_QUERY, wire.query_body(statement, values)
        )

    async def _execute_prepared(
        self, statement: str, values: list[Any], *, retried: bool = False
    ) -> dict[str, Any]:
        entry = self._prepared.get(statement)
        if entry is None:
            prepared = await self._call(
                wire.OP_PREPARE, wire.prepare_body(statement)
            )
            if prepared.get("kind") != "prepared":
                raise wire.CqlError(0, f"bad PREPARE result: {prepared}")
            entry = (prepared["id"], prepared["bind_types"])
            self._prepared[statement] = entry
        prepared_id, bind_types = entry
        try:
            return await self._call(
                wire.OP_EXECUTE, wire.execute_body(prepared_id, bind_types, values)
            )
        except wire.CqlError as e:
            # UNPREPARED (id evicted server-side): re-prepare ONCE — a
            # server that rejects even a fresh id must surface, not recurse
            if e.code != 0x2500 or retried:
                raise
            self._prepared.pop(statement, None)
            return await self._execute_prepared(statement, values, retried=True)


class CassandraDataSource(DataSource):
    """`service: cassandra` (and `astra` / `astra-vector-db`) datasource.

    config: ``contact-points`` (host or host:port), ``port``, ``username`` /
    ``password`` (Astra: ``token`` / ``AstraCS:...``; also accepts
    ``clientId`` / ``secret``), ``tls``, ``keyspace``."""

    def __init__(self, config: dict[str, Any]) -> None:
        contact = str(
            config.get("contact-points")
            or config.get("contactPoints")
            or "localhost"
        ).split(",")[0].strip()
        if ":" in contact:
            host, _, port_s = contact.rpartition(":")
            self.host, self.port = host, int(port_s)
        else:
            self.host = contact
            self.port = int(config.get("port", 9042))
        self.username = str(
            config.get("username") or config.get("clientId") or ""
        )
        self.password = str(
            config.get("password")
            or config.get("secret")
            or config.get("token")
            or ""
        )
        if config.get("token") and not config.get("username"):
            self.username = "token"  # Astra token auth convention
        self.tls = bool(config.get("tls", False))
        self.keyspace = config.get("keyspace")
        self._conn: Optional[CqlConnection] = None
        self._lock = asyncio.Lock()

    async def conn(self) -> CqlConnection:
        async with self._lock:
            if self._conn is None:
                conn = CqlConnection(
                    self.host, self.port, self.username, self.password, self.tls
                )
                await conn.connect()
                if self.keyspace:
                    await conn.query(f'USE "{self.keyspace}"')
                self._conn = conn
            return self._conn

    async def close(self) -> None:
        if self._conn is not None:
            await self._conn.close()
            self._conn = None

    async def fetch_data(self, query: str, params: list[Any]) -> list[dict[str, Any]]:
        result = await (await self.conn()).query(query, params)
        return result.get("rows", [])

    async def execute_statement(self, query: str, params: list[Any]) -> dict[str, Any]:
        result = await (await self.conn()).query(query, params)
        return {"kind": result.get("kind", "void")}


class CassandraWriter(VectorDatabaseWriter):
    """vector-db-sink writer: INSERT is Cassandra's native upsert
    (reference CassandraWriter.java field mapping)."""

    def __init__(self, datasource: CassandraDataSource, config: dict[str, Any]) -> None:
        self.datasource = datasource
        table = config.get("table-name", "documents")
        keyspace = config.get("keyspace") or datasource.keyspace
        if "." in table:  # "ks.table" wins over the datasource keyspace
            keyspace, _, table = table.partition(".")
        self.table = table
        self.keyspace = keyspace
        self.fields = list(config.get("fields", []))

    async def upsert(self, record: Any, context: dict[str, Any]) -> None:
        from langstream_tpu.agents.genai import el
        from langstream_tpu.agents.genai.mutable import MutableRecord

        ctx = MutableRecord.from_record(record)
        names: list[str] = []
        values: list[Any] = []
        for f in self.fields:
            names.append(f["name"])
            values.append(el.evaluate(f.get("expression", "value"), ctx))
        table = f'"{self.keyspace}"."{self.table}"' if self.keyspace else f'"{self.table}"'
        cols = ", ".join(f'"{n}"' for n in names)
        placeholders = ", ".join("?" for _ in names)
        await self.datasource.execute_statement(
            f"INSERT INTO {table} ({cols}) VALUES ({placeholders})", values
        )


class CassandraTableAssetManager(AssetManager):
    """`cassandra-table` asset: DDL create-statements / delete-statements
    (reference CassandraAssetsManagerProvider table manager)."""

    def __init__(self) -> None:
        self._asset = None
        self._datasource: Optional[CassandraDataSource] = None

    async def initialize(self, asset) -> None:
        self._asset = asset
        ds_config = asset.config.get("datasource", {})
        if isinstance(ds_config, dict):
            ds_config = ds_config.get("configuration", ds_config)
        self._datasource = CassandraDataSource(dict(ds_config))

    async def close(self) -> None:
        if self._datasource is not None:
            await self._datasource.close()

    def _table(self) -> str:
        assert self._asset is not None
        return str(self._asset.config.get("table-name", ""))

    async def asset_exists(self) -> bool:
        assert self._asset and self._datasource
        keyspace = self._asset.config.get("keyspace") or self._datasource.keyspace or ""
        rows = await self._datasource.fetch_data(
            "SELECT table_name FROM system_schema.tables "
            "WHERE keyspace_name = ? AND table_name = ?",
            [keyspace, self._table()],
        )
        return bool(rows)

    async def deploy_asset(self) -> None:
        assert self._asset and self._datasource
        for stmt in self._asset.config.get("create-statements", []):
            await self._datasource.execute_statement(stmt, [])

    async def delete_asset(self) -> None:
        assert self._asset and self._datasource
        stmts = self._asset.config.get("delete-statements") or [
            f"DROP TABLE IF EXISTS {self._table()}"
        ]
        for stmt in stmts:
            await self._datasource.execute_statement(stmt, [])


class CassandraKeyspaceAssetManager(AssetManager):
    """`cassandra-keyspace` / `astra-keyspace` asset (reference keyspace
    manager): create/drop a keyspace."""

    def __init__(self) -> None:
        self._asset = None
        self._datasource: Optional[CassandraDataSource] = None

    async def initialize(self, asset) -> None:
        self._asset = asset
        ds_config = asset.config.get("datasource", {})
        if isinstance(ds_config, dict):
            ds_config = ds_config.get("configuration", ds_config)
        ds_config = dict(ds_config)
        ds_config.pop("keyspace", None)  # must not USE a keyspace being created
        self._datasource = CassandraDataSource(ds_config)

    async def close(self) -> None:
        if self._datasource is not None:
            await self._datasource.close()

    def _keyspace(self) -> str:
        assert self._asset is not None
        return str(self._asset.config.get("keyspace", ""))

    async def asset_exists(self) -> bool:
        assert self._datasource
        rows = await self._datasource.fetch_data(
            "SELECT keyspace_name FROM system_schema.keyspaces WHERE keyspace_name = ?",
            [self._keyspace()],
        )
        return bool(rows)

    async def deploy_asset(self) -> None:
        assert self._asset and self._datasource
        stmts = self._asset.config.get("create-statements") or [
            f"CREATE KEYSPACE IF NOT EXISTS {self._keyspace()} WITH replication = "
            "{'class': 'SimpleStrategy', 'replication_factor': 1}"
        ]
        for stmt in stmts:
            await self._datasource.execute_statement(stmt, [])

    async def delete_asset(self) -> None:
        assert self._datasource
        await self._datasource.execute_statement(
            f"DROP KEYSPACE IF EXISTS {self._keyspace()}", []
        )
