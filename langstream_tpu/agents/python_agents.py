"""Out-of-process Python agent types.

Parity: reference ``PythonAgentsCodeProvider.java:25-39`` — agent types
``python-source`` / ``python-processor`` / ``python-sink`` / ``python-service``
(and the ``experimental-python-*`` aliases) backed by the gRPC subprocess
bridge. Configuration: ``className`` (module.Class of user code implementing
the SDK ABCs in langstream_tpu.api.agent) and optional ``pythonPath``.
"""

from __future__ import annotations

from langstream_tpu.api.agent import ComponentType
from langstream_tpu.api.doc import ConfigModel, ConfigProperty
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo
from langstream_tpu.grpc_runtime.bridge import (
    GrpcAgentProcessor,
    GrpcAgentService,
    GrpcAgentSink,
    GrpcAgentSource,
)


def _config_model(type_: str) -> ConfigModel:
    return ConfigModel(
        type=type_,
        allow_unknown=True,
        properties={
            "className": ConfigProperty(
                "className", "module.Class of the user agent", type="string", required=True
            ),
            "pythonPath": ConfigProperty(
                "pythonPath", "extra sys.path entries for the subprocess", type="string"
            ),
        },
    )


def _register() -> None:
    for type_, component, factory in (
        ("python-source", ComponentType.SOURCE, GrpcAgentSource),
        ("python-processor", ComponentType.PROCESSOR, GrpcAgentProcessor),
        ("python-sink", ComponentType.SINK, GrpcAgentSink),
        ("python-service", ComponentType.SERVICE, GrpcAgentService),
    ):
        REGISTRY.register_agent(
            AgentTypeInfo(
                type=type_,
                component_type=component,
                factory=factory,
                description=f"User Python agent in an isolated subprocess ({component.value}).",
                config_model=_config_model(type_),
                aliases=(f"experimental-{type_}",)
                + (("python-function",) if type_ == "python-processor" else ()),
            )
        )


_register()
