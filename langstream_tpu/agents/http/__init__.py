"""HTTP client agents.

Parity: reference `langstream-agent-http-request` (SURVEY §2.5):
`http-request` (HttpRequestAgent.java — per-record templated HTTP calls)
and `langserve-invoke` (LangServeClient.java — LangServe /invoke and
/stream endpoints, incl. SSE streaming to an intermediate topic, matching
the completions chunk-streaming contract).
"""

from __future__ import annotations

import json
from typing import Any, Optional
from urllib.parse import urlencode

import aiohttp

from langstream_tpu.agents.genai import el
from langstream_tpu.agents.genai.mutable import MutableRecord
from langstream_tpu.api.agent import ComponentType, SingleRecordProcessor
from langstream_tpu.api.doc import ConfigModel, ConfigProperty, props
from langstream_tpu.api.record import Record, SimpleRecord
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo


class HttpRequestAgent(SingleRecordProcessor):
    """`http-request`: per-record HTTP call; url/headers/query/body values are
    EL-templated against the record; the response lands in `output-field`
    (JSON-decoded when the content type says so)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.url = configuration.get("url", "")
        self.method = configuration.get("method", "GET").upper()
        self.output_field = configuration.get("output-field", "value")
        self.headers = dict(configuration.get("headers", {}))
        self.query_string = dict(configuration.get("query-string", {}))
        self.body = configuration.get("body")
        self.allow_redirects = bool(configuration.get("allow-redirects", True))
        self.handle_cookies = bool(configuration.get("handle-cookies", True))
        self._session: Optional[aiohttp.ClientSession] = None

    async def start(self) -> None:
        jar = None if self.handle_cookies else aiohttp.DummyCookieJar()
        self._session = aiohttp.ClientSession(cookie_jar=jar)

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()

    def _render(self, template: str, ctx: MutableRecord) -> str:
        return el.render_template(template, ctx)

    async def process_record(self, record: Record) -> list[Record]:
        assert self._session is not None, "agent not started"
        ctx = MutableRecord.from_record(record)
        url = self._render(self.url, ctx)
        if self.query_string:
            qs = urlencode({k: self._render(str(v), ctx) for k, v in self.query_string.items()})
            url = f"{url}{'&' if '?' in url else '?'}{qs}"
        headers = {k: self._render(str(v), ctx) for k, v in self.headers.items()}
        body = self._render(self.body, ctx) if isinstance(self.body, str) else self.body
        async with self._session.request(
            self.method,
            url,
            headers=headers,
            data=body,
            allow_redirects=self.allow_redirects,
        ) as resp:
            resp.raise_for_status()
            if "json" in resp.content_type:
                payload: Any = await resp.json()
            else:
                payload = await resp.text()
        ctx.set_field(self.output_field, payload)
        self.processed(1)
        return [ctx.to_record()]


class LangServeInvokeAgent(SingleRecordProcessor):
    """`langserve-invoke`: call a LangServe runnable. `/invoke` returns the
    final output; `/stream` consumes server-sent events and forwards each
    content delta to `stream-to-topic` before emitting the final record —
    the same chunk contract as ai-chat-completions."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.url = configuration.get("url", "")
        self.output_field = configuration.get("output-field", "value.answer")
        self.content_field = configuration.get("content-field", "content")
        self.fields = list(configuration.get("fields", []))
        self.stream_to_topic = configuration.get("stream-to-topic", "")
        self.min_chunks_per_message = int(configuration.get("min-chunks-per-message", 10))
        self.debug = bool(configuration.get("debug", False))
        self._session: Optional[aiohttp.ClientSession] = None

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()

    def _input(self, ctx: MutableRecord) -> dict[str, Any]:
        return {
            f.get("name", f"field{i}"): el.evaluate(f.get("expression", "value"), ctx)
            for i, f in enumerate(self.fields)
        }

    @staticmethod
    def _content_of(payload: Any, content_field: str) -> str:
        if isinstance(payload, dict):
            if content_field in payload:
                return str(payload[content_field])
            output = payload.get("output")
            if isinstance(output, dict) and content_field in output:
                return str(output[content_field])
            if isinstance(output, str):
                return output
            return json.dumps(payload)
        return str(payload)

    async def process_record(self, record: Record) -> list[Record]:
        assert self._session is not None, "agent not started"
        ctx = MutableRecord.from_record(record)
        body = {"input": self._input(ctx)}
        streaming = bool(self.stream_to_topic) and self.url.rstrip("/").endswith("/stream")
        if streaming:
            answer = await self._stream(body, record)
        else:
            async with self._session.post(self.url, json=body) as resp:
                resp.raise_for_status()
                payload = await resp.json()
            answer = self._content_of(payload.get("output", payload), self.content_field)
        ctx.set_field(self.output_field, answer)
        self.processed(1)
        return [ctx.to_record()]

    async def _stream(self, body: dict[str, Any], record: Record) -> str:
        """SSE consumption with min-chunks growth batching (reference
        LangServeClient + StreamingChunksConsumer semantics)."""
        assert self.context is not None and self._session is not None
        producer = self.context.get_topic_producer(self.stream_to_topic)
        parts: list[str] = []
        batch: list[str] = []
        batch_target = 1
        index = 0
        async with self._session.post(self.url, json=body) as resp:
            resp.raise_for_status()
            event = ""
            async for raw in resp.content:
                line = raw.decode("utf-8").rstrip("\n")
                if line.startswith("event:"):
                    event = line[len("event:") :].strip()
                elif line.startswith("data:"):
                    data = line[len("data:") :].strip()
                    if event in ("", "data"):
                        try:
                            payload = json.loads(data)
                        except json.JSONDecodeError:
                            payload = data
                        delta = self._content_of(payload, self.content_field)
                        parts.append(delta)
                        batch.append(delta)
                        if len(batch) >= batch_target:
                            await self._emit_chunk(producer, record, "".join(batch), index, False)
                            index += 1
                            batch = []
                            # growth batching: later chunks batch more
                            batch_target = min(batch_target * 2, self.min_chunks_per_message)
                elif line == "" and event == "end":
                    break
        await self._emit_chunk(producer, record, "".join(batch), index, True)
        return "".join(parts)

    async def _emit_chunk(
        self, producer: Any, record: Record, content: str, index: int, last: bool
    ) -> None:
        chunk = SimpleRecord.of(
            content,
            key=record.key,
            headers=[
                ("stream-index", str(index)),
                ("stream-last-message", str(last).lower()),
            ],
            origin=record.origin,
        )
        await producer.write(chunk)


def _register() -> None:
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="http-request",
            component_type=ComponentType.PROCESSOR,
            factory=HttpRequestAgent,
            composable=True,
            description="Per-record templated HTTP request.",
            config_model=ConfigModel(
                type="http-request",
                properties=props(
                    ConfigProperty("url", "target url (EL-templated)", required=True),
                    ConfigProperty("method", "HTTP method", default="GET"),
                    ConfigProperty("output-field", "where the response lands", default="value"),
                    ConfigProperty("headers", "request headers (EL-templated values)", type="object"),
                    ConfigProperty("query-string", "query params (EL-templated values)", type="object"),
                    ConfigProperty("body", "request body (EL-templated string)"),
                    ConfigProperty("allow-redirects", "follow redirects", type="boolean", default=True),
                    ConfigProperty("handle-cookies", "keep a cookie jar", type="boolean", default=True),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="langserve-invoke",
            component_type=ComponentType.PROCESSOR,
            factory=LangServeInvokeAgent,
            composable=False,  # may stream to a side topic
            description="Invoke a LangServe runnable (/invoke or /stream + SSE).",
            config_model=ConfigModel(
                type="langserve-invoke",
                properties=props(
                    ConfigProperty("url", "runnable endpoint", required=True),
                    ConfigProperty("output-field", "where the answer lands", default="value.answer"),
                    ConfigProperty("content-field", "delta content field", default="content"),
                    ConfigProperty("fields", "list of {name, expression} inputs", type="array"),
                    ConfigProperty("stream-to-topic", "topic for streamed chunks"),
                    ConfigProperty("min-chunks-per-message", "growth batching cap", type="integer", default=10),
                    ConfigProperty("debug", "log requests", type="boolean", default=False),
                ),
            ),
        )
    )


_register()
