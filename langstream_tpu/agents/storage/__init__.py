"""Object-storage sources.

Parity: reference `langstream-agent-s3` (`s3-source`: poll bucket, emit one
record per object, delete-on-commit) and
`langstream-agent-azure-blob-storage-source` (SURVEY §2.5). The reference
uses the minio/azure SDKs; neither is bundled here, so:

- `s3-source` speaks the S3 REST API directly (SigV4 signing via stdlib
  hmac/hashlib; ListObjectsV2/GetObject/DeleteObject) — works against
  minio/S3-compatible endpoints,
- `azure-blob-storage-source` uses SAS-token auth over the Blob REST API,
- `local-directory-source` is the filesystem analogue used for local mode
  and tests (same emit/delete-on-commit contract).
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
from pathlib import Path
from typing import Any, Optional
from urllib.parse import quote, urlparse
from xml.etree import ElementTree

import aiohttp

from langstream_tpu.api.agent import AgentSource, ComponentType
from langstream_tpu.api.doc import ConfigModel, ConfigProperty, props
from langstream_tpu.api.record import Record, SimpleRecord
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo

DEFAULT_EXTENSIONS = "pdf,docx,html,htm,md,txt"


class _ObjectStorageSource(AgentSource):
    """Shared poll→emit→delete-on-commit loop (reference S3Source.java)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.idle_time = float(configuration.get("idle-time", 5))
        extensions = configuration.get("file-extensions", DEFAULT_EXTENSIONS)
        self.extensions = [e.strip().lower() for e in str(extensions).split(",") if e.strip()]
        self.delete_objects = bool(configuration.get("delete-objects", True))
        self._in_flight: set[str] = set()
        # committed-but-kept objects (delete-objects=false) must not re-emit;
        # in-memory like the reference → restart re-emits (at-least-once)
        self._done: set[str] = set()

    def _extension_ok(self, name: str) -> bool:
        if not self.extensions:
            return True
        return name.rsplit(".", 1)[-1].lower() in self.extensions

    async def list_objects(self) -> list[str]:
        raise NotImplementedError

    async def get_object(self, name: str) -> bytes:
        raise NotImplementedError

    async def delete_object(self, name: str) -> None:
        raise NotImplementedError

    async def read(self) -> list[Record]:
        for name in await self.list_objects():
            if name in self._in_flight or name in self._done:
                continue
            if not self._extension_ok(name):
                continue
            body = await self.get_object(name)
            self._in_flight.add(name)
            self.processed(1)
            return [
                SimpleRecord.of(
                    body,
                    key=name,
                    headers=[("name", name), ("bucket", getattr(self, "bucket", ""))],
                    origin=self.agent_type,
                )
            ]
        await asyncio.sleep(self.idle_time)
        return []

    async def commit(self, records: list[Record]) -> None:
        for r in records:
            name = str(r.key)
            self._in_flight.discard(name)
            if self.delete_objects:
                await self.delete_object(name)
            else:
                self._done.add(name)


# ---------------------------------------------------------------------------
# local directory
# ---------------------------------------------------------------------------


class LocalDirectorySource(_ObjectStorageSource):
    """`local-directory-source`: same contract against a filesystem dir."""

    async def init(self, configuration: dict[str, Any]) -> None:
        await super().init(configuration)
        self.directory = Path(configuration.get("directory", "."))
        self.bucket = str(self.directory)

    async def list_objects(self) -> list[str]:
        if not self.directory.exists():
            return []
        return sorted(
            str(p.relative_to(self.directory))
            for p in self.directory.rglob("*")
            if p.is_file()
        )

    async def get_object(self, name: str) -> bytes:
        return (self.directory / name).read_bytes()

    async def delete_object(self, name: str) -> None:
        try:
            (self.directory / name).unlink()
        except FileNotFoundError:
            pass


# ---------------------------------------------------------------------------
# S3 (SigV4 REST)
# ---------------------------------------------------------------------------


def _sigv4_headers(
    method: str,
    url: str,
    region: str,
    access_key: str,
    secret_key: str,
    payload: bytes = b"",
    service: str = "s3",
) -> dict[str, str]:
    """Minimal AWS Signature V4 signing (S3 by default; any AWS service —
    the bedrock provider signs with service="bedrock")."""
    parsed = urlparse(url)
    host = parsed.netloc
    # callers build URLs with already-percent-encoded paths (quote(name)),
    # so the path is the canonical URI as-is; re-quoting would double-encode
    canonical_uri = parsed.path or "/"
    # canonical query: sorted key=value with URI-encoded parts
    query_pairs = []
    if parsed.query:
        for pair in parsed.query.split("&"):
            k, _, v = pair.partition("=")
            query_pairs.append((quote(k, safe="-_.~"), quote(v, safe="-_.~")))
    canonical_query = "&".join(f"{k}={v}" for k, v in sorted(query_pairs))

    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(payload).hexdigest()

    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed_headers = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join(
        [method, canonical_uri, canonical_query, canonical_headers, signed_headers, payload_hash]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )

    def sign(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k_date = sign(f"AWS4{secret_key}".encode(), datestamp)
    k_region = sign(k_date, region)
    k_service = sign(k_region, service)
    k_signing = sign(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(), hashlib.sha256).hexdigest()

    headers["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    return headers


class S3Source(_ObjectStorageSource):
    """`s3-source` against any S3-compatible endpoint (minio in the reference
    test/deploy stack). Path-style addressing: {endpoint}/{bucket}/{key}."""

    async def init(self, configuration: dict[str, Any]) -> None:
        await super().init(configuration)
        self.bucket = configuration.get("bucketName", "langstream-source")
        self.endpoint = configuration.get("endpoint", "http://minio-endpoint.-not-set:9090").rstrip("/")
        self.access_key = configuration.get("access-key", "minioadmin")
        self.secret_key = configuration.get("secret-key", "minioadmin")
        self.region = configuration.get("region", "us-east-1")
        self._session: Optional[aiohttp.ClientSession] = None

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()

    async def _request(self, method: str, url: str) -> tuple[int, bytes]:
        assert self._session is not None, "agent not started"
        headers = _sigv4_headers(method, url, self.region, self.access_key, self.secret_key)
        async with self._session.request(method, url, headers=headers) as resp:
            return resp.status, await resp.read()

    async def list_objects(self) -> list[str]:
        url = f"{self.endpoint}/{self.bucket}?list-type=2"
        status, body = await self._request("GET", url)
        if status != 200:
            raise RuntimeError(f"S3 list failed ({status}): {body[:200]!r}")
        root = ElementTree.fromstring(body)
        ns = ""
        if root.tag.startswith("{"):
            ns = root.tag[: root.tag.index("}") + 1]
        return [
            c.findtext(f"{ns}Key", "")
            for c in root.iter(f"{ns}Contents")
            if c.findtext(f"{ns}Key")
        ]

    async def get_object(self, name: str) -> bytes:
        url = f"{self.endpoint}/{self.bucket}/{quote(name)}"
        status, body = await self._request("GET", url)
        if status != 200:
            raise RuntimeError(f"S3 get {name} failed ({status})")
        return body

    async def delete_object(self, name: str) -> None:
        url = f"{self.endpoint}/{self.bucket}/{quote(name)}"
        status, _ = await self._request("DELETE", url)
        if status not in (200, 204, 404):
            raise RuntimeError(f"S3 delete {name} failed ({status})")


# ---------------------------------------------------------------------------
# Azure Blob (SAS token)
# ---------------------------------------------------------------------------


class AzureBlobStorageSource(_ObjectStorageSource):
    """`azure-blob-storage-source` via SAS-token auth (the SDK-free path;
    the reference supports sas-token alongside account keys)."""

    async def init(self, configuration: dict[str, Any]) -> None:
        await super().init(configuration)
        self.container = configuration.get("container", "langstream-source")
        endpoint = configuration.get("endpoint", "")
        if not endpoint:
            account = configuration.get("storage-account-name", "")
            endpoint = f"https://{account}.blob.core.windows.net"
        self.endpoint = endpoint.rstrip("/")
        self.sas_token = configuration.get("sas-token", "").lstrip("?")
        self.bucket = self.container
        self._session: Optional[aiohttp.ClientSession] = None

    async def start(self) -> None:
        self._session = aiohttp.ClientSession()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()

    def _url(self, path: str, query: str = "") -> str:
        parts = [q for q in (query, self.sas_token) if q]
        suffix = ("?" + "&".join(parts)) if parts else ""
        return f"{self.endpoint}/{path}{suffix}"

    async def list_objects(self) -> list[str]:
        assert self._session is not None, "agent not started"
        url = self._url(self.container, "restype=container&comp=list")
        async with self._session.get(url) as resp:
            body = await resp.read()
            if resp.status != 200:
                raise RuntimeError(f"Azure list failed ({resp.status}): {body[:200]!r}")
        root = ElementTree.fromstring(body)
        return [b.findtext("Name", "") for b in root.iter("Blob") if b.findtext("Name")]

    async def get_object(self, name: str) -> bytes:
        assert self._session is not None, "agent not started"
        async with self._session.get(self._url(f"{self.container}/{quote(name)}")) as resp:
            if resp.status != 200:
                raise RuntimeError(f"Azure get {name} failed ({resp.status})")
            return await resp.read()

    async def delete_object(self, name: str) -> None:
        assert self._session is not None, "agent not started"
        async with self._session.delete(self._url(f"{self.container}/{quote(name)}")) as resp:
            if resp.status not in (200, 202, 404):
                raise RuntimeError(f"Azure delete {name} failed ({resp.status})")


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

_COMMON = (
    ConfigProperty("idle-time", "poll sleep when empty (s)", type="number", default=5),
    ConfigProperty("file-extensions", "comma list filter", default=DEFAULT_EXTENSIONS),
    ConfigProperty("delete-objects", "delete after commit", type="boolean", default=True),
)


def _register() -> None:
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="s3-source",
            component_type=ComponentType.SOURCE,
            factory=S3Source,
            description="Poll an S3 bucket; emit objects; delete on commit.",
            config_model=ConfigModel(
                type="s3-source",
                properties=props(
                    ConfigProperty("bucketName", "bucket", default="langstream-source"),
                    ConfigProperty("endpoint", "S3 endpoint url", required=True),
                    ConfigProperty("access-key", "access key"),
                    ConfigProperty("secret-key", "secret key"),
                    ConfigProperty("region", "region", default="us-east-1"),
                    *_COMMON,
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="azure-blob-storage-source",
            component_type=ComponentType.SOURCE,
            factory=AzureBlobStorageSource,
            description="Poll an Azure Blob container; emit blobs; delete on commit.",
            config_model=ConfigModel(
                type="azure-blob-storage-source",
                properties=props(
                    ConfigProperty("container", "container", default="langstream-source"),
                    ConfigProperty("endpoint", "blob endpoint url"),
                    ConfigProperty("storage-account-name", "account (builds endpoint)"),
                    ConfigProperty("sas-token", "SAS token"),
                    *_COMMON,
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="local-directory-source",
            component_type=ComponentType.SOURCE,
            factory=LocalDirectorySource,
            description="Poll a directory; emit files; delete on commit.",
            config_model=ConfigModel(
                type="local-directory-source",
                properties=props(
                    ConfigProperty("directory", "dir to poll", required=True),
                    *_COMMON,
                ),
            ),
        )
    )


_register()
