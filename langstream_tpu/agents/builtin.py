"""Core structural agents: identity, topic passthrough, mock/test agents.

Parity: the reference's implicit identity processor (AgentRunner.java:319-358
wraps a bare source/sink with an identity processor) and the `mockagents`
test providers (SURVEY §4 tier-1).
"""

from __future__ import annotations

from typing import Any

from langstream_tpu.api.agent import (
    AgentProcessor,
    AgentSink,
    AgentSource,
    ComponentType,
    ProcessorResult,
    SingleRecordProcessor,
)
from langstream_tpu.api.doc import ConfigModel, ConfigProperty, props
from langstream_tpu.api.record import Record, SimpleRecord
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo


class IdentityAgent(AgentProcessor):
    """Pass-through processor."""

    async def process(self, records: list[Record]) -> list[ProcessorResult]:
        self.processed(len(records))
        return [ProcessorResult.ok(r, [r]) for r in records]


class ListSource(AgentSource):
    """Emits a configured list of values once — test/demo source."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self._items = list(configuration.get("items", []))
        self._emitted = False
        self.committed: list[Record] = []

    async def read(self) -> list[Record]:
        if self._emitted:
            import asyncio

            await asyncio.sleep(0.01)
            return []
        self._emitted = True
        self.processed(len(self._items))
        return [SimpleRecord.of(v, origin="list-source") for v in self._items]

    async def commit(self, records: list[Record]) -> None:
        self.committed.extend(records)


class CollectSink(AgentSink):
    """Collects records in memory — test/demo sink."""

    async def init(self, configuration: dict[str, Any]) -> None:
        self.collected: list[Record] = []

    async def write(self, record: Record) -> None:
        self.collected.append(record)
        self.processed(1)


class NoopProcessor(SingleRecordProcessor):
    async def process_record(self, record: Record) -> list[Record]:
        return [record]


def _register() -> None:
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="identity",
            component_type=ComponentType.PROCESSOR,
            factory=IdentityAgent,
            composable=True,
            description="Pass records through unchanged.",
            config_model=ConfigModel(type="identity", allow_unknown=True),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="list-source",
            component_type=ComponentType.SOURCE,
            factory=ListSource,
            description="Emit a fixed list of values (testing).",
            config_model=ConfigModel(
                type="list-source",
                properties=props(
                    ConfigProperty("items", "values to emit", type="array"),
                ),
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="collect-sink",
            component_type=ComponentType.SINK,
            factory=CollectSink,
            description="Collect records in memory (testing).",
            config_model=ConfigModel(type="collect-sink", allow_unknown=True),
        )
    )


_register()
