"""Kafka Connect adapter agents + camel-source (gated).

Parity: reference ``kafkaconnect/KafkaConnectSinkAgent.java:1`` /
``KafkaConnectSourceAgent.java:1`` (types ``sink`` / ``source`` — run stock
Kafka Connect connectors as agents) and ``CamelSource.java:1``
(``camel-source``).

The reference EMBEDS the connector jar in its JVM runtime (instantiates the
SinkTask/SourceTask classes in-process). This image has no JVM, so that
path cannot exist; instead these agents drive an EXTERNAL Kafka Connect
cluster through its documented REST interface (the same API `curl` and the
Confluent tooling use), restoring the capability class natively:

- ``sink``: the agent creates/updates the connector
  (``PUT /connectors/{name}/config``) pointing it at a BRIDGE topic, then
  bridges every pipeline record into that topic. When the app runs on the
  kafka streaming cluster the external Connect workers consume the bridge
  topic directly — the standard Connect data path, zero copies beyond the
  broker. The agent watches ``GET /connectors/{name}/status`` and restarts
  FAILED tasks (``POST .../restart``).
- ``source``: the connector's config is pointed at the bridge topic
  (``topic``/``kafka.topic``) and the agent consumes it, emitting records
  into the pipeline with at-least-once commit semantics.

``camel-source`` interprets the COMMON Camel endpoint URI schemes
natively (timer:, file:, http(s): — CamelSourceAgent); the long tail of
JVM-only components gates with an explicit message.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any, Optional

from langstream_tpu.api.agent import AgentSink, AgentSource, ComponentType
from langstream_tpu.api.doc import ConfigModel, ConfigProperty
from langstream_tpu.api.record import Record
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo

log = logging.getLogger(__name__)

_CAMEL_GATE = (
    "camel-source embeds JVM Camel components this image does not ship; "
    "use the http/webcrawler/storage sources, or a Kafka Connect source "
    "via an external Connect cluster (type: source)"
)


_CRON_MONTHS = {"JAN": 1, "FEB": 2, "MAR": 3, "APR": 4, "MAY": 5, "JUN": 6,
                "JUL": 7, "AUG": 8, "SEP": 9, "OCT": 10, "NOV": 11, "DEC": 12}
# Quartz numbering: 1 = Sunday (0 tolerated as Sunday too)
_CRON_DAYS = {"SUN": 1, "MON": 2, "TUE": 3, "WED": 4, "THU": 5, "FRI": 6, "SAT": 7}


def _cron_parse_field(
    spec: str, lo: int, hi: int, names: dict[str, int], classic_dow: bool = False
):
    """One Quartz field → set of matching ints, or None for */?.
    ``classic_dow``: numeric tokens use crontab numbering (0-7, 0 and
    7 = Sunday) and are translated to Quartz (1 = Sunday)."""
    spec = spec.strip().upper()
    if spec in ("*", "?"):
        return None

    def conv(token: str) -> int:
        if token in names:
            return names[token]
        v = int(token)
        if names is _CRON_DAYS:
            if classic_dow:
                return (v % 7) + 1  # crontab 0/7=SUN,1=MON → quartz 1=SUN
            return lo if v == 0 else v  # quartz tolerates 0 as Sunday
        return v

    out: set[int] = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
        if part in ("*", "?", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = conv(a), conv(b)
        else:
            start = conv(part)
            end = hi if step > 1 else start
        if not (lo <= start <= hi and lo <= end <= hi):
            raise ValueError(f"cron field {spec!r} out of range [{lo},{hi}]")
        if start <= end:
            out.update(range(start, end + 1, step))
        else:
            # wrap-around range (FRI-SUN, 22-2): high side then low side
            span = list(range(start, hi + 1)) + list(range(lo, end + 1))
            out.update(span[::step])
    return out


def _cron_parse(expr: str) -> list:
    """Quartz cron: ``sec min hour dom month dow [year]`` (camel-cron's
    ``schedule=`` syntax, ``+`` already decoded to spaces). A classic
    5-field crontab is accepted by prepending second 0 — its numeric
    day-of-week keeps crontab numbering (0/7 = Sunday); a trailing year
    field is ignored."""
    fields = expr.split()
    classic = len(fields) == 5
    if classic:
        fields = ["0", *fields]
    if len(fields) == 7:
        fields = fields[:6]
    if len(fields) != 6:
        raise ValueError(f"cron schedule {expr!r}: expected 5-7 fields")
    sec, minute, hour, dom, month, dow = fields
    return [
        _cron_parse_field(sec, 0, 59, {}),
        _cron_parse_field(minute, 0, 59, {}),
        _cron_parse_field(hour, 0, 23, {}),
        _cron_parse_field(dom, 1, 31, {}),
        _cron_parse_field(month, 1, 12, _CRON_MONTHS),
        _cron_parse_field(dow, 1, 7, _CRON_DAYS, classic_dow=classic),
    ]


def _cron_due(fields: list, tm: time.struct_time) -> bool:
    quartz_dow = ((tm.tm_wday + 1) % 7) + 1  # tm: 0=Mon → quartz: 1=Sun
    values = (tm.tm_sec, tm.tm_min, tm.tm_hour, tm.tm_mday, tm.tm_mon, quartz_dow)
    return all(f is None or v in f for f, v in zip(fields, values))


def _parse_feed_entries(body: str) -> list[dict]:
    """RSS 2.0 ``channel/item`` or Atom ``entry`` elements → normalized
    dicts (id/title/link/published/summary). The id (guid / atom:id /
    link / title, first present) is the camel-rss dedupe key."""
    import xml.etree.ElementTree as ET

    try:
        root = ET.fromstring(body)
    except ET.ParseError as e:
        log.warning("camel feed parse failed: %s", e)
        return []

    def text(el, *tags) -> str:
        for tag in tags:
            child = el.find(tag)
            if child is not None and (child.text or "").strip():
                return child.text.strip()
        return ""

    out: list[dict] = []
    # RSS 2.0 (no namespace)
    for item in root.iter("item"):
        entry = {
            "title": text(item, "title"),
            "link": text(item, "link"),
            "published": text(item, "pubDate"),
            "summary": text(item, "description"),
        }
        entry["id"] = text(item, "guid") or entry["link"] or entry["title"]
        out.append(entry)
    # Atom
    ns = "{http://www.w3.org/2005/Atom}"
    for item in root.iter(f"{ns}entry"):
        link_el = item.find(f"{ns}link")
        entry = {
            "title": text(item, f"{ns}title"),
            "link": link_el.get("href", "") if link_el is not None else "",
            "published": text(item, f"{ns}published", f"{ns}updated"),
            "summary": text(item, f"{ns}summary", f"{ns}content"),
        }
        entry["id"] = text(item, f"{ns}id") or entry["link"] or entry["title"]
        out.append(entry)
    return [e for e in out if e["id"]]


class ConnectRestError(RuntimeError):
    pass


class ConnectRestClient:
    """Minimal client for the Kafka Connect REST interface."""

    def __init__(self, rest_url: str) -> None:
        self.rest_url = rest_url.rstrip("/")
        self._http = None

    async def _session(self):
        import aiohttp

        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession()
        return self._http

    async def close(self) -> None:
        if self._http is not None and not self._http.closed:
            await self._http.close()
        self._http = None

    async def request(
        self, method: str, path: str, body: Optional[Any] = None
    ) -> tuple[int, Any]:
        session = await self._session()
        async with session.request(
            method, f"{self.rest_url}{path}", json=body
        ) as resp:
            try:
                doc = await resp.json(content_type=None)
            except Exception:  # noqa: BLE001 — empty body (e.g. 204)
                doc = None
            return resp.status, doc

    async def info(self) -> dict:
        status, doc = await self.request("GET", "/")
        if status != 200:
            raise ConnectRestError(f"connect cluster unreachable: HTTP {status}")
        return doc or {}

    async def put_config(self, name: str, config: dict) -> dict:
        status, doc = await self.request(
            "PUT", f"/connectors/{name}/config", config
        )
        if status not in (200, 201):
            raise ConnectRestError(
                f"connector {name} config rejected: HTTP {status} {doc}"
            )
        return doc or {}

    async def status(self, name: str) -> dict:
        status, doc = await self.request("GET", f"/connectors/{name}/status")
        if status == 404:
            return {}
        return doc or {}

    async def restart(self, name: str, task: Optional[int] = None) -> None:
        path = f"/connectors/{name}/restart"
        if task is not None:
            path = f"/connectors/{name}/tasks/{task}/restart"
        await self.request("POST", path)

    async def delete(self, name: str) -> None:
        await self.request("DELETE", f"/connectors/{name}")


class _ConnectAgentBase:
    """Shared lifecycle: config parsing, connector upsert, health watch."""

    def _parse(self, configuration: dict[str, Any]) -> None:
        connect = configuration.get("connect", {}) or {}
        self.rest = ConnectRestClient(
            connect.get("rest-url", "http://localhost:8083")
        )
        self.connector_name = connect.get("name") or f"ls-{self.agent_id or 'connector'}"
        self.delete_on_close = bool(connect.get("delete-on-close", False))
        self.status_interval = float(connect.get("status-interval", 10.0))
        # everything else (connector.class, transforms, …) IS the connector
        # config — the reference passes the agent configuration through the
        # same way (KafkaConnectSinkAgent.java adapter config pass-through)
        self.connector_config = {
            k: v
            for k, v in configuration.items()
            if k not in ("connect", "composable", "agent.type")
        }
        self._last_status: dict[str, Any] = {}
        self._last_check = 0.0

    async def _watch_once(self) -> None:
        """Poll status; restart FAILED connector/tasks (the reference's
        embedded runtime restarts crashed tasks the same way). Best-effort:
        it runs on the record hot path, and a blip in the Connect cluster's
        REST endpoint must not fail records whose bridge write succeeded."""
        now = time.monotonic()
        if now - self._last_check < self.status_interval:
            return
        self._last_check = now
        try:
            await self._watch_unguarded()
        except Exception:  # noqa: BLE001 — health polling never fails records
            log.warning(
                "connector %s status poll failed", self.connector_name, exc_info=True
            )

    async def _watch_unguarded(self) -> None:
        doc = await self.rest.status(self.connector_name)
        self._last_status = doc
        if not doc:
            return
        if doc.get("connector", {}).get("state") == "FAILED":
            log.warning("connector %s FAILED; restarting", self.connector_name)
            await self.rest.restart(self.connector_name)
        for task in doc.get("tasks", []):
            if task.get("state") == "FAILED":
                log.warning(
                    "connector %s task %s FAILED; restarting",
                    self.connector_name,
                    task.get("id"),
                )
                await self.rest.restart(self.connector_name, int(task.get("id", 0)))

    def _info(self) -> dict[str, Any]:
        return {
            "connector": self.connector_name,
            "rest-url": self.rest.rest_url,
            "status": self._last_status,
        }

    async def _teardown(self) -> None:
        if self.delete_on_close:
            try:
                await self.rest.delete(self.connector_name)
            except ConnectRestError:
                log.warning("connector %s delete failed", self.connector_name)
        await self.rest.close()


class KafkaConnectSinkAgent(AgentSink, _ConnectAgentBase):
    """type: sink — bridge pipeline records into the connector's topic on
    the app's streaming cluster and manage the connector remotely."""

    def component_type(self) -> ComponentType:
        return ComponentType.SINK

    async def init(self, configuration: dict[str, Any]) -> None:
        self._parse(configuration)
        self.bridge_topic = (
            configuration.get("topics") or f"ls-connect-{self.agent_id or 'sink'}"
        )
        self.connector_config.setdefault("topics", self.bridge_topic)
        self._producer = None

    async def start(self) -> None:
        await self.rest.info()  # fail fast when the cluster is unreachable
        await self.rest.put_config(self.connector_name, self.connector_config)
        assert self.context is not None
        admin = self.context.get_topic_admin()
        if not await admin.topic_exists(self.bridge_topic):
            await admin.create_topic(self.bridge_topic)
        self._producer = self.context.get_topic_producer(self.bridge_topic)
        await self._producer.start()
        await self._watch_once()

    async def write(self, record: Record) -> None:
        assert self._producer is not None, "agent not started"
        await self._producer.write(record)
        await self._watch_once()

    async def close(self) -> None:
        if self._producer is not None:
            await self._producer.close()
            self._producer = None
        await self._teardown()

    def agent_info(self) -> dict[str, Any]:
        return {**super().agent_info(), **self._info(), "bridge-topic": self.bridge_topic}


class KafkaConnectSourceAgent(AgentSource, _ConnectAgentBase):
    """type: source — the connector produces into the bridge topic; the
    agent consumes it into the pipeline (at-least-once via commit)."""

    def component_type(self) -> ComponentType:
        return ComponentType.SOURCE

    async def init(self, configuration: dict[str, Any]) -> None:
        self._parse(configuration)
        self.bridge_topic = (
            configuration.get("topic")
            or configuration.get("kafka.topic")
            or f"ls-connect-{self.agent_id or 'source'}"
        )
        # the common config keys source connectors use for their target
        self.connector_config.setdefault("topic", self.bridge_topic)
        self.connector_config.setdefault("kafka.topic", self.bridge_topic)
        self._consumer = None

    async def start(self) -> None:
        await self.rest.info()
        assert self.context is not None
        admin = self.context.get_topic_admin()
        if not await admin.topic_exists(self.bridge_topic):
            await admin.create_topic(self.bridge_topic)
        await self.rest.put_config(self.connector_name, self.connector_config)
        self._consumer = self.context.get_topic_consumer(self.bridge_topic)
        await self._consumer.start()
        await self._watch_once()

    async def read(self) -> list[Record]:
        assert self._consumer is not None, "agent not started"
        records = await self._consumer.read()
        await self._watch_once()
        return records

    async def commit(self, records: list[Record]) -> None:
        assert self._consumer is not None
        await self._consumer.commit(records)

    async def close(self) -> None:
        if self._consumer is not None:
            await self._consumer.close()
            self._consumer = None
        await self._teardown()

    def agent_info(self) -> dict[str, Any]:
        return {**super().agent_info(), **self._info(), "bridge-topic": self.bridge_topic}


class CamelSourceAgent(AgentSource):
    """type: camel-source — NATIVE interpreters for the common Camel
    endpoint URI schemes (reference CamelSource.java:172-174 config
    surface: component-uri, max-buffered-records, key-header):

    - ``timer:name?period=N[&repeatCount=K]`` — periodic tick records
    - ``cron:name?schedule=<quartz expr>`` — Quartz-scheduled ticks
      (camel-cron; ``+`` separators decoded, 5/6/7-field accepted)
    - ``file:/dir[?delete=true]`` — poll a directory, one record per file
    - ``http(s)://url?delay=N`` — poll an HTTP endpoint, one record per
      response body
    - ``exec:command?args=...&delay=N`` — run a local command per poll,
      one record per stdout (camel-exec consumer)
    - ``rss:URL`` / ``atom:URL?delay=N`` — poll a feed, one record per
      NEW entry (split + dedupe — camel-rss/atom defaults)

    Anything else (kafka:, jms:, aws-sqs:, the ~300 JVM components) gates
    with an explicit message — interpreting Camel's component registry
    without a JVM is not honest to fake."""

    def component_type(self) -> ComponentType:
        return ComponentType.SOURCE

    async def init(self, configuration: dict[str, Any]) -> None:
        import urllib.parse

        uri = str(configuration.get("component-uri", ""))
        self.key_header = configuration.get("key-header") or ""
        self.max_buffered = int(configuration.get("max-buffered-records", 100))
        scheme, _, rest = uri.partition(":")
        self.scheme = scheme
        path, _, query = rest.partition("?")
        self.path = path.lstrip("/") if scheme == "timer" else path
        self.params = dict(urllib.parse.parse_qsl(query))
        if scheme == "timer":
            self.period = float(self.params.get("period", 1000)) / 1000.0
            self.repeat = int(self.params.get("repeatCount", 0))  # 0 = forever
            self._ticks = 0
        elif scheme == "file":
            self.delete = str(self.params.get("delete", "")).lower() == "true"
            self._seen: set = set()
        elif scheme in ("http", "https"):
            import urllib.parse as _up

            self.delay = float(self.params.get("delay", 1000)) / 1000.0
            # strip ONLY the camel-level delay param; everything else
            # (tokens, filters) belongs to the polled endpoint
            base, _, query = uri.partition("?")
            keep = [(k, v) for k, v in _up.parse_qsl(query) if k != "delay"]
            self.url = base + ("?" + _up.urlencode(keep) if keep else "")
            self._http = None
        elif scheme == "cron":
            self.path = path.lstrip("/")
            # camel encodes spaces in schedule= as '+'; parse_qsl already
            # decoded them
            schedule = self.params.get("schedule", "* * * * * ?")
            self.cron_fields = _cron_parse(schedule)
            self._ticks = 0
            self._checked_sec = int(time.time())  # fire on FUTURE matches
        elif scheme == "exec":
            import shlex as _shlex

            self.delay = float(self.params.get("delay", 1000)) / 1000.0
            self.exec_cmd = [path, *_shlex.split(self.params.get("args", ""))]
        elif scheme in ("rss", "atom"):
            import urllib.parse as _up

            self.delay = float(self.params.get("delay", 1000)) / 1000.0
            # the URI after the scheme IS the feed URL; strip camel-level
            # params, keep the feed's own query
            _camel = {"delay", "initialDelay", "splitEntries", "filter",
                      "sortEntries", "throttleEntries", "feedHeader",
                      "lastUpdate"}
            feed = rest
            base, _, query = feed.partition("?")
            keep = [(k, v) for k, v in _up.parse_qsl(query) if k not in _camel]
            self.url = base + ("?" + _up.urlencode(keep) if keep else "")
            self._http = None
            # insertion-ordered so the dedupe memory can rotate (see read)
            from collections import OrderedDict

            self._seen_entries: "OrderedDict[str, None]" = OrderedDict()
        else:
            raise NotImplementedError(
                f"camel component {scheme!r} needs the JVM Camel runtime; "
                "native schemes: timer:, cron:, file:, http(s):, exec:, "
                "rss:, atom:  — " + _CAMEL_GATE
            )
        self._last = 0.0
        # file scheme: records delivered but not yet committed → their
        # source paths; deletion happens in commit() (at-least-once)
        self._pending_delete: dict[str, str] = {}

    async def _throttled(self, now: float) -> bool:
        """Shared poll throttle (http/exec/rss): True = not yet time."""
        import asyncio as _asyncio

        wait = self.delay - (now - self._last)
        if wait > 0:
            await _asyncio.sleep(min(wait, 0.5))
            if self.delay - (time.monotonic() - self._last) > 0:
                return True
        self._last = time.monotonic()
        return False

    async def _fetch_url(self) -> Optional[str]:
        """Shared GET for the http/rss/atom pollers: response body, or
        None on transport/HTTP errors (logged; retried next poll)."""
        import aiohttp

        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession()
        try:
            async with self._http.get(self.url) as resp:
                if resp.status >= 300:
                    log.warning(
                        "camel %s poll %s -> HTTP %d; retrying next poll",
                        self.scheme, self.url, resp.status,
                    )
                    return None
                return await resp.text()
        except aiohttp.ClientError as e:
            log.warning(
                "camel %s poll %s failed (%s); retrying", self.scheme, self.url, e
            )
            return None

    def _rec(self, value, natural_key):
        """Build a record honoring key-header: the reference takes the
        record key from the named exchange header — natively, the natural
        key rides both as the key and under that header name."""
        from langstream_tpu.api.record import SimpleRecord

        headers = (
            ((self.key_header, natural_key),)
            if self.key_header and natural_key is not None
            else None
        )
        return SimpleRecord.of(value, key=natural_key, headers=headers)

    async def read(self) -> list[Record]:
        import asyncio as _asyncio

        now = time.monotonic()
        if self.scheme == "timer":
            if self.repeat and self._ticks >= self.repeat:
                await _asyncio.sleep(0.05)
                return []
            wait = self.period - (now - self._last)
            if wait > 0:
                await _asyncio.sleep(min(wait, 0.5))
                if self.period - (time.monotonic() - self._last) > 0:
                    return []
            self._last = time.monotonic()
            self._ticks += 1
            return [self._rec(
                json.dumps({"timer": self.path, "count": self._ticks}),
                self.path,
            )]
        if self.scheme == "cron":
            await _asyncio.sleep(0.1)
            sec = int(time.time())
            if sec == self._checked_sec:
                return []
            # catch-up scan: a stall (>1s between reads — slow downstream,
            # busy loop) must not silently skip a scheduled second (a lost
            # daily tick). Bounded to the last 5 minutes.
            start = max(self._checked_sec + 1, sec - 300)
            self._checked_sec = sec
            out = []
            for s in range(start, sec + 1):
                if not _cron_due(self.cron_fields, time.localtime(s)):
                    continue
                self._ticks += 1
                out.append(self._rec(
                    json.dumps({"cron": self.path, "count": self._ticks,
                                "timestamp": s}),
                    self.path,
                ))
                if len(out) >= self.max_buffered:
                    # rewind the cursor to the last second actually SCANNED:
                    # marking all of (s, sec] checked would silently drop any
                    # due seconds between the buffer-full break and now
                    self._checked_sec = s
                    break
            return out
        if self.scheme == "exec":
            if await self._throttled(now):
                return []
            proc = await _asyncio.create_subprocess_exec(
                *self.exec_cmd,
                stdout=_asyncio.subprocess.PIPE,
                stderr=_asyncio.subprocess.PIPE,
            )
            stdout, stderr = await proc.communicate()
            if proc.returncode != 0:
                log.warning(
                    "camel exec %s exited %d: %s; retrying next poll",
                    self.exec_cmd[0], proc.returncode,
                    stderr.decode(errors="replace")[:200],
                )
                return []
            return [self._rec(stdout, None)]
        if self.scheme in ("rss", "atom"):
            if await self._throttled(now):
                return []
            body = await self._fetch_url()
            if body is None:
                return []
            out = []
            for entry in _parse_feed_entries(body):
                if entry["id"] in self._seen_entries:
                    # refresh recency so rotation evicts truly-gone ids
                    self._seen_entries.move_to_end(entry["id"])
                    continue
                self._seen_entries[entry["id"]] = None
                out.append(self._rec(json.dumps(entry), entry["id"]))
                if len(out) >= self.max_buffered:
                    break
            # bound the dedupe memory for immortal high-churn feeds: ids
            # not seen in the last 10k entries may re-emit (at-least-once)
            while len(self._seen_entries) > 10_000:
                self._seen_entries.popitem(last=False)
            return out
        if self.scheme == "file":
            import pathlib

            out = []
            directory = pathlib.Path(self.path)
            if directory.is_dir():
                live = {str(f) for f in directory.iterdir()}
                self._seen &= live  # rotated-away files never accumulate
                for f in sorted(directory.iterdir()):
                    if f.is_file() and str(f) not in self._seen:
                        out.append(self._rec(f.read_bytes(), f.name))
                        self._seen.add(str(f))
                        if self.delete:
                            self._pending_delete[f.name] = str(f)
                        if len(out) >= self.max_buffered:
                            break
            if not out:
                await _asyncio.sleep(0.05)
            return out
        # http(s) poller
        if await self._throttled(now):
            return []
        body = await self._fetch_url()
        return [] if body is None else [self._rec(body, None)]

    async def commit(self, records: list[Record]) -> None:
        """file scheme's delete=true happens HERE — after every downstream
        write landed — so a crash mid-pipeline never loses the file."""
        import pathlib

        for r in records:
            path = self._pending_delete.pop(str(r.key), None)
            if path is not None:
                pathlib.Path(path).unlink(missing_ok=True)

    async def close(self) -> None:
        http = getattr(self, "_http", None)
        if http is not None and not http.closed:
            await http.close()

    def agent_info(self) -> dict[str, Any]:
        return {**super().agent_info(), "component-uri": f"{self.scheme}:..."}


def _register() -> None:
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="sink",
            component_type=ComponentType.SINK,
            factory=KafkaConnectSinkAgent,
            description=(
                "Stock Kafka Connect sink connector, managed on an external "
                "Connect cluster over its REST API."
            ),
            config_model=ConfigModel(
                type="sink",
                allow_unknown=True,
                properties={
                    "connector.class": ConfigProperty(
                        "connector.class", "Connect connector class", required=True
                    ),
                    "connect": ConfigProperty(
                        "connect",
                        "External cluster: rest-url, name, delete-on-close",
                        type="object",
                    ),
                },
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="source",
            component_type=ComponentType.SOURCE,
            factory=KafkaConnectSourceAgent,
            description=(
                "Stock Kafka Connect source connector, managed on an external "
                "Connect cluster over its REST API."
            ),
            config_model=ConfigModel(
                type="source",
                allow_unknown=True,
                properties={
                    "connector.class": ConfigProperty(
                        "connector.class", "Connect connector class", required=True
                    ),
                    "connect": ConfigProperty(
                        "connect",
                        "External cluster: rest-url, name, delete-on-close",
                        type="object",
                    ),
                },
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="camel-source",
            component_type=ComponentType.SOURCE,
            factory=CamelSourceAgent,
            description=(
                "Camel endpoint URI as a source: timer:/file:/http(s): "
                "interpreted natively; JVM-only components gate."
            ),
            config_model=ConfigModel(
                type="camel-source",
                allow_unknown=True,
                properties={
                    "component-uri": ConfigProperty(
                        "component-uri", "Camel endpoint URI", required=True
                    )
                },
            ),
        )
    )


_register()
