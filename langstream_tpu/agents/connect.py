"""Connector adapter agent types (gated).

Parity: reference ``kafkaconnect/KafkaConnectSinkAgent.java`` /
``KafkaConnectSourceAgent.java`` (types ``sink`` / ``source`` — run stock
Kafka Connect connectors as agents) and ``CamelSource.java``
(``camel-source`` — any Apache Camel endpoint as a source).

Both depend on JVM connector runtimes the image does not ship; the planner
accepts and validates these types (so apps referencing them parse, plan, and
document — the reference's planner-metadata layer), but starting one raises
with an explicit gating message, matching the kafka/pulsar broker-runtime
pattern.
"""

from __future__ import annotations

from typing import Any

from langstream_tpu.api.agent import AgentSink, AgentSource, ComponentType
from langstream_tpu.api.doc import ConfigModel, ConfigProperty
from langstream_tpu.api.record import Record
from langstream_tpu.core.registry import REGISTRY, AgentTypeInfo

_GATE_MESSAGE = (
    "{kind} adapters embed a JVM connector runtime that this image does not "
    "ship; run the connector natively against the broker, or use a built-in "
    "agent type"
)


class KafkaConnectSinkAgent(AgentSink):
    async def init(self, configuration: dict[str, Any]) -> None:
        raise NotImplementedError(_GATE_MESSAGE.format(kind="Kafka Connect sink"))

    async def write(self, record: Record) -> None:  # pragma: no cover
        raise NotImplementedError


class KafkaConnectSourceAgent(AgentSource):
    async def init(self, configuration: dict[str, Any]) -> None:
        raise NotImplementedError(_GATE_MESSAGE.format(kind="Kafka Connect source"))

    async def read(self) -> list[Record]:  # pragma: no cover
        raise NotImplementedError


class CamelSourceAgent(AgentSource):
    async def init(self, configuration: dict[str, Any]) -> None:
        raise NotImplementedError(_GATE_MESSAGE.format(kind="Apache Camel source"))

    async def read(self) -> list[Record]:  # pragma: no cover
        raise NotImplementedError


def _register() -> None:
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="sink",
            component_type=ComponentType.SINK,
            factory=KafkaConnectSinkAgent,
            description="Stock Kafka Connect sink connector (gated: JVM runtime).",
            config_model=ConfigModel(
                type="sink",
                allow_unknown=True,
                properties={
                    "connector.class": ConfigProperty(
                        "connector.class", "Connect connector class", required=True
                    )
                },
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="source",
            component_type=ComponentType.SOURCE,
            factory=KafkaConnectSourceAgent,
            description="Stock Kafka Connect source connector (gated: JVM runtime).",
            config_model=ConfigModel(
                type="source",
                allow_unknown=True,
                properties={
                    "connector.class": ConfigProperty(
                        "connector.class", "Connect connector class", required=True
                    )
                },
            ),
        )
    )
    REGISTRY.register_agent(
        AgentTypeInfo(
            type="camel-source",
            component_type=ComponentType.SOURCE,
            factory=CamelSourceAgent,
            description="Apache Camel endpoint as a source (gated: JVM runtime).",
            config_model=ConfigModel(
                type="camel-source",
                allow_unknown=True,
                properties={
                    "component-uri": ConfigProperty(
                        "component-uri", "Camel endpoint URI", required=True
                    )
                },
            ),
        )
    )


_register()
