"""Lightweight distributed tracing + JAX profiler hooks.

The reference has NO tracing (SURVEY §5: "no OpenTelemetry; observability =
prometheus + logs"); this is one of the rebuild's additions. Spans are
in-process (contextvars parent propagation, ring-buffered), exported over
the runtime HTTP server (``/traces``) in a jaeger-ish JSON shape, and
propagated ACROSS agents through a record header (``ls-trace-id``) so a
record's path through a pipeline stitches into one trace.

``device_trace`` wraps ``jax.profiler`` (xprof) for TPU-side profiling —
point TensorBoard at the output dir.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

TRACE_HEADER = "ls-trace-id"

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "ls_current_span", default=None
)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_s: float
    duration_s: float = 0.0
    attributes: dict[str, Any] = field(default_factory=dict)
    status: str = "ok"

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "start": self.start_s,
            "durationMs": round(self.duration_s * 1000.0, 3),
            "attributes": self.attributes,
            "status": self.status,
        }


class Tracer:
    """Per-process tracer; finished spans land in a bounded ring buffer."""

    def __init__(self, capacity: int = 2048) -> None:
        self._finished: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.enabled = True

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        trace_id: Optional[str] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        if not self.enabled:
            yield Span(name, "", "", None, 0.0)
            return
        parent = _current_span.get()
        span = Span(
            name=name,
            trace_id=trace_id
            or (parent.trace_id if parent is not None else uuid.uuid4().hex[:16]),
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id if parent is not None else None,
            start_s=time.time(),
            attributes=dict(attributes),
        )
        token = _current_span.set(span)
        started = time.monotonic()
        try:
            yield span
        except BaseException as e:
            span.status = f"error: {type(e).__name__}"
            raise
        finally:
            span.duration_s = time.monotonic() - started
            _current_span.reset(token)
            with self._lock:
                self._finished.append(span)

    def emit(self, span: Span) -> None:
        """Append an already-finished span built by hand — the serving
        engine's request-lifecycle spans are assembled from phase
        timestamps at request completion (one emission point, nothing on
        the token hot loop) rather than held open across engine-thread
        iterations, so the context-manager form cannot carry them."""
        if not self.enabled:
            return
        with self._lock:
            self._finished.append(span)

    def current_trace_id(self) -> Optional[str]:
        span = _current_span.get()
        return span.trace_id if span is not None else None

    def find(self, name: str, trace_id: Optional[str] = None) -> list[Span]:
        """Finished spans by name (and optionally trace) — tests/debugging."""
        with self._lock:
            items = list(self._finished)
        return [
            s
            for s in items
            if s.name == name and (trace_id is None or s.trace_id == trace_id)
        ]

    def spans(self, limit: int = 500) -> list[dict[str, Any]]:
        with self._lock:
            items = list(self._finished)[-limit:]
        return [s.to_dict() for s in items]

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


TRACER = Tracer()


def record_trace_id(record: Any) -> Optional[str]:
    """Extract the propagated trace id from a record's headers."""
    headers = getattr(record, "headers", ())
    for h in headers:
        if h.key == TRACE_HEADER:
            return h.value_as_string()
    return None


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """TPU-side profiling via jax.profiler (xprof); view with TensorBoard."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
